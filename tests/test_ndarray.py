"""NDArray basics (mirrors tests/python/unittest/test_ndarray.py core cases)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_creation():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == onp.float32
    assert a.size == 4
    b = nd.zeros((3, 4))
    assert (b.asnumpy() == 0).all()
    c = nd.ones((2, 3), dtype="int32")
    assert c.dtype == onp.int32
    d = nd.full((2, 2), 7.0)
    assert (d.asnumpy() == 7).all()
    e = nd.arange(0, 10, 2)
    assert_almost_equal(e, onp.arange(0, 10, 2, dtype=onp.float32))


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[5.0, 6.0], [7.0, 8.0]])
    assert_almost_equal(a + b, onp.array([[6, 8], [10, 12]]))
    assert_almost_equal(a - b, onp.array([[-4, -4], [-4, -4]]))
    assert_almost_equal(a * b, onp.array([[5, 12], [21, 32]]))
    assert_almost_equal(b / a, onp.array([[5, 3], [7 / 3, 2]]))
    assert_almost_equal(a + 1, onp.array([[2, 3], [4, 5]]))
    assert_almost_equal(1 - a, onp.array([[0, -1], [-2, -3]]))
    assert_almost_equal(2 * a, onp.array([[2, 4], [6, 8]]))
    assert_almost_equal(a ** 2, onp.array([[1, 4], [9, 16]]))
    assert_almost_equal(-a, -a.asnumpy())
    assert_almost_equal(abs(nd.array([-1.0, 2.0])), onp.array([1, 2]))


def test_inplace():
    a = nd.ones((2, 2))
    a += 1
    assert (a.asnumpy() == 2).all()
    a *= 3
    assert (a.asnumpy() == 6).all()
    a /= 2
    assert (a.asnumpy() == 3).all()


def test_comparisons():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert_almost_equal(a > b, onp.array([0, 0, 1]))
    assert_almost_equal(a >= b, onp.array([0, 1, 1]))
    assert_almost_equal(a == b, onp.array([0, 1, 0]))
    assert_almost_equal(a != b, onp.array([1, 0, 1]))


def test_indexing():
    a = nd.array(onp.arange(24).reshape(2, 3, 4))
    assert a[0].shape == (3, 4)
    assert a[0, 1].shape == (4,)
    assert float(a[1, 2, 3].asscalar()) == 23.0
    assert a[:, 1:3].shape == (2, 2, 4)
    sliced = a[0, :, ::2]
    assert sliced.shape == (3, 2)
    b = nd.zeros((3, 3))
    b[1, 1] = 5.0
    assert float(b[1, 1].asscalar()) == 5.0
    b[...] = 2.0
    assert (b.asnumpy() == 2).all()
    # advanced indexing
    idx = nd.array([0, 1], dtype="int32")
    got = a[idx]
    assert got.shape == (2, 3, 4)


def test_reshape_transpose():
    a = nd.array(onp.arange(12).reshape(3, 4))
    assert a.reshape(4, 3).shape == (4, 3)
    assert a.reshape((2, 6)).shape == (2, 6)
    assert a.reshape(-1).shape == (12,)
    assert a.T.shape == (4, 3)
    assert a.transpose().shape == (4, 3)
    b = nd.zeros((2, 3, 4))
    assert b.transpose(2, 0, 1).shape == (4, 2, 3)
    assert b.swapaxes(0, 2).shape == (4, 3, 2)
    assert b.flatten().shape == (2, 12)
    assert b.expand_dims(0).shape == (1, 2, 3, 4)
    # reference reshape special codes
    c = nd.zeros((2, 3, 4))
    assert c.reshape(0, -1).shape == (2, 12)
    assert nd.reshape(c, shape=(-2,)).shape == (2, 3, 4)
    assert nd.reshape(c, shape=(-3, 4)).shape == (6, 4)


def test_reductions():
    a = nd.array(onp.arange(12, dtype=onp.float32).reshape(3, 4))
    assert float(a.sum().asscalar()) == 66
    assert_almost_equal(a.sum(axis=0), a.asnumpy().sum(0))
    assert_almost_equal(a.mean(axis=1), a.asnumpy().mean(1))
    assert_almost_equal(a.max(axis=0), a.asnumpy().max(0))
    assert_almost_equal(a.min(axis=1), a.asnumpy().min(1))
    assert float(a.argmax().asscalar()) == 11
    assert_almost_equal(a.argmax(axis=1), a.asnumpy().argmax(1).astype("f"))
    assert_almost_equal(nd.sum(a, axis=0, exclude=True), a.asnumpy().sum(1))
    n = a.norm()
    assert_almost_equal(n, onp.linalg.norm(a.asnumpy()), rtol=1e-4)


def test_dot():
    a = nd.array(onp.random.rand(3, 4).astype("f"))
    b = nd.array(onp.random.rand(4, 5).astype("f"))
    assert_almost_equal(nd.dot(a, b), a.asnumpy().dot(b.asnumpy()), rtol=1e-4)
    c = nd.array(onp.random.rand(2, 3, 4).astype("f"))
    d = nd.array(onp.random.rand(2, 4, 5).astype("f"))
    assert_almost_equal(nd.batch_dot(c, d),
                        onp.matmul(c.asnumpy(), d.asnumpy()), rtol=1e-4)
    assert_almost_equal(nd.dot(a, b, transpose_b=False),
                        a.asnumpy() @ b.asnumpy(), rtol=1e-4)


def test_astype_copy():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == onp.int32
    c = a.astype("bfloat16")
    assert str(c.dtype) == "bfloat16"
    d = a.copy()
    d += 1
    assert float(a[0].asscalar()) == 1.5


def test_wait_and_context():
    a = nd.ones((4, 4))
    a.wait_to_read()
    assert a.context == mx.cpu()
    b = a.as_in_context(mx.cpu(0))
    assert b is a
    nd.waitall()


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    c2 = nd.concat([a, b], dim=1)
    assert c2.shape == (2, 6)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(c, 2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.params")
    a = nd.array([[1, 2], [3, 4]])
    b = nd.ones((3,), dtype="int32")
    nd.save(fname, {"a": a, "b": b})
    loaded = nd.load(fname)
    assert set(loaded) == {"a", "b"}
    assert_almost_equal(loaded["a"], a.asnumpy())
    assert loaded["b"].dtype == onp.int32
    # list form
    nd.save(fname, [a, b])
    out = nd.load(fname)
    assert isinstance(out, list) and len(out) == 2
    # bf16 roundtrip
    c = a.astype("bfloat16")
    nd.save(fname, {"c": c})
    back = nd.load(fname)["c"]
    assert str(back.dtype) == "bfloat16"


def test_take_pick_gather():
    a = nd.array(onp.arange(12, dtype="f").reshape(3, 4))
    idx = nd.array([0, 2], dtype="int32")
    assert_almost_equal(nd.take(a, idx, axis=0), a.asnumpy()[[0, 2]])
    p = nd.pick(a, nd.array([0, 1, 2]), axis=1)
    assert_almost_equal(p, onp.array([0, 5, 10]))
    g = nd.gather_nd(a, nd.array([[0, 1], [1, 2]], dtype="int32"))
    assert_almost_equal(g, onp.array([a.asnumpy()[0, 1], a.asnumpy()[1, 2]]))


def test_one_hot_where_clip():
    oh = nd.one_hot(nd.array([0, 2], dtype="int32"), depth=3)
    assert_almost_equal(oh, onp.array([[1, 0, 0], [0, 0, 1]], dtype="f"))
    w = nd.where(nd.array([1.0, 0.0]), nd.array([1.0, 2.0]), nd.array([3.0, 4.0]))
    assert_almost_equal(w, onp.array([1, 4]))
    c = nd.clip(nd.array([-2.0, 0.5, 9.0]), a_min=0.0, a_max=1.0)
    assert_almost_equal(c, onp.array([0, 0.5, 1]))


def test_topk_sort():
    a = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    v = nd.topk(a, k=2, ret_typ="value")
    assert_almost_equal(v, onp.array([[3, 2], [5, 4]]))
    s = nd.sort(a, axis=1)
    assert_almost_equal(s, onp.sort(a.asnumpy(), axis=1))
    idx = nd.argsort(a, axis=1)
    assert_almost_equal(idx, onp.argsort(a.asnumpy(), 1).astype("f"))


def test_sequence_ops():
    data = nd.array(onp.arange(24, dtype="f").reshape(4, 2, 3))  # (seq, batch, c)
    length = nd.array([2, 3])
    masked = nd.SequenceMask(data, length, use_sequence_length=True, value=-1.0)
    np_d = data.asnumpy().copy()
    np_d[2:, 0] = -1
    np_d[3:, 1] = -1
    assert_almost_equal(masked, np_d)
    last = nd.SequenceLast(data, length, use_sequence_length=True)
    assert_almost_equal(last, onp.stack([data.asnumpy()[1, 0],
                                         data.asnumpy()[2, 1]]))


def test_random_ops():
    mx.random.seed(7)
    u = nd.random.uniform(0, 1, shape=(100,))
    assert 0 <= float(u.min().asscalar()) and float(u.max().asscalar()) <= 1
    n1 = nd.random.normal(0, 1, shape=(50,))
    mx.random.seed(7)
    u2 = nd.random.uniform(0, 1, shape=(100,))
    assert_almost_equal(u, u2)  # seeding reproducible
    r = nd.random.randint(0, 10, shape=(20,))
    assert r.dtype == onp.int32
    m = nd.random.multinomial(nd.array([[0.0, 1.0], [1.0, 0.0]]))
    assert_almost_equal(m, onp.array([1, 0]))


def test_numpy_interop():
    a = nd.array([[1.0, 2.0]])
    np_view = onp.asarray(a)
    assert np_view.shape == (1, 2)
    b = a + onp.array([[1.0, 1.0]])
    assert_almost_equal(b, onp.array([[2, 3]]))


def test_mx_random_module_samplers():
    """mx.random re-exports the nd samplers (python/mxnet/random.py parity)."""
    import mxnet_tpu as mx
    mx.random.seed(7)
    a = mx.random.normal(shape=(4,)).asnumpy()
    mx.random.seed(7)
    b = mx.random.normal(shape=(4,)).asnumpy()
    onp.testing.assert_array_equal(a, b)
    u = mx.random.uniform(low=-1, high=1, shape=(8,)).asnumpy()
    assert ((u >= -1) & (u <= 1)).all()
    with pytest.raises(AttributeError):
        mx.random.not_a_sampler
