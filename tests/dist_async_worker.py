"""Worker body for the 4-process dist_async test (VERDICT r3 #7).

Distinguishes true async-apply from sync semantics: ranks 0-2 push
immediately; rank 3 sleeps first. Under async, the fast workers' updates are
visible in a pull BEFORE the laggard has pushed anything (a sync allreduce
would block until all four contribute). After everyone finishes, the weight
reflects every push applied per-arrival (SGD with lr 0.1 is additive, so the
final value is order-independent: init - 0.1 * sum(all grads))."""
import json
import os
import sys
import time

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd

LAG = 3.0
SHAPE = (2, 4)


def main():
    kv = mx.kv.create("dist_async")
    rank, size = kv.rank, kv.num_workers
    assert size == 4, f"expected 4 workers, got {size}"
    assert kv.type == "dist_async"

    def updater(key, grad, weight):
        weight -= 0.1 * grad

    kv.set_updater(updater)
    kv.init("w", nd.zeros(SHAPE))

    outdir = os.environ["ASYNC_TEST_DIR"]
    if rank == 3:
        time.sleep(LAG)
        t_before_push = time.time()
        kv.push("w", nd.ones(SHAPE))
        record = {"rank": rank, "pushed_at": t_before_push}
    else:
        kv.push("w", nd.ones(SHAPE) * (rank + 1))
        out = nd.zeros(SHAPE)
        kv.pull("w", out=out)
        t_seen = time.time()
        seen = float(out.asnumpy()[0, 0])
        # async: our own push (and possibly peers') already applied while the
        # laggard is still asleep — the weight moved without rank 3
        record = {"rank": rank, "seen_nonzero_at": t_seen, "seen": seen}
        assert seen < 0.0, f"rank {rank}: no update applied before laggard ({seen})"

    with open(os.path.join(outdir, f"r{rank}.json"), "w") as f:
        json.dump(record, f)

    # converge: wait for all pushes (1+2+3+1 = 7 -> final = -0.7)
    deadline = time.time() + 60
    out = nd.zeros(SHAPE)
    while time.time() < deadline:
        kv.pull("w", out=out)
        if abs(float(out.asnumpy()[0, 0]) + 0.7) < 1e-5:
            break
        time.sleep(0.1)
    onp.testing.assert_allclose(out.asnumpy(), onp.full(SHAPE, -0.7),
                                rtol=1e-5)
    print(f"worker {rank}/4: ASYNC OK", flush=True)


if __name__ == "__main__":
    main()
