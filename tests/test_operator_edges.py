"""Operator edge-case matrix (VERDICT r4 #2).

The breadth suites probe each op a few times at friendly shapes; this tier
ports the reference's edge-case discipline (tests/python/unittest/
test_operator.py:1, test_numpy_op.py — zero-size shapes, negative/None
axes, dtype sweeps incl. bf16/fp16/int8, broadcasting corners, and
kAddTo/grad_req='add' accumulation) across every §2.2 family. Oracles are
numpy computed in f32 with dtype-scaled tolerances (the reference's
check_consistency pattern, test_utils.py:1428).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd

import ml_dtypes  # ships with jax

_BF16 = onp.dtype(ml_dtypes.bfloat16)

TOL = {"float32": (1e-5, 1e-6), "bfloat16": (3e-2, 3e-2),
       "float16": (2e-3, 2e-3)}


def _to(dtype, a):
    if dtype == "bfloat16":
        return a.astype(_BF16)
    return a.astype(dtype)


def _f32(a):
    return onp.asarray(a, dtype=onp.float32)


def _mk(shape, dtype, seed=0, lo=0.25, hi=2.0):
    """Positive-range input: keeps log/sqrt/rsqrt/gamma oracles defined."""
    a = onp.random.RandomState(seed).uniform(lo, hi, size=shape)
    return _to(dtype, a.astype("float32"))


# ---------------------------------------------------------------------------
# 1. unary elementwise: dtype sweep x zero-size + degenerate + broadcastable
# ---------------------------------------------------------------------------
UNARY = {
    "relu": lambda x: onp.maximum(x, 0),
    "sigmoid": lambda x: 1 / (1 + onp.exp(-x)),
    "softsign": lambda x: x / (1 + onp.abs(x)),
    "exp": onp.exp,
    "expm1": onp.expm1,
    "log": onp.log,
    "log1p": onp.log1p,
    "log2": onp.log2,
    "log10": onp.log10,
    "sqrt": onp.sqrt,
    "rsqrt": lambda x: 1 / onp.sqrt(x),
    "cbrt": onp.cbrt,
    "square": onp.square,
    "reciprocal": lambda x: 1 / x,
    "negative": onp.negative,
    "abs": onp.abs,
    "sign": onp.sign,
    "floor": onp.floor,
    "ceil": onp.ceil,
    "trunc": onp.trunc,
    "rint": onp.rint,
    "sin": onp.sin,
    "cos": onp.cos,
    "tan": onp.tan,
    "arcsin": lambda x: onp.arcsin(x / 3),
    "arccos": lambda x: onp.arccos(x / 3),
    "arctan": onp.arctan,
    "sinh": onp.sinh,
    "cosh": onp.cosh,
    "tanh": onp.tanh,
    "arcsinh": onp.arcsinh,
    "arctanh": lambda x: onp.arctanh(x / 3),
    "erf": None,   # scipy-free: checked for shape/dtype only
    "gammaln": None,
    "gelu": None,
}


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "float16"])
@pytest.mark.parametrize("op_name", sorted(UNARY))
def test_unary_dtype_and_zero_size(op_name, dtype):
    fn = getattr(nd, op_name)
    oracle = UNARY[op_name]
    for shape in [(0,), (2, 0, 3), (3, 1, 2), (1,)]:
        x = _mk(shape, dtype, seed=hash(op_name) % 1000)
        if op_name in ("arcsin", "arccos", "arctanh"):
            x = _to(dtype, _f32(x) / 3)   # domain (-1, 1)
            oracle_in = _f32(x) * 3       # oracle fns divide again
        else:
            oracle_in = _f32(x)
        out = fn(nd.array(x))
        assert out.shape == shape, (op_name, dtype, shape, out.shape)
        assert str(out.dtype) == dtype, (op_name, dtype, out.dtype)
        if oracle is not None and 0 not in shape:
            rtol, atol = TOL[dtype]
            onp.testing.assert_allclose(_f32(out.asnumpy()),
                                        oracle(oracle_in), rtol=rtol,
                                        atol=atol, err_msg=op_name)


# ---------------------------------------------------------------------------
# 2. binary broadcasting corners
# ---------------------------------------------------------------------------
BINARY = {
    "broadcast_add": onp.add,
    "broadcast_sub": onp.subtract,
    "broadcast_mul": onp.multiply,
    "broadcast_div": onp.divide,
    "broadcast_maximum": onp.maximum,
    "broadcast_minimum": onp.minimum,
    "broadcast_power": onp.power,
    "broadcast_hypot": onp.hypot,
    "broadcast_equal": lambda a, b: (a == b).astype("float32"),
    "broadcast_not_equal": lambda a, b: (a != b).astype("float32"),
    "broadcast_greater": lambda a, b: (a > b).astype("float32"),
    "broadcast_lesser_equal": lambda a, b: (a <= b).astype("float32"),
}
SHAPE_PAIRS = [
    ((2, 1, 3), (1, 4, 1)),      # two-sided broadcast
    ((0, 3), (1, 3)),            # zero-size left
    ((4, 1), (1, 0)),            # zero-size from broadcast
    ((1,), (5,)),                # scalar-ish stretch
    ((2, 3), (2, 3)),            # no broadcast
]


@pytest.mark.parametrize("shapes", SHAPE_PAIRS,
                         ids=[f"{a}x{b}" for a, b in SHAPE_PAIRS])
@pytest.mark.parametrize("op_name", sorted(BINARY))
def test_binary_broadcast_corners(op_name, shapes):
    sa, sb = shapes
    a = _mk(sa, "float32", seed=1)
    b = _mk(sb, "float32", seed=2)
    out = getattr(nd, op_name)(nd.array(a), nd.array(b))
    want = BINARY[op_name](a, b)
    assert out.shape == want.shape, (op_name, out.shape, want.shape)
    if 0 not in want.shape:
        onp.testing.assert_allclose(out.asnumpy().astype("float32"), want,
                                    rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# 3. reductions: axis=None/0/-1/tuple, keepdims, zero-size axes
# ---------------------------------------------------------------------------
REDUCE = {
    "sum": onp.sum, "mean": onp.mean, "prod": onp.prod,
    "max": onp.max, "min": onp.min,
    "nansum": onp.nansum, "nanprod": onp.nanprod,
}
AXES = [None, 0, -1, (0, 2), 1]


@pytest.mark.parametrize("axis", AXES, ids=[str(a) for a in AXES])
@pytest.mark.parametrize("op_name", sorted(REDUCE))
@pytest.mark.parametrize("keepdims", [False, True])
def test_reduction_axes(op_name, axis, keepdims):
    x = _mk((2, 3, 4), "float32", seed=3, lo=-2.0)
    out = getattr(nd, op_name)(nd.array(x), axis=axis, keepdims=keepdims)
    want = REDUCE[op_name](x, axis=axis, keepdims=keepdims)
    want = onp.asarray(want, dtype="float32")
    assert out.shape == want.shape, (op_name, axis, keepdims, out.shape)
    onp.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("op_name", ["sum", "mean", "prod", "nansum"])
def test_reduction_over_zero_size_axis(op_name):
    """Reducing a zero-length axis: sum/nansum -> 0, prod -> 1, mean -> nan
    (numpy semantics; the reference's kZeroSize handling)."""
    x = onp.zeros((3, 0, 2), "float32")
    out = getattr(nd, op_name)(nd.array(x), axis=1)
    assert out.shape == (3, 2)
    got = out.asnumpy()
    if op_name in ("sum", "nansum"):
        onp.testing.assert_array_equal(got, onp.zeros((3, 2), "float32"))
    elif op_name == "prod":
        onp.testing.assert_array_equal(got, onp.ones((3, 2), "float32"))
    else:
        assert onp.isnan(got).all()


@pytest.mark.parametrize("op_name", ["argmax", "argmin"])
@pytest.mark.parametrize("axis", [0, 1, -1])
def test_arg_reductions(op_name, axis):
    x = onp.random.RandomState(4).randn(3, 4, 5).astype("float32")
    out = getattr(nd, op_name)(nd.array(x), axis=axis)
    want = getattr(onp, op_name)(x, axis=axis).astype("float32")
    onp.testing.assert_array_equal(out.asnumpy(), want)


# ---------------------------------------------------------------------------
# 4. negative-axis equivalence for shape/axis ops
# ---------------------------------------------------------------------------
def _neg_axis_cases():
    x3 = onp.random.RandomState(5).randn(2, 3, 4).astype("float32")
    return [
        ("concat", lambda ax: nd.concat(nd.array(x3), nd.array(x3), dim=ax),
         2),
        ("stack", lambda ax: nd.stack(nd.array(x3), nd.array(x3), axis=ax),
         2),
        ("softmax", lambda ax: nd.softmax(nd.array(x3), axis=ax), 1),
        ("log_softmax", lambda ax: nd.log_softmax(nd.array(x3), axis=ax), 1),
        ("expand_dims", lambda ax: nd.expand_dims(nd.array(x3), axis=ax), 1),
        ("reverse", lambda ax: nd.reverse(nd.array(x3), axis=ax), 2),
        ("repeat", lambda ax: nd.repeat(nd.array(x3), repeats=2, axis=ax), 0),
        ("cumsum", lambda ax: nd.cumsum(nd.array(x3), axis=ax), 1),
        ("take", lambda ax: nd.take(nd.array(x3), nd.array([1.0, 0.0]),
                                    axis=ax), 2),
        ("split", lambda ax: nd.split(nd.array(x3), num_outputs=2,
                                      axis=ax)[0], 2),
    ]


@pytest.mark.parametrize("case", _neg_axis_cases(),
                         ids=[c[0] for c in _neg_axis_cases()])
def test_negative_axis_equals_positive(case):
    name, fn, pos_ax = case
    # expand_dims/stack insert an axis, so negative axes index the OUTPUT
    # rank (4); everything else indexes the input rank (3)
    ndim = 4 if name in ("expand_dims", "stack") else 3
    neg_ax = pos_ax - ndim
    a = fn(pos_ax)
    b = fn(neg_ax)
    onp.testing.assert_allclose(a.asnumpy(), b.asnumpy(), rtol=1e-6,
                                err_msg=f"{name}: axis {pos_ax} vs {neg_ax}")


# ---------------------------------------------------------------------------
# 5. grad_req='add' (kAddTo) accumulation semantics
# ---------------------------------------------------------------------------
def _grad_add_cases():
    rs = onp.random.RandomState(6)
    x23 = rs.randn(2, 3).astype("float32")
    x_img = rs.randn(2, 3, 5, 5).astype("float32")
    w_fc = rs.randn(4, 3).astype("float32")
    w_cv = rs.randn(2, 3, 3, 3).astype("float32")
    idx = onp.array([1.0, 0.0, 1.0], "float32")
    table = rs.randn(4, 3).astype("float32")
    return [
        ("FullyConnected",
         x23, lambda x: nd.FullyConnected(x, nd.array(w_fc), None,
                                          num_hidden=4, no_bias=True)),
        ("Convolution",
         x_img, lambda x: nd.Convolution(x, nd.array(w_cv), None,
                                         kernel=(3, 3), num_filter=2,
                                         no_bias=True)),
        ("broadcast_mul",
         x23, lambda x: nd.broadcast_mul(x, nd.array(x23[:1]))),
        ("sum", x23, lambda x: nd.sum(x, axis=1)),
        ("softmax", x23, lambda x: nd.softmax(x, axis=-1)),
        ("dot", x23, lambda x: nd.dot(x, nd.array(w_fc.T))),
        ("Embedding",
         idx, lambda i: nd.Embedding(i, nd.array(table), input_dim=4,
                                     output_dim=3)),
        ("LayerNorm",
         x23, lambda x: nd.LayerNorm(x, nd.array(onp.ones(3, "float32")),
                                     nd.array(onp.zeros(3, "float32")))),
    ]


@pytest.mark.parametrize("case", _grad_add_cases(),
                         ids=[c[0] for c in _grad_add_cases()])
def test_grad_req_add_accumulates(case):
    """grad_req='add' must ACCUMULATE across backward passes where 'write'
    overwrites (imperative kAddTo semantics, imperative_utils.h:462)."""
    name, x_np, fn = case

    def one_backward(req):
        x = nd.array(x_np)
        x.attach_grad(grad_req=req)
        grads = []
        for _ in range(2):
            with autograd.record():
                y = fn(x)
            y.backward()
            grads.append(x.grad.asnumpy().copy())
        return grads

    w1, w2 = one_backward("write")
    onp.testing.assert_allclose(w1, w2, rtol=1e-5,
                                err_msg=f"{name}: write not idempotent")
    a1, a2 = one_backward("add")
    onp.testing.assert_allclose(a1, w1, rtol=1e-5)
    onp.testing.assert_allclose(a2, 2 * w1, rtol=1e-5, atol=1e-6,
                                err_msg=f"{name}: add did not accumulate")


# ---------------------------------------------------------------------------
# 6. zero-batch through nn ops
# ---------------------------------------------------------------------------
def test_zero_batch_fully_connected():
    w = onp.ones((4, 3), "float32")
    out = nd.FullyConnected(nd.zeros((0, 3)), nd.array(w), None,
                            num_hidden=4, no_bias=True)
    assert out.shape == (0, 4)


def test_zero_batch_convolution():
    w = onp.ones((2, 3, 3, 3), "float32")
    out = nd.Convolution(nd.zeros((0, 3, 8, 8)), nd.array(w), None,
                         kernel=(3, 3), num_filter=2, no_bias=True)
    assert out.shape == (0, 2, 6, 6)


def test_zero_batch_pooling():
    out = nd.Pooling(nd.zeros((0, 2, 4, 4)), kernel=(2, 2), pool_type="max",
                     stride=(2, 2))
    assert out.shape == (0, 2, 2, 2)


def test_zero_batch_batchnorm_eval():
    c = 3
    out, _, _ = nd.BatchNorm(
        nd.zeros((0, c, 2, 2)), nd.ones((c,)), nd.zeros((c,)),
        nd.zeros((c,)), nd.ones((c,)), fix_gamma=False, training=False,
        output_mean_var=True) if False else (
        nd.BatchNorm(nd.zeros((0, c, 2, 2)), nd.ones((c,)), nd.zeros((c,)),
                     nd.zeros((c,)), nd.ones((c,)), fix_gamma=False),
        None, None)
    assert out.shape == (0, c, 2, 2)


def test_zero_batch_activation_and_dropout():
    assert nd.Activation(nd.zeros((0, 4)), act_type="relu").shape == (0, 4)
    assert nd.Dropout(nd.zeros((0, 4)), p=0.5).shape == (0, 4)


# ---------------------------------------------------------------------------
# 7. dtype preservation: casts, int ops, comparison outputs
# ---------------------------------------------------------------------------
CAST_DTYPES = ["float32", "float16", "bfloat16", "int32", "int8", "uint8"]


@pytest.mark.parametrize("src", CAST_DTYPES)
@pytest.mark.parametrize("dst", CAST_DTYPES)
def test_cast_matrix(src, dst):
    vals = onp.array([0, 1, 2, 3], "float32")
    x = nd.array(_to(src, vals))
    out = nd.cast(x, dtype=dst)
    assert str(out.dtype) == dst, (src, dst, out.dtype)
    onp.testing.assert_array_equal(_f32(out.asnumpy()), vals)


@pytest.mark.parametrize("dtype", ["int32", "int8"])
@pytest.mark.parametrize("op_name", ["abs", "sign", "clip"])
def test_int_elemwise(op_name, dtype):
    x = onp.array([-3, -1, 0, 2, 5], dtype=dtype)
    if op_name == "clip":
        out = nd.clip(nd.array(x), a_min=-1.0, a_max=2.0)
        want = onp.clip(x, -1, 2)
    else:
        out = getattr(nd, op_name)(nd.array(x))
        want = getattr(onp, op_name if op_name != "abs" else "abs")(x)
    assert str(out.dtype) == dtype
    onp.testing.assert_array_equal(out.asnumpy(), want)


# ---------------------------------------------------------------------------
# 8. degenerate contraction dims
# ---------------------------------------------------------------------------
def test_dot_zero_k():
    a, b = nd.zeros((3, 0)), nd.zeros((0, 4))
    out = nd.dot(a, b)
    assert out.shape == (3, 4)
    onp.testing.assert_array_equal(out.asnumpy(), onp.zeros((3, 4)))


def test_batch_dot_zero_batch():
    out = nd.batch_dot(nd.zeros((0, 2, 3)), nd.zeros((0, 3, 4)))
    assert out.shape == (0, 2, 4)


def test_linalg_gemm2_degenerate():
    out = nd.linalg_gemm2(nd.zeros((2, 0)), nd.zeros((0, 3)))
    assert out.shape == (2, 3)
    onp.testing.assert_array_equal(out.asnumpy(), onp.zeros((2, 3)))


# ---------------------------------------------------------------------------
# 9. indexing edges
# ---------------------------------------------------------------------------
def test_take_clip_mode_out_of_range():
    x = onp.arange(12, dtype="float32").reshape(4, 3)
    out = nd.take(nd.array(x), nd.array([-1.0, 5.0]), axis=0, mode="clip")
    onp.testing.assert_array_equal(out.asnumpy(), x[[0, 3]])


def test_take_empty_indices():
    x = onp.arange(6, dtype="float32").reshape(2, 3)
    out = nd.take(nd.array(x), nd.array(onp.zeros((0,), "float32")), axis=0)
    assert out.shape == (0, 3)


def test_gather_nd_basic_and_negative():
    # indices are per-DIMENSION rows (tensor/indexing_op.h gather_nd):
    # idx[0] = coords in dim 0, idx[1] = coords in dim 1
    x = onp.arange(12, dtype="float32").reshape(3, 4)
    idx = onp.array([[0, 2], [1, 3]], "float32")
    out = nd.gather_nd(nd.array(x), nd.array(idx))
    onp.testing.assert_array_equal(out.asnumpy(), x[[0, 2], [1, 3]])


def test_one_hot_zero_and_dtype():
    out = nd.one_hot(nd.array(onp.zeros((0,), "float32")), depth=4)
    assert out.shape == (0, 4)
    out = nd.one_hot(nd.array([1.0, 3.0]), depth=4)
    onp.testing.assert_array_equal(out.asnumpy(),
                                   onp.eye(4, dtype="float32")[[1, 3]])


def test_where_broadcast():
    cond = onp.array([[1.0], [0.0]], "float32")
    a = onp.ones((2, 3), "float32")
    b = onp.zeros((2, 3), "float32")
    out = nd.where(nd.array(cond.repeat(3, 1)), nd.array(a), nd.array(b))
    onp.testing.assert_array_equal(out.asnumpy(), cond.repeat(3, axis=1))


# ---------------------------------------------------------------------------
# 10. random family: zero-size draws, dtype, bounds
# ---------------------------------------------------------------------------
def test_random_zero_size():
    assert nd.random.uniform(shape=(0,)).shape == (0,)
    assert nd.random.normal(shape=(2, 0)).shape == (2, 0)


def test_random_bounds_and_dtype():
    u = nd.random.uniform(low=2.0, high=3.0, shape=(64,)).asnumpy()
    assert (u >= 2.0).all() and (u < 3.0).all()
    r = nd.random.randint(low=0, high=5, shape=(64,))
    rv = r.asnumpy()
    assert (rv >= 0).all() and (rv < 5).all()


# ---------------------------------------------------------------------------
# 11. quantization family edges
# ---------------------------------------------------------------------------
def test_quantize_v2_roundtrip_extremes():
    x = onp.array([[-1.0, 0.0, 1.0], [0.5, -0.5, 0.25]], "float32")
    q, mn, mx_ = nd.contrib.quantize_v2(nd.array(x), min_calib_range=-1.0,
                                        max_calib_range=1.0)
    assert str(q.dtype) in ("int8", "uint8")
    back = nd.contrib.dequantize(q, mn, mx_)
    onp.testing.assert_allclose(back.asnumpy(), x, atol=2e-2)


def test_quantized_flatten_shape():
    q, mn, mx_ = nd.contrib.quantize_v2(
        nd.array(onp.ones((2, 3, 4), "float32")),
        min_calib_range=-1.0, max_calib_range=1.0)
    f, _, _ = nd.contrib.quantized_flatten(q, mn, mx_)
    assert f.shape == (2, 12)


# ---------------------------------------------------------------------------
# 12. contrib detection / attention edges
# ---------------------------------------------------------------------------
def test_box_nms_all_below_threshold():
    # every box below valid_thresh -> all entries -1 (reference convention)
    dets = onp.array([[[0.05, 0.1, 0.1, 0.9, 0.9],
                       [0.02, 0.2, 0.2, 0.8, 0.8]]], "float32")
    out = nd.contrib.box_nms(nd.array(dets), valid_thresh=0.5)
    assert (out.asnumpy() == -1).all()


def test_box_iou_zero_boxes():
    a = nd.zeros((0, 4))
    b = nd.array(onp.array([[0.0, 0.0, 1.0, 1.0]], "float32"))
    out = nd.contrib.box_iou(a, b)
    assert out.shape == (0, 1)


def test_interleaved_selfatt_minimal():
    # qkv (S, B, 3*H*D) with S=1: attention over one position is identity-ish
    S, B, H, D = 1, 2, 2, 4
    qkv = onp.random.RandomState(8).randn(S, B, 3 * H * D).astype("float32")
    att = nd.contrib.interleaved_matmul_selfatt_qk(nd.array(qkv), heads=H)
    assert att.shape == (B * H, S, S)
    probs = nd.softmax(att, axis=-1)
    out = nd.contrib.interleaved_matmul_selfatt_valatt(
        nd.array(qkv), probs, heads=H)
    assert out.shape == (S, B, H * D)


def test_roi_align_zero_rois():
    feat = nd.array(onp.random.RandomState(9).rand(1, 2, 8, 8)
                    .astype("float32"))
    rois = nd.zeros((0, 5))
    out = nd.contrib.ROIAlign(feat, rois, pooled_size=(2, 2),
                              spatial_scale=1.0)
    assert out.shape == (0, 2, 2, 2)


# ---------------------------------------------------------------------------
# 13. RNN edges: seq-len 1, batch 1
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode", ["rnn_tanh", "lstm", "gru"])
def test_rnn_minimal_lengths(mode):
    T, B, I, H = 1, 1, 3, 4
    ngates = {"rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
    nparams = ngates * H * (I + H + 2)
    x = nd.array(onp.random.RandomState(10).randn(T, B, I).astype("float32"))
    params = nd.array(onp.random.RandomState(11)
                      .randn(nparams).astype("float32") * 0.1)
    init_h = nd.zeros((1, B, H))
    if mode == "lstm":
        out = nd.RNN(x, params, init_h, nd.zeros((1, B, H)),
                     state_size=H, num_layers=1, mode=mode)
    else:
        out = nd.RNN(x, params, init_h, state_size=H, num_layers=1, mode=mode)
    first = out[0] if isinstance(out, (list, tuple)) else out
    assert first.shape == (T, B, H)
    assert onp.isfinite(first.asnumpy()).all()


# ---------------------------------------------------------------------------
# 14. control flow with degenerate trip counts
# ---------------------------------------------------------------------------
def test_foreach_length_zero():
    from mxnet_tpu.ops.registry import apply_op
    data = nd.zeros((0, 3))
    init = nd.ones((3,))
    outs, states = nd.contrib.foreach(
        lambda x, s: (x + s, s * 2), data, init)
    assert outs.shape == (0, 3)
    onp.testing.assert_array_equal(states.asnumpy(), onp.ones(3))


def test_while_loop_zero_iterations():
    outs, states = nd.contrib.while_loop(
        cond=lambda s: s < 0,           # immediately false
        func=lambda s: (s, s + 1),
        loop_vars=nd.array([5.0]),
        max_iterations=4)
    onp.testing.assert_array_equal(states[0].asnumpy()
                                   if isinstance(states, (list, tuple))
                                   else states.asnumpy(), [5.0])


# ---------------------------------------------------------------------------
# 15. image family edges
# ---------------------------------------------------------------------------
def test_image_resize_identity_and_upscale():
    img = nd.array(onp.random.RandomState(12).rand(4, 4, 3)
                   .astype("float32"))
    same = mx.image.imresize(img, 4, 4)
    assert same.shape == (4, 4, 3)
    up = mx.image.imresize(img, 8, 8)
    assert up.shape == (8, 8, 3)


def test_image_crop_corner():
    img = nd.array(onp.arange(4 * 4 * 3, dtype="float32").reshape(4, 4, 3))
    out = mx.image.fixed_crop(img, 0, 0, 2, 2)
    onp.testing.assert_array_equal(out.asnumpy(), img.asnumpy()[:2, :2])


# ---------------------------------------------------------------------------
# 16. optimizer ops with zero-size weights (scheduler-robustness edge)
# ---------------------------------------------------------------------------
def test_sgd_update_zero_size():
    out = nd.sgd_update(nd.zeros((0, 3)), nd.zeros((0, 3)), lr=0.1)
    assert out.shape == (0, 3)


def test_adam_update_zero_size():
    outs = nd.adam_update(nd.zeros((0,)), nd.zeros((0,)), nd.zeros((0,)),
                          nd.zeros((0,)), lr=0.1)
    assert outs[0].shape == (0,)


# ---------------------------------------------------------------------------
# 17. numpy-surface edges (mx.np family)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("op_name", ["sum", "mean", "max", "min", "prod"])
def test_np_reduction_none_axis_zero_size(op_name):
    from mxnet_tpu import np as mnp
    x = mnp.ones((2, 3))
    out = getattr(mnp, op_name)(x, axis=None)
    assert out.shape == ()
    want = getattr(onp, op_name)(onp.ones((2, 3), "float32"))
    onp.testing.assert_allclose(float(out), want)


def test_np_concatenate_with_empty():
    from mxnet_tpu import np as mnp
    a = mnp.ones((0, 3))
    b = mnp.ones((2, 3))
    out = mnp.concatenate([a, b], axis=0)
    assert out.shape == (2, 3)


def test_np_einsum_zero_dim():
    from mxnet_tpu import np as mnp
    a = mnp.ones((3, 0))
    b = mnp.ones((0, 4))
    out = mnp.einsum("ij,jk->ik", a, b)
    assert out.shape == (3, 4)
    onp.testing.assert_array_equal(onp.asarray(out), onp.zeros((3, 4)))


def test_np_where_scalar_branches():
    from mxnet_tpu import np as mnp
    cond = mnp.array([True, False, True])
    out = mnp.where(cond, 1.0, -1.0)
    onp.testing.assert_array_equal(onp.asarray(out), [1.0, -1.0, 1.0])


def test_np_broadcasting_arithmetic_zero():
    from mxnet_tpu import np as mnp
    out = mnp.ones((2, 0, 3)) + mnp.ones((1, 1, 3))
    assert out.shape == (2, 0, 3)


# ---------------------------------------------------------------------------
# 18. sequence ops: minimal lengths + per-batch lengths
# ---------------------------------------------------------------------------
def test_sequence_mask_lengths():
    x = onp.ones((3, 2, 4), "float32")      # (T, B, ...)
    out = nd.SequenceMask(nd.array(x), nd.array([1.0, 3.0]),
                          use_sequence_length=True, value=-1.0)
    got = out.asnumpy()
    assert (got[0] == 1).all()
    assert (got[1:, 0] == -1).all() and (got[1:, 1] == 1).all()


def test_sequence_last_per_batch():
    x = onp.arange(3 * 2 * 1, dtype="float32").reshape(3, 2, 1)
    out = nd.SequenceLast(nd.array(x), nd.array([1.0, 3.0]),
                          use_sequence_length=True)
    onp.testing.assert_array_equal(out.asnumpy().ravel(),
                                   [x[0, 0, 0], x[2, 1, 0]])


def test_sequence_reverse_respects_lengths():
    x = onp.arange(3 * 2 * 1, dtype="float32").reshape(3, 2, 1)
    out = nd.SequenceReverse(nd.array(x), nd.array([2.0, 3.0]),
                             use_sequence_length=True)
    got = out.asnumpy()
    onp.testing.assert_array_equal(got[:, 0, 0],
                                   [x[1, 0, 0], x[0, 0, 0], x[2, 0, 0]])
    onp.testing.assert_array_equal(got[:, 1, 0], x[::-1, 1, 0])


# ---------------------------------------------------------------------------
# 19. sparse zero-nnz
# ---------------------------------------------------------------------------
def test_rowsparse_zero_nnz_to_dense():
    from mxnet_tpu.sparse import RowSparseNDArray
    rsp = RowSparseNDArray(onp.zeros((0, 3), "float32"),
                           onp.zeros((0,), "int32"), (4, 3))
    dense = rsp.todense() if hasattr(rsp, "todense") else rsp.to_dense()
    onp.testing.assert_array_equal(onp.asarray(dense.asnumpy()),
                                   onp.zeros((4, 3)))


def test_csr_zero_nnz_dot():
    from mxnet_tpu.sparse import CSRNDArray
    csr = CSRNDArray(onp.zeros((0,), "float32"), onp.zeros((0,), "int32"),
                     onp.zeros((4,), "int32"), (3, 5))
    out = nd.dot(csr, nd.ones((5, 2)))
    onp.testing.assert_array_equal(out.asnumpy(), onp.zeros((3, 2)))
