"""Monitor tests (parity pattern: tests/python/unittest/test_monitor.py)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd


def _bound_exe():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc1", num_hidden=4)
    out = mx.sym.Activation(fc, name="act1", act_type="relu")
    exe = out.simple_bind(mx.cpu(), data=(2, 3))
    exe.arg_dict["data"][:] = nd.array(onp.ones((2, 3), "float32"))
    return exe


def test_monitor_collects_outputs_and_args():
    mon = mx.Monitor(interval=1)
    exe = _bound_exe()
    mon.install(exe)
    mon.tic()
    exe.forward()
    res = mon.toc()
    names = [k for _, k, _ in res]
    assert any(n.endswith("_output") for n in names), names
    assert "data" in names  # arguments collected too
    assert all(isinstance(v, str) and v.strip() for _, _, v in res)


def test_monitor_pattern_and_interval():
    mon = mx.Monitor(interval=2, pattern=".*output")
    exe = _bound_exe()
    mon.install(exe)
    mon.tic()            # step 0: active
    exe.forward()
    res = mon.toc()
    assert res and all(k.endswith("_output") for _, k, _ in res)
    mon.tic()            # step 1: inactive (interval 2)
    exe.forward()
    assert mon.toc() == []


def test_monitor_monitor_all_inputs():
    mon = mx.Monitor(interval=1, monitor_all=True, pattern=".*input.*")
    exe = _bound_exe()
    mon.install(exe)
    mon.tic()
    exe.forward()
    res = mon.toc()
    assert any("_input" in k for _, k, _ in res), res


def test_opperf_harness():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "opperf", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "benchmark", "opperf.py"))
    opperf = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(opperf)
    res = opperf.run_performance_test(["exp", "dot"], warmup=1, runs=2)
    assert {r["operator"] for r in res} == {"exp", "dot"}
    for r in res:
        assert r["avg_time_forward_us"] > 0
        assert "avg_time_backward_us" in r
