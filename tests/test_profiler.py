"""Profiler dispatch-hook tests.

Parity: the reference attaches a ProfileOperator to every engine op
(src/profiler/profiler.h:251, src/engine/threaded_engine.h:85) so that
``profiler.start(); net(x); profiler.dumps()`` yields a populated per-op
table with zero user annotations. These tests assert the same contract for
the eager op path, the CachedOp (hybridized) path, and the chrome-trace dump.
"""
import json
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler
from mxnet_tpu.gluon import nn


@pytest.fixture(autouse=True)
def _clean_profiler_state():
    profiler._STATE["running"] = False
    profiler._STATE["events"].clear()
    profiler._STATE["agg"].clear()
    yield
    profiler._STATE["running"] = False
    profiler._STATE["events"].clear()
    profiler._STATE["agg"].clear()


def test_eager_ops_recorded_without_annotations():
    a = mx.nd.ones((4, 4))
    b = mx.nd.ones((4, 4))
    profiler.start()
    c = (a + b) * 2
    d = mx.nd.dot(c, c)
    d.wait_to_read()
    profiler.stop()
    table = profiler.dumps()
    # at least the elemwise and dot ops must appear by name
    assert "dot" in table
    agg = profiler._STATE["agg"]
    assert any(v[0] >= 1 for v in agg.values())
    # durations are positive
    for name, (count, total, mn, mx_) in agg.items():
        assert count >= 1
        assert total >= 0.0


def test_ops_not_recorded_when_stopped():
    a = mx.nd.ones((2, 2))
    _ = a + a
    assert not profiler._STATE["agg"]


def test_cached_op_path_recorded():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 16))
    net(x)  # warm up / compile outside the profiled region
    profiler.start()
    y = net(x)
    y.wait_to_read()
    profiler.stop()
    table = profiler.dumps()
    assert "CachedOp[HybridSequential]" in table


def test_chrome_trace_dump(tmp_path):
    fname = str(tmp_path / "profile.json")
    profiler.set_config(filename=fname)
    a = mx.nd.ones((4,))
    profiler.start()
    (a * 3).wait_to_read()
    profiler.stop()
    profiler.dump()
    with open(fname) as f:
        trace = json.load(f)
    assert "traceEvents" in trace
    assert len(trace["traceEvents"]) >= 1
    ev = trace["traceEvents"][0]
    assert {"name", "ph", "ts", "dur"} <= set(ev)


# ---------------------------------------------------------------------------
# r7 satellites: Counter atomicity, dumps(format=), continuous_dump, schema
# ---------------------------------------------------------------------------
def test_counter_increment_is_atomic_under_threads():
    """Regression: the read-modify-write of Counter.value used to run outside
    _STATE['lock'], so concurrent increments lost counts."""
    import threading
    c = profiler.Counter("race_counter")
    n_threads, n_iter = 8, 5000

    def bump():
        for _ in range(n_iter):
            c.increment()

    threads = [threading.Thread(target=bump) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_iter
    for t in [threading.Thread(target=lambda: [c.decrement()
                                               for _ in range(n_iter)])
              for _ in range(n_threads)]:
        t.start()
        t.join()
    assert c.value == 0


def test_counter_events_emitted_under_lock_while_running():
    profiler.start()
    c = profiler.Counter("tracked", value=10)
    c.increment(5)
    c.decrement(3)
    profiler.stop()
    evs = [e for e in profiler._STATE["events"] if e["name"] == "tracked"]
    assert [e["args"]["value"] for e in evs] == [15, 12]
    assert all(e["ph"] == "C" for e in evs)


def test_dumps_json_format():
    a = mx.nd.ones((4, 4))
    profiler.start()
    mx.nd.dot(a, a).wait_to_read()
    profiler.stop()
    out = profiler.dumps(format="json")
    table = json.loads(out)
    assert "dot" in table
    row = table["dot"]
    assert row["count"] >= 1
    assert row["total_us"] >= row["min_us"] >= 0
    assert row["max_us"] >= row["avg_us"] > 0 or row["total_us"] == 0
    # default stays the text table; bad formats are rejected loudly
    assert "Name" in profiler.dumps()
    import pytest as _pytest
    with _pytest.raises(ValueError):
        profiler.dumps(format="csv")


def test_continuous_dump_appends_and_clears(tmp_path):
    fname = str(tmp_path / "cont.json")
    profiler.set_config(filename=fname, continuous_dump=True)
    a = mx.nd.ones((8,))
    profiler.start()
    (a + a).wait_to_read()
    profiler.dump(finished=False)
    assert profiler._STATE["events"] == []     # incremental dump drains memory
    n_first = len(open(fname).read().strip().splitlines())
    (a * 2).wait_to_read()
    (a * 3).wait_to_read()
    profiler.dump(finished=False)
    assert profiler._STATE["events"] == []
    content = open(fname).read()
    assert len(content.strip().splitlines()) > n_first  # appended, not rewrote
    profiler.stop()
    profiler.dump(finished=True)               # closes the array: strict JSON
    events = json.loads(open(fname).read())
    assert isinstance(events, list) and len(events) >= 3
    assert all("name" in e for e in events[:-1])
    # reset config for other tests (module-global state)
    profiler.set_config()


def test_chrome_trace_schema(tmp_path):
    """Every emitted event carries the chrome-trace required keys, the file
    JSON round-trips, and ph:'C' counter samples carry args.value."""
    fname = str(tmp_path / "schema.json")
    profiler.set_config(filename=fname)
    from mxnet_tpu import telemetry
    a = mx.nd.ones((4, 4))
    profiler.start()
    mx.nd.dot(a, a).wait_to_read()
    with profiler.scope("user_scope"):
        (a + 1).wait_to_read()
    with telemetry.span("test.schema_span"):
        pass
    c = profiler.Counter("schema_counter")
    c.increment(7)
    profiler.Marker("schema_marker").mark()
    t = profiler.Task("schema_task")
    t.start()
    t.stop()
    profiler.stop()
    profiler.dump()
    trace = json.loads(open(fname).read())    # JSON round-trips
    events = trace["traceEvents"]
    assert len(events) >= 5
    phases = set()
    for ev in events:
        assert {"name", "ph", "ts", "pid"} <= set(ev), f"bad event {ev}"
        assert isinstance(ev["ts"], int)
        phases.add(ev["ph"])
        if ev["ph"] == "X":
            assert "dur" in ev and ev["dur"] >= 0
        if ev["ph"] == "C":
            assert "args" in ev and "value" in ev["args"], \
                f"counter event without args.value: {ev}"
    assert {"X", "C", "i"} <= phases
    # the telemetry span landed in the same timeline with its trace id
    span_evs = [e for e in events if e["name"] == "test.schema_span"]
    assert span_evs and "trace_id" in span_evs[0]["args"]
