"""Profiler dispatch-hook tests.

Parity: the reference attaches a ProfileOperator to every engine op
(src/profiler/profiler.h:251, src/engine/threaded_engine.h:85) so that
``profiler.start(); net(x); profiler.dumps()`` yields a populated per-op
table with zero user annotations. These tests assert the same contract for
the eager op path, the CachedOp (hybridized) path, and the chrome-trace dump.
"""
import json
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler
from mxnet_tpu.gluon import nn


@pytest.fixture(autouse=True)
def _clean_profiler_state():
    profiler._STATE["running"] = False
    profiler._STATE["events"].clear()
    profiler._STATE["agg"].clear()
    yield
    profiler._STATE["running"] = False
    profiler._STATE["events"].clear()
    profiler._STATE["agg"].clear()


def test_eager_ops_recorded_without_annotations():
    a = mx.nd.ones((4, 4))
    b = mx.nd.ones((4, 4))
    profiler.start()
    c = (a + b) * 2
    d = mx.nd.dot(c, c)
    d.wait_to_read()
    profiler.stop()
    table = profiler.dumps()
    # at least the elemwise and dot ops must appear by name
    assert "dot" in table
    agg = profiler._STATE["agg"]
    assert any(v[0] >= 1 for v in agg.values())
    # durations are positive
    for name, (count, total, mn, mx_) in agg.items():
        assert count >= 1
        assert total >= 0.0


def test_ops_not_recorded_when_stopped():
    a = mx.nd.ones((2, 2))
    _ = a + a
    assert not profiler._STATE["agg"]


def test_cached_op_path_recorded():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 16))
    net(x)  # warm up / compile outside the profiled region
    profiler.start()
    y = net(x)
    y.wait_to_read()
    profiler.stop()
    table = profiler.dumps()
    assert "CachedOp[HybridSequential]" in table


def test_chrome_trace_dump(tmp_path):
    fname = str(tmp_path / "profile.json")
    profiler.set_config(filename=fname)
    a = mx.nd.ones((4,))
    profiler.start()
    (a * 3).wait_to_read()
    profiler.stop()
    profiler.dump()
    with open(fname) as f:
        trace = json.load(f)
    assert "traceEvents" in trace
    assert len(trace["traceEvents"]) >= 1
    ev = trace["traceEvents"][0]
    assert {"name", "ph", "ts", "dur"} <= set(ev)
