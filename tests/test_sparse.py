"""Sparse storage tests (parity patterns: tests/python/unittest/test_sparse_ndarray.py,
test_sparse_operator.py; sparse optimizer tests in test_optimizer.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, sparse
from mxnet_tpu.sparse import (CSRNDArray, RowSparseNDArray, cast_storage,
                              csr_matrix, row_sparse_array)


def _rand_dense(shape, density=0.3, seed=0):
    rng = onp.random.RandomState(seed)
    arr = rng.randn(*shape).astype("float32")
    mask = rng.rand(*shape) < density
    return arr * mask


# ---------------------------------------------------------------------------
# storage round trips
# ---------------------------------------------------------------------------
def test_row_sparse_roundtrip():
    dense = onp.zeros((6, 4), "float32")
    dense[1] = 1.5
    dense[4] = -2.0
    a = nd.array(dense)
    rsp = a.tostype("row_sparse")
    assert isinstance(rsp, RowSparseNDArray)
    assert rsp.stype == "row_sparse"
    assert rsp.nnz == 2
    assert rsp.indices.asnumpy().tolist() == [1, 4]
    onp.testing.assert_allclose(rsp.asnumpy(), dense)
    back = rsp.tostype("default")
    assert back.stype == "default"
    onp.testing.assert_allclose(back.asnumpy(), dense)


def test_csr_roundtrip():
    dense = _rand_dense((5, 7))
    csr = nd.array(dense).tostype("csr")
    assert isinstance(csr, CSRNDArray)
    assert csr.stype == "csr"
    onp.testing.assert_allclose(csr.asnumpy(), dense, rtol=1e-6)
    assert csr.indptr.asnumpy()[-1] == csr.nnz
    onp.testing.assert_allclose(csr.todense().asnumpy(), dense, rtol=1e-6)
    # csr <-> row_sparse via dense
    rsp = csr.tostype("row_sparse")
    onp.testing.assert_allclose(rsp.asnumpy(), dense, rtol=1e-6)


def test_constructors():
    rsp = row_sparse_array((onp.ones((2, 3), "float32"), [1, 3]), shape=(5, 3))
    assert rsp.shape == (5, 3)
    assert rsp.asnumpy()[1].tolist() == [1, 1, 1]
    assert rsp.asnumpy()[0].tolist() == [0, 0, 0]

    csr = csr_matrix((onp.array([1., 2., 3.], "float32"), [0, 2, 1],
                      [0, 2, 2, 3]), shape=(3, 4))
    expect = onp.zeros((3, 4), "float32")
    expect[0, 0], expect[0, 2], expect[2, 1] = 1, 2, 3
    onp.testing.assert_allclose(csr.asnumpy(), expect)

    z = sparse.zeros("row_sparse", (4, 2))
    assert z.nnz == 0
    onp.testing.assert_allclose(z.asnumpy(), onp.zeros((4, 2)))


def test_save_load_sparse(tmp_path):
    dense = _rand_dense((6, 3))
    rsp = nd.array(dense).tostype("row_sparse")
    csr = nd.array(_rand_dense((4, 5), seed=1)).tostype("csr")
    f = str(tmp_path / "sp.params")
    nd.save(f, {"rsp": rsp, "csr": csr, "dense": nd.array(dense)})
    loaded = nd.load(f)
    assert isinstance(loaded["rsp"], RowSparseNDArray)
    assert isinstance(loaded["csr"], CSRNDArray)
    onp.testing.assert_allclose(loaded["rsp"].asnumpy(), dense, rtol=1e-6)
    onp.testing.assert_allclose(loaded["csr"].asnumpy(), csr.asnumpy(), rtol=1e-6)
    onp.testing.assert_allclose(loaded["dense"].asnumpy(), dense, rtol=1e-6)


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------
def test_csr_dot():
    lhs = _rand_dense((5, 7), seed=2)
    rhs = onp.random.RandomState(3).randn(7, 4).astype("float32")
    csr = nd.array(lhs).tostype("csr")
    out = sparse.dot(csr, nd.array(rhs))
    onp.testing.assert_allclose(out.asnumpy(), lhs @ rhs, rtol=1e-5, atol=1e-5)
    # transpose_a: (7,5)·(5,4) contributions scatter over columns
    rhs_t = onp.random.RandomState(4).randn(5, 4).astype("float32")
    out_t = sparse.dot(csr, nd.array(rhs_t), transpose_a=True)
    onp.testing.assert_allclose(out_t.asnumpy(), lhs.T @ rhs_t, rtol=1e-5,
                                atol=1e-5)


def test_rsp_dot_and_scalar_ops():
    lhs = onp.zeros((6, 3), "float32")
    lhs[2] = [1, 2, 3]
    lhs[5] = [-1, 0, 1]
    rhs = onp.random.RandomState(5).randn(3, 2).astype("float32")
    rsp = nd.array(lhs).tostype("row_sparse")
    out = sparse.dot(rsp, nd.array(rhs))
    onp.testing.assert_allclose(out.asnumpy(), lhs @ rhs, rtol=1e-5, atol=1e-5)
    scaled = rsp * 2.0
    assert isinstance(scaled, RowSparseNDArray)
    onp.testing.assert_allclose(scaled.asnumpy(), lhs * 2, rtol=1e-6)
    s = rsp + rsp
    assert isinstance(s, RowSparseNDArray)
    onp.testing.assert_allclose(s.asnumpy(), lhs * 2, rtol=1e-6)


def test_retain():
    dense = onp.diag(onp.arange(1, 5, dtype="float32"))
    rsp = nd.array(dense).tostype("row_sparse")
    kept = sparse.retain(rsp, [0, 2])
    expect = onp.zeros_like(dense)
    expect[0], expect[2] = dense[0], dense[2]
    onp.testing.assert_allclose(kept.asnumpy(), expect)


def test_add_n_dedup():
    a = row_sparse_array((onp.ones((2, 2), "float32"), [0, 2]), shape=(4, 2))
    b = row_sparse_array((onp.full((2, 2), 2.0, "float32"), [2, 3]), shape=(4, 2))
    s = sparse.add_n([a, b])
    expect = onp.zeros((4, 2), "float32")
    expect[0] = 1
    expect[2] = 3
    expect[3] = 2
    onp.testing.assert_allclose(s.asnumpy(), expect)


# ---------------------------------------------------------------------------
# autograd: Embedding sparse_grad
# ---------------------------------------------------------------------------
def test_embedding_sparse_grad_matches_dense():
    vocab, dim = 10, 4
    rng = onp.random.RandomState(0)
    w_np = rng.randn(vocab, dim).astype("float32")
    tokens = nd.array(onp.array([[1, 3], [3, 7]]), dtype="int32")

    grads = {}
    for sparse_grad in (False, True):
        w = nd.array(w_np)
        w.attach_grad(stype="row_sparse" if sparse_grad else None)
        with autograd.record():
            emb = nd.Embedding(tokens, w, input_dim=vocab, output_dim=dim,
                               sparse_grad=sparse_grad)
            loss = (emb * emb).sum()
        loss.backward()
        grads[sparse_grad] = w.grad

    assert isinstance(grads[True], RowSparseNDArray)
    # touched rows only: 1, 3, 7 (3 counted twice)
    idx = grads[True].indices.asnumpy()
    real = idx[idx < vocab]
    assert sorted(set(real.tolist())) == [1, 3, 7]
    onp.testing.assert_allclose(grads[True].asnumpy(), grads[False].asnumpy(),
                                rtol=1e-5, atol=1e-6)


def test_gluon_embedding_sparse_grad_end_to_end():
    from mxnet_tpu.gluon import nn
    net = nn.Embedding(20, 6, sparse_grad=True)
    net.initialize()
    x = nd.array(onp.array([[0, 5, 5, 19]]), dtype="int32")
    with autograd.record():
        y = net(x)
        loss = y.sum()
    loss.backward()
    g = net.weight.grad()
    assert isinstance(g, RowSparseNDArray)
    gd = g.asnumpy()
    assert abs(gd[5].sum() - 12.0) < 1e-4  # row 5 hit twice, d(sum)/dy = 1
    assert abs(gd[1].sum()) < 1e-6         # untouched row


# ---------------------------------------------------------------------------
# sparse (lazy) optimizer updates
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("opt_cls, kwargs", [
    (mx.optimizer.SGD, {"learning_rate": 0.1, "momentum": 0.9}),
    (mx.optimizer.Adam, {"learning_rate": 0.01}),
])
def test_sparse_optimizer_lazy_update(opt_cls, kwargs):
    vocab, dim = 8, 3
    rng = onp.random.RandomState(1)
    w_np = rng.randn(vocab, dim).astype("float32")
    g_rows = rng.randn(2, dim).astype("float32")
    touched = [2, 5]

    # dense reference: same rule applied to only the touched rows
    opt_d = opt_cls(**kwargs)
    w_d = nd.array(w_np[touched])
    state_d = opt_d.create_state(0, w_d)
    opt_d.update(0, w_d, nd.array(g_rows), state_d)

    opt_s = opt_cls(**kwargs)
    w_s = nd.array(w_np)
    state_s = opt_s.create_state(0, w_s)
    grad = row_sparse_array((g_rows, touched), shape=(vocab, dim))
    opt_s.update(0, w_s, grad, state_s)

    out = w_s.asnumpy()
    onp.testing.assert_allclose(out[touched], w_d.asnumpy(), rtol=1e-5,
                                atol=1e-6)
    untouched = [i for i in range(vocab) if i not in touched]
    onp.testing.assert_allclose(out[untouched], w_np[untouched])  # lazy


def test_sparse_optimizer_duplicate_indices_summed():
    opt = mx.optimizer.SGD(learning_rate=1.0)
    w = nd.array(onp.zeros((4, 2), "float32"))
    grad = row_sparse_array((onp.ones((2, 2), "float32"), [1, 1]), shape=(4, 2))
    opt.update(0, w, grad, None)
    onp.testing.assert_allclose(w.asnumpy()[1], [-2.0, -2.0])


# ---------------------------------------------------------------------------
# kvstore
# ---------------------------------------------------------------------------
def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    w = nd.array(_rand_dense((6, 4), density=1.0, seed=6))
    kv.init(3, w)
    out = sparse.zeros("row_sparse", (6, 4))
    kv.row_sparse_pull(3, out=out, row_ids=nd.array([1, 4], dtype="int32"))
    assert isinstance(out, RowSparseNDArray)
    onp.testing.assert_allclose(out.asnumpy()[[1, 4]], w.asnumpy()[[1, 4]],
                                rtol=1e-6)
    onp.testing.assert_allclose(out.asnumpy()[0], onp.zeros(4))
    # dense out gets the zero-padded dense copy
    dout = nd.zeros((6, 4))
    kv.row_sparse_pull(3, out=dout, row_ids=nd.array([2], dtype="int32"))
    onp.testing.assert_allclose(dout.asnumpy()[2], w.asnumpy()[2], rtol=1e-6)
    assert abs(dout.asnumpy()[[0, 1, 3, 4, 5]]).sum() == 0


def test_kvstore_sparse_push_with_updater():
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
    w = nd.array(onp.zeros((5, 2), "float32"))
    kv.init(0, w)
    g1 = row_sparse_array((onp.ones((1, 2), "float32"), [1]), shape=(5, 2))
    g2 = row_sparse_array((onp.ones((1, 2), "float32"), [3]), shape=(5, 2))
    kv.push(0, [g1, g2])
    out = nd.zeros((5, 2))
    kv.pull(0, out=out)
    got = out.asnumpy()
    onp.testing.assert_allclose(got[1], [-1, -1])
    onp.testing.assert_allclose(got[3], [-1, -1])
    assert abs(got[[0, 2, 4]]).sum() == 0


# ---------------------------------------------------------------------------
# end-to-end: LSTM language model with sparse embedding grads (BASELINE cfg 5)
# ---------------------------------------------------------------------------
def test_lstm_lm_sparse_embedding_trains():
    """Sparse (lazy-Adam) LM training must (a) make real progress and
    (b) track a dense-embedding twin trained from the same init on the same
    data — the convergence bar is derived from the dense run, not absolute
    (reference lazy_update=True semantics, optimizer_op.cc sparse adam)."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn, rnn

    vocab, emb, hid, seq, batch = 50, 16, 32, 8, 4

    def make_lm(sparse_grad):
        class LM(gluon.Block):
            def __init__(self):
                super().__init__()
                with self.name_scope():
                    self.embed = nn.Embedding(vocab, emb,
                                              sparse_grad=sparse_grad)
                    self.lstm = rnn.LSTM(hid, num_layers=1, layout="NTC")
                    self.decoder = nn.Dense(vocab, flatten=False)

            def forward(self, x):
                return self.decoder(self.lstm(self.embed(x)))

        return LM()

    rng = onp.random.RandomState(0)
    data = rng.randint(0, vocab, (batch, seq + 1))
    x = nd.array(data[:, :-1], dtype="int32")
    y = nd.array(data[:, 1:].astype("float32"))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def train(net, steps=12):
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 0.01})
        losses = []
        for _ in range(steps):
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(batch)
            losses.append(float(loss.mean().asscalar()))
        return losses

    sparse_net = make_lm(sparse_grad=True)
    sparse_net.initialize(mx.init.Xavier())
    # identical init for the dense twin
    dense_net = make_lm(sparse_grad=False)
    dense_net.initialize(mx.init.Xavier())
    sparse_net(x)  # materialize deferred-init shapes before copying
    dense_net(x)
    sp = dict(sparse_net.collect_params().items())
    dp = dense_net.collect_params()
    for (ks, vs), (kd, vd) in zip(sorted(sp.items()), sorted(dp.items())):
        vd.set_data(nd.array(vs.data().asnumpy()))

    sparse_losses = train(sparse_net)
    dense_losses = train(dense_net)

    g = sparse_net.embed.weight.grad()
    assert isinstance(g, RowSparseNDArray)
    # real progress: final loss meaningfully below chance/initial
    assert sparse_losses[-1] < sparse_losses[0] * 0.85, sparse_losses
    # and the sparse lazy path tracks the dense trajectory closely
    onp.testing.assert_allclose(sparse_losses, dense_losses, rtol=0.08)


# ---------------------------------------------------------------------------
# regression: autograd.grad with sparse cotangents; grad_req='add' nnz cap;
# row_sparse_pull from a sparse store entry
# ---------------------------------------------------------------------------
def test_autograd_grad_returns_row_sparse():
    """autograd.grad() on a sparse_grad Embedding returns a RowSparseNDArray
    instead of crashing (python/mxnet/autograd.py grad parity)."""
    from mxnet_tpu.gluon import nn

    embed = nn.Embedding(10, 4, sparse_grad=True)
    embed.initialize()
    x = nd.array(onp.array([1, 3, 3, 7]), dtype="int32")
    w = embed.weight.data()
    with autograd.record():
        out = embed(x)
        loss = out.sum()
    g = autograd.grad(loss, [w])[0]
    assert isinstance(g, RowSparseNDArray)
    dense = g.asnumpy()
    exp = onp.zeros((10, 4), "float32")
    for i in [1, 3, 3, 7]:
        exp[i] += 1
    onp.testing.assert_allclose(dense, exp)


def test_sparse_grad_add_req_nnz_capped():
    """grad_req='add': repeated backwards must not grow the sparse grad
    buffer unboundedly — nnz stays <= number of distinct touched rows."""
    from mxnet_tpu.gluon import nn

    embed = nn.Embedding(10, 4, sparse_grad=True)
    embed.initialize()
    embed.weight.grad_req = "add"
    x = nd.array(onp.array([1, 3, 3, 7]), dtype="int32")
    for step in range(4):
        with autograd.record():
            loss = embed(x).sum()
        loss.backward()
    g = embed.weight.grad()
    assert isinstance(g, RowSparseNDArray)
    assert g.nnz <= 10, g.nnz
    dense = g.asnumpy()
    exp = onp.zeros((10, 4), "float32")
    for i in [1, 3, 3, 7]:
        exp[i] += 4.0
    onp.testing.assert_allclose(dense, exp)


def test_row_sparse_pull_from_sparse_store():
    """row_sparse_pull after a sparse push with no updater (store entry is a
    RowSparseNDArray) must gather logical rows, not value rows."""
    kv = mx.kv.create("local")
    g = row_sparse_array((onp.arange(4, dtype="float32").reshape(2, 2),
                          [1, 3]), shape=(5, 2))
    kv.init(0, nd.zeros((5, 2)))
    kv.push(0, g)
    out = sparse.row_sparse_array(
        (onp.zeros((2, 2), "float32"), [1, 3]), shape=(5, 2))
    kv.row_sparse_pull(0, out=out, row_ids=nd.array(onp.array([1, 3]),
                                                    dtype="int32"))
    got = out.asnumpy()
    exp = onp.zeros((5, 2), "float32")
    exp[1] = [0, 1]
    exp[3] = [2, 3]
    onp.testing.assert_allclose(got, exp)


def test_retain_jitted_padding():
    """retain keeps static nnz: dropped rows become shape[0] sentinels."""
    rsp = sparse.row_sparse_array((onp.array([[1., 1], [2, 2], [3, 3]]),
                                   [1, 4, 7]), shape=(10, 2))
    out = rsp.retain(nd.array([4, 9]))
    assert out.nnz == rsp.nnz  # static-nnz: no shape change, no recompile
    dense = out.todense().asnumpy()
    want = onp.zeros((10, 2), "float32")
    want[4] = 2.0
    onp.testing.assert_allclose(dense, want)
    # the kept row survives, dropped indices became the padding sentinel
    idx = onp.asarray(out._indices)
    assert (idx == 10).sum() == 2 and (idx == 4).sum() == 1


def test_csr_elemwise_same_pattern():
    d = onp.array([[0, 1., 0], [2., 0, 3.]], "float32")
    a = sparse.csr_matrix(d)
    b = sparse.csr_matrix(2 * d)
    s = sparse.elemwise_add(a, b)
    assert s.stype == "csr"
    onp.testing.assert_allclose(s.todense().asnumpy(), 3 * d)
    m = sparse.elemwise_mul(a, b)
    assert m.stype == "csr"
    onp.testing.assert_allclose(m.todense().asnumpy(), 2 * d * d)
    sc = a * 4.0
    assert sc.stype == "csr"
    onp.testing.assert_allclose(sc.todense().asnumpy(), 4 * d)


def test_csr_elemwise_different_pattern_densifies_correctly():
    d1 = onp.array([[0, 1., 0], [2., 0, 0]], "float32")
    d2 = onp.array([[5., 0, 0], [0, 0, 7.]], "float32")
    a, b = sparse.csr_matrix(d1), sparse.csr_matrix(d2)
    s = a + b
    onp.testing.assert_allclose(s.todense().asnumpy(), d1 + d2)
    m = a * b
    onp.testing.assert_allclose(m.todense().asnumpy(), d1 * d2)


def test_csr_csr_dot():
    rng = onp.random.RandomState(0)
    d1 = rng.rand(4, 6) * (rng.rand(4, 6) > 0.5)
    d2 = rng.rand(6, 3) * (rng.rand(6, 3) > 0.5)
    a = sparse.csr_matrix(d1.astype("float32"))
    b = sparse.csr_matrix(d2.astype("float32"))
    out = sparse.dot(a, b)
    onp.testing.assert_allclose(out.asnumpy(), d1 @ d2, rtol=1e-5, atol=1e-6)


def test_sparse_astype():
    rsp = sparse.row_sparse_array((onp.array([[1., 2]]), [3]), shape=(5, 2))
    out = rsp.astype("bfloat16")
    assert str(out.dtype) == "bfloat16"
    onp.testing.assert_allclose(out.todense().asnumpy().astype("float32"),
                                rsp.todense().asnumpy(), rtol=1e-2)


def test_libsvm_iter(tmp_path):
    """LibSVM iterator yields CSR batches (iter_libsvm.cc parity pattern:
    tests/python/unittest/test_io.py test_LibSVMIter)."""
    import mxnet_tpu as mx
    from mxnet_tpu.io import LibSVMIter
    from mxnet_tpu.sparse import CSRNDArray
    f = tmp_path / "train.libsvm"
    f.write_text("1 0:1.5 3:2.0\n0 1:0.5\n1 2:3.0 3:1.0\n")
    it = LibSVMIter(str(f), data_shape=(4,), batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    b0 = batches[0]
    assert isinstance(b0.data[0], CSRNDArray)
    dense = b0.data[0].todense().asnumpy()
    want = onp.zeros((2, 4), "float32")
    want[0, 0], want[0, 3], want[1, 1] = 1.5, 2.0, 0.5
    onp.testing.assert_allclose(dense, want)
    onp.testing.assert_allclose(b0.label[0].asnumpy(), [1.0, 0.0])
    assert batches[1].pad == 1  # 3 rows, batch 2 -> last batch padded
    it.reset()
    assert len(list(it)) == 2


def test_row_sparse_pull_duplicate_unsorted_empty():
    """row_sparse_pull must tolerate duplicate and unsorted row ids (dedup +
    sort before the gather, the sparse._dedup_fn convention) and an empty
    row-id pull (kvstore.h PullRowSparse tolerates all three)."""
    kv = mx.kv.create("local")
    w = nd.array(_rand_dense((8, 3), density=1.0, seed=11))
    kv.init(9, w)
    # duplicate + unsorted: rows gathered once each, in sorted order
    out = sparse.zeros("row_sparse", (8, 3))
    kv.row_sparse_pull(9, out=out, row_ids=nd.array([5, 2, 5, 0, 2],
                                                    dtype="int32"))
    idx = out.indices.asnumpy()
    real = sorted(set(idx[idx < 8].tolist()))
    assert real == [0, 2, 5]
    onp.testing.assert_allclose(out.asnumpy()[[0, 2, 5]],
                                w.asnumpy()[[0, 2, 5]], rtol=1e-6)
    assert abs(out.asnumpy()[[1, 3, 4, 6, 7]]).sum() == 0
    # dense out, duplicated ids: each requested row appears exactly once
    dout = nd.zeros((8, 3))
    kv.row_sparse_pull(9, out=dout, row_ids=nd.array([4, 4, 4], dtype="int32"))
    onp.testing.assert_allclose(dout.asnumpy()[4], w.asnumpy()[4], rtol=1e-6)
    assert abs(dout.asnumpy()[[0, 1, 2, 3, 5, 6, 7]]).sum() == 0
    # empty pull: no rows travel, out is all-zero
    eout = nd.zeros((8, 3))
    kv.row_sparse_pull(9, out=eout, row_ids=nd.array([], dtype="int32"))
    assert abs(eout.asnumpy()).sum() == 0


def test_gluon_embedding_sparse_vs_dense_grad_bitwise():
    """The sparse_grad=True gradient densifies BITWISE-equal to the dense
    path: the RowSparse cotangent accumulates duplicate hits in the same
    positional order as the dense scatter-add."""
    from mxnet_tpu.gluon import nn
    rng = onp.random.RandomState(3)
    w0 = rng.randn(12, 5).astype("float32")
    x = nd.array(onp.array([[3, 7, 3], [7, 0, 3]]), dtype="int32")
    scale = nd.array(rng.randn(2, 3, 5).astype("float32"))
    grads = {}
    for sg in (False, True):
        net = nn.Embedding(12, 5, sparse_grad=sg)
        net.initialize()
        net.weight.set_data(nd.array(w0))
        with autograd.record():
            loss = (net(x) * scale).sum()
        loss.backward()
        g = net.weight.grad()
        if sg:
            assert isinstance(g, RowSparseNDArray)
        grads[sg] = g.asnumpy()
    assert onp.array_equal(grads[True], grads[False])
