"""Multi-chip sharding tests on the virtual 8-device CPU mesh (conftest).

Pattern follows the reference's local-multiprocess distributed tests
(tests/nightly/dist_sync_kvstore.py via tools/launch.py --launcher local):
everything runs in one process, the mesh supplies the "cluster"."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.gluon import nn, loss as gloss


def test_make_mesh_shapes():
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    assert mesh.size == 8
    assert mesh.shape == {"dp": 4, "tp": 2}
    mesh2 = parallel.make_mesh({"dp": -1, "tp": 2})
    assert mesh2.shape["dp"] == 4


def test_collectives_shard_map():
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = parallel.make_mesh({"dp": 8})

    def f(x):
        return parallel.all_reduce(x, "dp")

    fn = shard_map(f, mesh=mesh.mesh, in_specs=P("dp"), out_specs=P("dp"))
    x = jnp.arange(8.0)
    out = fn(x)
    assert float(out[0]) == float(jnp.sum(x))


def test_train_step_data_parallel_matches_single_device():
    """The fused dp step must agree with the single-device eager path."""
    import jax.numpy as jnp
    onp.random.seed(0)
    xs = onp.random.randn(16, 8).astype("float32")
    ys = onp.random.randn(16, 1).astype("float32")

    def build():
        net = nn.Dense(1, in_units=8)
        net.initialize(mx.init.Constant(0.05))
        return net

    # eager single-device reference
    net_ref = build()
    trainer = mx.gluon.Trainer(net_ref.collect_params(), "sgd",
                               {"learning_rate": 0.1}, kvstore=None)
    l2 = gloss.L2Loss()
    for _ in range(3):
        x, y = mx.nd.array(xs), mx.nd.array(ys)
        with mx.autograd.record():
            out = net_ref(x)
            L = l2(out, y).mean()
        L.backward()
        trainer.step(1, ignore_stale_grad=True)

    # fused multi-chip step
    net_par = build()
    mesh = parallel.make_mesh({"dp": 8})
    step = parallel.ParallelTrainStep(
        net_par, gloss.L2Loss(), mx.optimizer.SGD(learning_rate=0.1), mesh)
    for _ in range(3):
        loss = step(xs, ys)
    step.sync_to_block()

    w_ref = net_ref.weight.data().asnumpy()
    w_par = net_par.weight.data().asnumpy()
    onp.testing.assert_allclose(w_ref, w_par, rtol=2e-5, atol=2e-5)


def test_train_step_tensor_parallel():
    """Dense weight sharded over tp: GSPMD handles the all-gather; result must
    match the replicated run."""
    from jax.sharding import PartitionSpec as P
    onp.random.seed(1)
    xs = onp.random.randn(8, 16).astype("float32")
    ys = onp.random.randn(8, 32).astype("float32")

    def run(shard):
        net = nn.Dense(32, in_units=16)
        net.initialize(mx.init.Xavier(rnd_type="gaussian", magnitude=1))
        # deterministic init for comparison
        net.weight.set_data(mx.nd.array(
            onp.linspace(-0.1, 0.1, 32 * 16).reshape(32, 16).astype("float32")))
        net.bias.set_data(mx.nd.array(onp.zeros(32, "float32")))
        if shard:
            net.weight.shard(P("tp", None))
        mesh = parallel.make_mesh({"dp": 4, "tp": 2})
        step = parallel.ParallelTrainStep(
            net, gloss.L2Loss(), mx.optimizer.SGD(learning_rate=0.05), mesh)
        for _ in range(2):
            step(xs, ys)
        step.sync_to_block()
        return net.weight.data().asnumpy()

    onp.testing.assert_allclose(run(False), run(True), rtol=2e-5, atol=2e-5)


def test_train_step_batchnorm_aux_updates():
    """BatchNorm moving stats must update through the pure aux path."""
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.BatchNorm(), nn.Dense(2))
    net.initialize()
    net(mx.nd.array(onp.zeros((2, 4), "float32")))  # materialize deferred shapes
    mesh = parallel.make_mesh({"dp": 8})
    step = parallel.ParallelTrainStep(
        net, gloss.L2Loss(), mx.optimizer.SGD(learning_rate=0.01), mesh)
    bn = net[1]
    before = bn.running_mean.data().asnumpy().copy()
    xs = onp.random.randn(16, 4).astype("float32") * 3 + 5
    ys = onp.random.randn(16, 2).astype("float32")
    for _ in range(2):
        step(xs, ys)
    step.sync_to_block()
    after = bn.running_mean.data().asnumpy()
    assert not onp.allclose(before, after)


def test_param_format_auto_matches_default():
    """param_format='auto' (XLA-chosen carried-state layouts via AOT
    compile) must train to the same weights as the default layout path."""
    def run(auto):
        onp.random.seed(5)
        mx.random.seed(5)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, in_units=8), nn.BatchNorm(), nn.Dense(4))
        net.initialize()
        net(mx.nd.array(onp.zeros((2, 8), "float32")))
        mesh = parallel.make_mesh({"dp": 8})
        step = parallel.ParallelTrainStep(
            net, gloss.L2Loss(), mx.optimizer.SGD(learning_rate=0.05), mesh,
            param_format="auto" if auto else None)
        xs = onp.random.randn(3, 16, 8).astype("float32")
        ys = onp.random.randn(3, 16, 4).astype("float32")
        losses = step.step_n(xs, ys)          # AOT path
        losses2 = step.step_n(xs, ys)         # steady state (cached compile)
        # single-step interleave + a batch-shape change: both must retrace /
        # re-own the carried state rather than crash or corrupt (r5 review)
        l_single = step(xs[0, :8], ys[0, :8])
        losses3 = step.step_n(xs[:, :8], ys[:, :8])
        step.sync_to_block()
        return (net[0].weight.data().asnumpy(), losses.asnumpy(),
                losses2.asnumpy(), float(l_single.asscalar()),
                losses3.asnumpy())

    w_ref, l_ref, l2_ref, ls_ref, l3_ref = run(False)
    w_auto, l_auto, l2_auto, ls_auto, l3_auto = run(True)
    onp.testing.assert_allclose(l_auto, l_ref, rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(l2_auto, l2_ref, rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(ls_auto, ls_ref, rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(l3_auto, l3_ref, rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(w_auto, w_ref, rtol=1e-5, atol=1e-6)


def test_ring_attention_matches_dense():
    import jax
    import jax.numpy as jnp
    onp.random.seed(2)
    B, H, S, D = 2, 4, 32, 16
    q = jnp.asarray(onp.random.randn(B, H, S, D).astype("float32"))
    k = jnp.asarray(onp.random.randn(B, H, S, D).astype("float32"))
    v = jnp.asarray(onp.random.randn(B, H, S, D).astype("float32"))

    mesh = parallel.make_mesh({"sp": 8})
    out_ring = parallel.ring_self_attention(q, k, v, mesh)

    scale = 1.0 / (D ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    p = jax.nn.softmax(s, axis=-1)
    out_ref = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    onp.testing.assert_allclose(onp.asarray(out_ring), onp.asarray(out_ref),
                                rtol=2e-4, atol=2e-4)


def test_ring_attention_causal():
    import jax
    import jax.numpy as jnp
    onp.random.seed(3)
    B, H, S, D = 1, 2, 16, 8
    q = jnp.asarray(onp.random.randn(B, H, S, D).astype("float32"))
    k = jnp.asarray(onp.random.randn(B, H, S, D).astype("float32"))
    v = jnp.asarray(onp.random.randn(B, H, S, D).astype("float32"))

    mesh = parallel.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    out_ring = parallel.ring_self_attention(q, k, v, mesh, causal=True)

    scale = 1.0 / (D ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    mask = onp.tril(onp.ones((S, S), bool))
    s = jnp.where(jnp.asarray(mask)[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out_ref = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    onp.testing.assert_allclose(onp.asarray(out_ring), onp.asarray(out_ref),
                                rtol=2e-4, atol=2e-4)


def test_step_n_matches_step():
    """K fused steps via lax.scan == K separate step() calls, including an lr
    schedule and Adam's per-step t (deterministic model, no dropout)."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.optimizer import lr_scheduler

    def build():
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
        net.initialize(mx.init.Xavier())
        net(nd.array(onp.zeros((1, 8), "float32")))
        import jax
        mesh = parallel.make_mesh({"dp": 1}, devices=jax.devices()[:1])
        sched = lr_scheduler.FactorScheduler(step=2, factor=0.5)
        opt = mx.optimizer.Adam(learning_rate=0.05, lr_scheduler=sched)
        return net, parallel.ParallelTrainStep(
            net, gloss.SoftmaxCrossEntropyLoss(), opt, mesh)

    rng = onp.random.RandomState(5)
    X = rng.rand(6, 8, 8).astype("float32")
    Y = rng.randint(0, 4, (6, 8)).astype("float32")

    mx.random.seed(11)
    onp.random.seed(11)
    net1, s1 = build()
    losses1 = [float(s1(X[i], Y[i]).asscalar()) for i in range(6)]

    mx.random.seed(11)
    onp.random.seed(11)
    net2, s2 = build()
    losses2 = list(s2.step_n(X[:3], Y[:3]).asnumpy()) + \
        list(s2.step_n(X[3:], Y[3:]).asnumpy())
    onp.testing.assert_allclose(losses1, losses2, rtol=1e-4, atol=1e-5)

    s1.sync_to_block()
    s2.sync_to_block()
    for (n1, p1), (n2, p2) in zip(sorted(net1.collect_params().items()),
                                  sorted(net2.collect_params().items())):
        onp.testing.assert_allclose(p1.data().asnumpy(), p2.data().asnumpy(),
                                    rtol=1e-4, atol=1e-5)
