"""Fleet observability (ISSUE 15): cross-process trace journeys, the fleet
collector, and the goodput ledger.

Covers: the per-pid span spool (flush, rotation, cross-process assembly,
MXNET_TRACE_ID inheritance), histogram merging whose quantiles are exactly
the quantiles of the concatenated observations (through the
``tools/metrics_dump.py`` multi-file path), goodput attribution invariants
(exclusive buckets, reconciliation against wall clock, non-negative idle),
the pooled debug pages (/statusz names every replica), and the acceptance
run: a request served through a 3-replica ServingPool with one autoscale
transition and one warm-restarted subprocess yields ONE ordered journey
from ``tools/trace_journey.py`` naming every process/replica crossed, and
``tools/fleet_report.py`` over the same run renders merged metrics plus a
goodput table whose buckets sum within 1% of wall clock.
"""
import gc
import json
import os
import subprocess
import sys
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, serving, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.telemetry import fleet, goodput
from mxnet_tpu.telemetry import debug_server as dbg
from mxnet_tpu.telemetry import tracing

REPO = os.path.join(os.path.dirname(__file__), os.pardir)


def _tool(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _set_env(pairs):
    """Set env vars, returning the saved values for _restore_env."""
    saved = {k: os.environ.get(k) for k in pairs}
    for k, v in pairs.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = str(v)
    tracing._reset_spool_for_tests()
    return saved


def _restore_env(saved):
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    tracing._reset_spool_for_tests()


def _mlp(seed, in_dim=6, out_dim=3):
    mx.random.seed(seed)
    onp.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(out_dim))
    net.initialize(mx.init.Xavier())
    net(nd.array(onp.zeros((2, in_dim), "float32")))
    return net


class _StubMonitor:
    burn_threshold = 14.0

    def __init__(self):
        self.fast_burn = 0.0
        self.alert = False

    def check_all(self):
        return [{"endpoint": "e", "fast_burn": self.fast_burn,
                 "slow_burn": self.fast_burn, "alert_active": self.alert}]


# ---------------------------------------------------------------------------
# span spool: flush, rotation, journey assembly, trace inheritance
# ---------------------------------------------------------------------------

def test_spool_flush_and_journey_roundtrip(tmp_path):
    spool = str(tmp_path / "spool")
    saved = _set_env({"MXNET_SPAN_SPOOL_DIR": spool})
    try:
        with telemetry.span("t.outer", step=3) as s:
            tid = s.trace_id
            with telemetry.span("t.inner"):
                pass
        telemetry.spool_flush()
    finally:
        _restore_env(saved)
    entries = telemetry.read_spool(spool)
    assert any(e["name"] == "t.outer" for e in entries)
    hops = telemetry.journey(tid, spool)
    assert [h["name"] for h in hops] == ["t.outer", "t.inner"]
    assert all(h["pid"] == os.getpid() for h in hops)
    # ordered by wall-clock start; parent/child linkage survives the spool
    assert hops[0]["t0_wall"] <= hops[1]["t0_wall"]
    assert hops[1]["parent_id"] == hops[0]["span_id"]
    assert hops[0]["attrs"] == {"step": 3}


def test_spool_rotation_under_size_cap(tmp_path):
    spool = str(tmp_path / "spool")
    saved = _set_env({"MXNET_SPAN_SPOOL_DIR": spool,
                      "MXNET_SPAN_SPOOL_MAX_BYTES": "600",
                      "MXNET_SPAN_SPOOL_FLUSH_N": "4"})
    try:
        telemetry.spool_flush()        # refresh the flush cadence knob
        for i in range(24):
            with telemetry.span("t.rot", i=i):
                pass
        telemetry.spool_flush()
        path = tracing.spool_path(spool)
    finally:
        _restore_env(saved)
    assert os.path.exists(path + ".1")           # cap forced a rotation
    # the live file never grows past the cap by more than one batch
    assert os.path.getsize(path) <= 600 + 1024
    # rotated lines still assemble into journeys: read_spool sees the .1
    # generation too (older generations are dropped by design)
    entries = telemetry.read_spool(spool)
    n = sum(1 for e in entries if e["name"] == "t.rot")
    assert 8 <= n <= 24
    in_rotated = 0
    with open(path + ".1") as f:
        in_rotated = sum(1 for _ in f)
    assert in_rotated >= 1


def test_trace_id_env_inheritance():
    saved = _set_env({"MXNET_TRACE_ID": "feedface00000001"})
    try:
        with telemetry.span("t.root_a") as a:
            assert a.trace_id == "feedface00000001"
            with telemetry.span("t.child") as c:
                assert c.trace_id == "feedface00000001"
        # EVERY root span of the process joins the inherited journey
        with telemetry.span("t.root_b") as b:
            assert b.trace_id == "feedface00000001"
        # explicit adoption still wins over inheritance
        with telemetry.span("t.adopted", trace_id="aa55aa55aa55aa55") as s:
            assert s.trace_id == "aa55aa55aa55aa55"
    finally:
        _restore_env(saved)
    with telemetry.span("t.root_c") as s:
        assert s.trace_id != "feedface00000001"


# ---------------------------------------------------------------------------
# cross-replica histogram merging (satellite: metrics_dump multi-file)
# ---------------------------------------------------------------------------

def test_merged_quantiles_equal_concatenated_observations(tmp_path):
    """The correctness pin: merging per-replica histograms by element-wise
    bucket-count sums yields EXACTLY the quantiles a single process would
    report had it observed every sample — proven through the
    tools/metrics_dump.py multi-file path."""
    from mxnet_tpu.telemetry.metrics import MetricsRegistry
    rng = onp.random.RandomState(5)
    obs_a = rng.gamma(2.0, 200.0, 400)
    obs_b = rng.gamma(3.0, 80.0, 250)

    def snap_with(obs):
        reg = MetricsRegistry()
        h = reg.histogram("mxtpu_test_lat_us", "t", labelnames=("endpoint",))
        child = h.labels("e")
        for v in obs:
            child.observe(float(v))
        return reg.snapshot()

    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    with open(pa, "w") as f:
        json.dump(snap_with(obs_a), f)
    with open(pb, "w") as f:
        json.dump(snap_with(obs_b), f)
    ref = snap_with(onp.concatenate([obs_a, obs_b]))
    ref_s = ref["metrics"]["mxtpu_test_lat_us"]["series"][0]

    metrics_dump = _tool("metrics_dump")
    merged = metrics_dump.load_merged([pa, pb])
    fam = merged["metrics"]["mxtpu_test_lat_us"]
    assert fam["label_names"][0] == "replica"
    per_rep = [s for s in fam["series"] if s["labels"]["replica"] != "ALL"]
    assert {s["labels"]["replica"] for s in per_rep} == {"a.json", "b.json"}
    all_row = [s for s in fam["series"]
               if s["labels"]["replica"] == "ALL"][0]
    assert all_row["labels"]["endpoint"] == "e"
    assert all_row["count"] == ref_s["count"]
    assert all_row["bucket_counts"] == ref_s["bucket_counts"]
    for q in ("p50", "p95", "p99"):
        assert all_row[q] == ref_s[q], q       # exact, not approximate
    assert all_row["min"] == ref_s["min"]
    assert all_row["max"] == ref_s["max"]
    assert all_row["sum"] == pytest.approx(ref_s["sum"])
    # the merged view renders through the unchanged single-process table
    table = metrics_dump.render_table(merged)
    assert "replica=ALL" in table


def test_merge_skips_mismatched_bucket_ladders():
    bounds = [1.0, 2.0]
    with pytest.raises(ValueError):
        fleet.merge_histogram_series(
            bounds, [{"bucket_counts": [1, 0, 0], "count": 1, "sum": 1.0,
                      "min": 1.0, "max": 1.0},
                     {"bucket_counts": [1, 0], "count": 1, "sum": 1.0,
                      "min": 1.0, "max": 1.0}])
    # merge_snapshots keeps per-replica rows but skips the ALL rollup
    fam = {"type": "histogram", "help": "", "label_names": [],
           "bucket_bounds": bounds}
    sa = {"metrics": {"mxtpu_test_m": dict(
        fam, series=[{"labels": {}, "count": 1, "sum": 1.0, "mean": 1.0,
                      "min": 1.0, "max": 1.0, "p50": 1, "p95": 1, "p99": 1,
                      "bucket_counts": [1, 0, 0]}])}}
    sb = {"metrics": {"mxtpu_test_m": dict(
        fam, bucket_bounds=[5.0, 9.0, 11.0], series=[
            {"labels": {}, "count": 1, "sum": 5.0, "mean": 5.0,
             "min": 5.0, "max": 5.0, "p50": 5, "p95": 5, "p99": 5,
             "bucket_counts": [1, 0, 0, 0]}])}}
    merged = fleet.merge_snapshots({"a": sa, "b": sb})
    series = merged["metrics"]["mxtpu_test_m"]["series"]
    assert {s["labels"]["replica"] for s in series} == {"a", "b"}  # no ALL


# ---------------------------------------------------------------------------
# goodput ledger invariants (satellite d)
# ---------------------------------------------------------------------------

def _synthetic_snap():
    return {"metrics": {
        "mxtpu_compile_wall_seconds_total": {
            "type": "counter", "series": [
                {"labels": {"site": "s", "phase": "p"}, "value": 1.5}]},
        "mxtpu_dataloader_wait_us": {
            "type": "histogram", "series": [
                {"labels": {}, "sum": 0.5e6, "count": 10}]},
        "mxtpu_train_step_latency_us": {
            "type": "histogram", "series": [
                {"labels": {}, "sum": 2.0e6, "count": 100}]},
        "mxtpu_checkpoint_save_duration_us": {
            "type": "histogram", "series": [
                {"labels": {}, "sum": 0.25e6, "count": 2}]},
        "mxtpu_span_duration_us": {
            "type": "histogram", "series": [
                {"labels": {"name": "checkpoint.restore"},
                 "sum": 0.2e6, "count": 1},
                {"labels": {"name": "serving.drain"},
                 "sum": 0.1e6, "count": 1},
                # step spans must NOT double-count into any bucket: the
                # step bucket reads the step-latency histograms only
                {"labels": {"name": "train.step"},
                 "sum": 123e6, "count": 1}]},
    }}


def test_goodput_buckets_exclusive_and_sum_to_wall():
    b = goodput.attribute(_synthetic_snap(), 10.0)
    assert set(b) == set(goodput.BUCKETS)
    assert b["compile"] == 1.5
    assert b["data_wait"] == pytest.approx(0.5)
    assert b["step"] == pytest.approx(2.0)          # not 125.0: exclusive
    assert b["checkpoint_flush"] == pytest.approx(0.25)
    assert b["retry_recovery"] == pytest.approx(0.2)
    assert b["drain"] == pytest.approx(0.1)
    assert b["idle"] == pytest.approx(10.0 - 4.55)
    assert sum(b.values()) == pytest.approx(10.0, rel=1e-9)


def test_goodput_idle_never_negative_rescales_overlap():
    # overlapped threads booked 4.55 active seconds into a 2 s wall window:
    # every active bucket scales down proportionally, idle clamps at 0
    b = goodput.attribute(_synthetic_snap(), 2.0)
    assert sum(b.values()) == pytest.approx(2.0, rel=1e-9)
    assert b["idle"] == 0.0
    assert all(v >= 0.0 for v in b.values())
    assert b["step"] / b["compile"] == pytest.approx(2.0 / 1.5)
    # no wall anchor: active buckets only, idle reports 0
    b3 = goodput.attribute(_synthetic_snap(), None)
    assert b3["idle"] == 0.0 and b3["step"] == pytest.approx(2.0)


def test_goodput_account_reconciles_live_run():
    """Scripted live run: the published counter series must sum to the
    published wall gauge within 1%."""
    goodput.reset()
    with telemetry.span("checkpoint.restore"):
        time.sleep(0.02)
    time.sleep(0.01)
    buckets = goodput.account()
    # the restore span lands in retry_recovery; its absolute share depends
    # on the registry's cumulative history (proportional rescale), so pin
    # presence, not magnitude
    assert buckets["retry_recovery"] > 0.0
    assert buckets["idle"] >= 0.0
    snap = telemetry.snapshot()
    fam = snap["metrics"]["mxtpu_goodput_seconds_total"]
    total = sum(s["value"] for s in fam["series"])
    wall = snap["metrics"]["mxtpu_goodput_wall_seconds"]["series"][0]["value"]
    assert wall > 0.0
    assert abs(total - wall) <= 0.01 * wall
    # repeated accounting stays reconciled (monotone counter, fresh deltas)
    time.sleep(0.01)
    goodput.account()
    snap = telemetry.snapshot()
    fam = snap["metrics"]["mxtpu_goodput_seconds_total"]
    total = sum(s["value"] for s in fam["series"])
    wall = snap["metrics"]["mxtpu_goodput_wall_seconds"]["series"][0]["value"]
    assert abs(total - wall) <= 0.01 * wall


# ---------------------------------------------------------------------------
# pooled debug pages (satellite a)
# ---------------------------------------------------------------------------

def _clear_attachments():
    for p in dbg.attached_pools():
        dbg.detach_pool(p)
    for a in dbg.attached_autoscalers():
        dbg.detach_autoscaler(a)
    for s in dbg.attached_servers():
        dbg.detach(s)
    gc.collect()


def test_pooled_statusz_names_every_replica():
    _clear_attachments()
    name = "t_statusz_ep"

    def factory(rid):
        srv = serving.InferenceServer(batch_timeout_ms=1.0, max_queue=64)
        srv.register(serving.ModelEndpoint(
            name, _mlp(11), input_shapes=(6,), max_batch_size=4))
        return srv

    pool = serving.ServingPool(factory, initial_replicas=3)
    mon = _StubMonitor()
    asc = serving.Autoscaler(pool, monitor=mon, min_replicas=1,
                             max_replicas=3, up_n=2, down_n=3,
                             cooldown_s=5.0, queue_high=0.9, queue_low=0.5)
    try:
        page = dbg.statusz()
        assert "== serving pool ==" in page
        assert "pool: replicas=3" in page
        for rid in (0, 1, 2):                  # every replica named
            assert f"replica {rid}: state=running" in page
        assert "autoscaler: replicas [1..3]" in page
        assert "over_polls=0/2" in page and "idle_polls=0/3" in page
        assert "cooldown=no" in page and "cooldown_s=5.0" in page
        code, body = dbg.healthz()
        assert code == 200 and body["ok"]
        assert any(p.get("replicas") == 3
                   and sorted(p.get("rotation", [])) == [0, 1, 2]
                   for p in body.get("pools", []))
        # a transition shows up in the autoscaler section
        mon.alert = True
        asc.tick(now=0.0)
        act = asc.tick(now=1.0)
        assert act is None and pool.size() == 3   # already at max: no-op
    finally:
        pool.stop(drain=True)
        serving.unregister(name)
        _clear_attachments()


def test_fleetz_page_is_json_and_carries_goodput():
    doc = dbg.fleetz()
    json.dumps(doc)                                # must be serializable
    assert doc["processes"] >= 1
    assert "merged" in doc and "health" in doc
    assert set(doc["goodput"]["buckets"]) == set(goodput.BUCKETS)
    assert doc["health"]["status"] in ("ok", "degraded", "down")
    assert isinstance(doc["utilization"], list)


# ---------------------------------------------------------------------------
# acceptance: pooled run + warm restart -> one journey + fleet report
# ---------------------------------------------------------------------------

# the warm-restarted process: rebuilds the endpoint against the SHARED
# executable cache the pool replicas populated, serves one request, and
# leaves its snapshot dump + span-spool lines for the fleet tools
_RESTART_CHILD_SRC = """\
import os
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import nd, serving, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.telemetry import goodput

mx.random.seed(11); onp.random.seed(11)
net = nn.HybridSequential()
with net.name_scope():
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
net.initialize(mx.init.Xavier())
net(nd.array(onp.zeros((2, 6), "float32")))
srv = serving.InferenceServer(batch_timeout_ms=1.0)
srv.register(serving.ModelEndpoint("t_fleet_ep", net, input_shapes=(6,),
                                   max_batch_size=4))
srv.start()
x = onp.ones((1, 6), "float32")
srv.submit("t_fleet_ep", x).result(timeout=60)
srv.stop()
serving.unregister("t_fleet_ep")
goodput.account()
telemetry.dump(os.environ["FLEET_DUMP"])
telemetry.spool_flush()
"""


@pytest.mark.filterwarnings("ignore")
def test_fleet_acceptance_journey_and_report(tmp_path, capsys):
    spool = str(tmp_path / "spool")
    cache = str(tmp_path / "xcache")
    dumps = tmp_path / "dumps"
    dumps.mkdir()
    name = "t_fleet_ep"
    tid = telemetry.new_trace_id()
    saved = _set_env({"MXNET_SPAN_SPOOL_DIR": spool,
                      "MXNET_TRACE_ID": tid,
                      "MXNET_EXEC_CACHE_DIR": cache})
    goodput.reset()
    nets = {}

    def factory(rid):
        srv = serving.InferenceServer(batch_timeout_ms=20.0, max_queue=64)
        net = _mlp(11)
        nets[rid] = net
        srv.register(serving.ModelEndpoint(
            name, net, input_shapes=(6,), max_batch_size=4))
        return srv

    try:
        pool = serving.ServingPool(factory, initial_replicas=2)
        mon = _StubMonitor()
        asc = serving.Autoscaler(pool, monitor=mon, min_replicas=1,
                                 max_replicas=3, up_n=2, down_n=3,
                                 cooldown_s=0.0, queue_high=0.9,
                                 queue_low=0.5)
        try:
            # one autoscale transition: 2 -> 3 replicas under synthetic burn
            mon.alert = True
            mon.fast_burn = 20.0
            asc.tick(now=0.0)
            act = asc.tick(now=1.0)
            assert act and act["action"] == "up" and pool.size() == 3
            # a burst of requests: least-loaded routing spreads them across
            # replicas (each submit parks rows in a replica's batch queue)
            xs = onp.random.RandomState(3).randn(12, 6).astype("float32")
            futs = [pool.submit(name, xs[i]) for i in range(12)]
            outs = [f.result(timeout=60).asnumpy() for f in futs]
            direct = nets[0](nd.array(xs)).asnumpy()
            assert all(onp.array_equal(o, direct[i])
                       for i, o in enumerate(outs))
        finally:
            pool.stop(drain=True)
            serving.unregister(name)
        # one warm restart: a REAL subprocess sharing the executable cache,
        # inheriting the trace id + spool dir from the environment
        env = dict(os.environ)
        env["FLEET_DUMP"] = str(dumps / "child.json")
        child = subprocess.run([sys.executable, "-c", _RESTART_CHILD_SRC],
                               env=env, capture_output=True, text=True)
        assert child.returncode == 0, child.stderr[-2000:]
        telemetry.spool_flush()
        goodput.account()
        parent_dump = str(dumps / "parent.json")
        telemetry.dump(parent_dump)
    finally:
        _restore_env(saved)

    # -- tools/trace_journey.py: ONE ordered timeline across processes -----
    trace_journey = _tool("trace_journey")
    assert trace_journey.main([spool, "--trace", tid, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    hops, procs = doc["hops"], doc["processes"]
    walls = [h["t0_wall"] for h in hops]
    assert walls == sorted(walls)              # a single ordered timeline
    pids = {p for p in procs if p.startswith("pid=")}
    reps = {p for p in procs if p.startswith("replica=")}
    assert f"pid={os.getpid()}" in pids
    assert len(pids) == 2                      # parent + warm-restart child
    assert len(reps) >= 2                      # burst crossed >=2 replicas
    # ... and names exactly the replicas the routed submits touched
    served = {(h.get("attrs") or {}).get("replica") for h in hops
              if h["name"] == "pool.submit"}
    assert reps == {f"replica={r}" for r in served}
    # the human rendering names every hop
    assert trace_journey.main([spool, "--trace", tid]) == 0
    rendered = capsys.readouterr().out
    for p in sorted(pids | reps):
        assert p in rendered

    # -- tools/fleet_report.py over the same run ---------------------------
    fleet_report = _tool("fleet_report")
    paths = [str(dumps / "child.json"), parent_dump]
    report = fleet_report.build_report(paths, spool_dir=spool, trace=tid)
    # goodput: every process's buckets sum within 1% of its wall clock
    assert report["goodput_ok"]
    for label, gp in report["goodput"].items():
        assert gp["wall_s"] is not None and gp["wall_s"] > 0.0, label
        assert abs(gp["sum_s"] - gp["wall_s"]) <= 0.01 * gp["wall_s"], label
    # merged metrics: per-replica series + exact ALL rollups render
    fam = report["merged"]["metrics"]["mxtpu_span_duration_us"]
    assert any(s["labels"].get("replica") == "ALL" for s in fam["series"])
    assert report["journey"]["processes"] == procs
    # CLI end-to-end: --verify holds the 1% reconciliation
    rc = fleet_report.main(paths + ["--spool-dir", spool, "--trace", tid,
                                    "--verify"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "goodput ledger" in out and "trace journey" in out
    assert "MISMATCH" not in out
