"""CPU<->TPU cross-context oracle (VERDICT r3 weak #6).

The reference's portability trick is running the same op suite under a second
context and comparing (tests/python/gpu/test_operator_gpu.py re-imports the
whole CPU suite; python/mxnet/test_utils.py:1428 check_consistency). Here the
second context is the real accelerator: every case below runs the op on
mx.cpu(0) and mx.tpu(0) with the SAME host inputs and compares outputs and
input gradients at tolerance — catching TPU-lowering-specific numerics the
same-backend jax.grad/numeric oracles cannot see.

Under the CI conftest (forced single-platform CPU) these tests skip; run them
on the TPU host via tools/cross_context_check.py, which also re-executes the
full breadth + numeric-gradient families under the TPU default context.
"""
import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_consistency

_HAS_ACCEL = any(d.platform != "cpu" for d in jax.devices())

pytestmark = pytest.mark.skipif(
    not _HAS_ACCEL, reason="needs a real accelerator next to the CPU backend "
                           "(run via tools/cross_context_check.py)")

RNG = onp.random.RandomState(11)

# f32 TPU matmul/conv use bf16-ish passes unless told otherwise; tolerances
# sized for highest-precision available comparisons
RTOL, ATOL = 2e-2, 2e-2


def _ctxs():
    return [mx.cpu(0), mx.tpu(0)]


CASES = [
    ("exp", lambda x: nd.exp(x), [(4, 5)]),
    ("sigmoid", lambda x: nd.sigmoid(x), [(4, 5)]),
    ("tanh", lambda x: nd.tanh(x), [(4, 5)]),
    ("softmax", lambda x: nd.softmax(x, axis=-1), [(4, 16)]),
    ("log_softmax", lambda x: nd.log_softmax(x, axis=-1), [(4, 16)]),
    ("erf", lambda x: nd.erf(x), [(4, 5)]),
    ("gelu", lambda x: nd.LeakyReLU(x, act_type="gelu"), [(4, 5)]),
    ("sum_axis", lambda x: nd.sum(x, axis=1), [(4, 5)]),
    ("mean", lambda x: nd.mean(x), [(6, 6)]),
    ("norm", lambda x: nd.norm(x), [(6, 6)]),
    ("dot", lambda a, b: nd.dot(a, b), [(8, 16), (16, 8)]),
    ("batch_dot", lambda a, b: nd.batch_dot(a, b), [(3, 4, 5), (3, 5, 6)]),
    ("add_bcast", lambda a, b: nd.broadcast_add(a, b), [(4, 5), (1, 5)]),
    ("mul", lambda a, b: a * b, [(4, 5), (4, 5)]),
    ("div", lambda a, b: a / (b + 2.0), [(4, 5), (4, 5)]),
    ("transpose", lambda x: nd.transpose(x, axes=(1, 0)), [(4, 5)]),
    ("slice", lambda x: nd.slice(x, begin=(1, 1), end=(3, 4)), [(4, 5)]),
    ("take", None, None),  # placeholder replaced below (int inputs)
    ("layernorm",
     lambda x, g, b: nd.LayerNorm(x, g, b, axis=-1), [(4, 16), (16,), (16,)]),
    ("fullyconnected",
     lambda x, w, b: nd.FullyConnected(x, w, b, num_hidden=8),
     [(4, 16), (8, 16), (8,)]),
    ("convolution",
     lambda x, w, b: nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4,
                                    pad=(1, 1)),
     [(2, 3, 8, 8), (4, 3, 3, 3), (4,)]),
    ("pooling",
     lambda x: nd.Pooling(x, kernel=(2, 2), pool_type="max", stride=(2, 2)),
     [(2, 3, 8, 8)]),
]
CASES = [c for c in CASES if c[1] is not None]


@pytest.mark.parametrize("name,fn,shapes", CASES, ids=[c[0] for c in CASES])
def test_forward_backward_cross_context(name, fn, shapes):
    inputs = [(RNG.rand(*s).astype("float32") - 0.3) for s in shapes]
    check_consistency(fn, inputs, _ctxs(), rtol=RTOL, atol=ATOL, grad=True)


def test_take_cross_context():
    data = RNG.rand(16, 4).astype("float32")
    idx = RNG.randint(0, 16, (6,)).astype("int32")
    check_consistency(lambda d, i: nd.take(d, i), [data, idx], _ctxs(),
                      rtol=RTOL, atol=ATOL)


def test_reductions_and_sorting_cross_context():
    x = RNG.rand(8, 32).astype("float32")
    check_consistency(lambda a: nd.sort(a, axis=-1), [x], _ctxs(),
                      rtol=RTOL, atol=ATOL)
    check_consistency(lambda a: nd.topk(a, k=5, ret_typ="value"), [x], _ctxs(),
                      rtol=RTOL, atol=ATOL)


def test_batchnorm_train_cross_context():
    x = RNG.rand(4, 6, 5, 5).astype("float32")
    gamma = onp.ones((6,), "float32")
    beta = onp.zeros((6,), "float32")
    mean = onp.zeros((6,), "float32")
    var = onp.ones((6,), "float32")

    def bn(x_, g, b, m, v):
        from mxnet_tpu import autograd
        with autograd.train_mode():
            return nd.BatchNorm(x_, g, b, m, v)

    check_consistency(bn, [x, gamma, beta, mean, var], _ctxs(),
                      rtol=RTOL, atol=ATOL)
