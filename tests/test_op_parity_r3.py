"""Round-3 operator-parity batch: legacy regression heads, STE ops,
mrcnn_mask_target, constraint_check, the nd.image namespace
(src/operator/image/), sparse square_sum/cast_storage surface, boolean-mask
indexing, np.random distribution breadth and array-parameter samplers
(multisample_op.cc)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.numpy as mnp
from mxnet_tpu import sparse


# ---------------------------------------------------------------------------
# legacy regression output heads (regression_output.cc)
# ---------------------------------------------------------------------------
def test_linear_regression_output():
    data = mx.nd.array(onp.array([[1., 2.], [3., 4.]], "float32"))
    label = mx.nd.array(onp.array([[0., 1.], [1., 2.]], "float32"))
    data.attach_grad()
    with mx.autograd.record():
        out = mx.nd.LinearRegressionOutput(data, label, grad_scale=2.0)
    out.backward()
    assert onp.allclose(out.asnumpy(), data.asnumpy())
    # dx = (data - label) * grad_scale / num_output, num_output = 2
    assert onp.allclose(data.grad.asnumpy(),
                        (data.asnumpy() - label.asnumpy()) * 2.0 / 2)


def test_logistic_regression_output():
    data = mx.nd.array(onp.array([[0.5, -0.5]], "float32"))
    label = mx.nd.array(onp.array([[1., 0.]], "float32"))
    data.attach_grad()
    with mx.autograd.record():
        out = mx.nd.LogisticRegressionOutput(data, label)
    out.backward()
    sig = 1 / (1 + onp.exp(-data.asnumpy()))
    assert onp.allclose(out.asnumpy(), sig, atol=1e-6)
    assert onp.allclose(data.grad.asnumpy(), (sig - label.asnumpy()) / 2,
                        atol=1e-6)


def test_mae_regression_output():
    data = mx.nd.array(onp.array([[3., -1.]], "float32"))
    label = mx.nd.array(onp.array([[1., 1.]], "float32"))
    data.attach_grad()
    with mx.autograd.record():
        out = mx.nd.MAERegressionOutput(data, label)
    out.backward()
    assert onp.allclose(data.grad.asnumpy(), onp.array([[1., -1.]]) / 2)


def test_regression_output_1d_label():
    # (B, 1) data with (B,) label (RegressionOpShape special case)
    data = mx.nd.array(onp.array([[1.], [2.]], "float32"))
    label = mx.nd.array(onp.array([0., 1.], "float32"))
    data.attach_grad()
    with mx.autograd.record():
        out = mx.nd.LinearRegressionOutput(data, label)
    out.backward()
    assert onp.allclose(data.grad.asnumpy(), onp.array([[1.], [1.]]))


# ---------------------------------------------------------------------------
# straight-through estimators (contrib/stes_op.cc)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("op,fwd", [("round_ste", onp.round),
                                    ("sign_ste", onp.sign)])
def test_ste(op, fwd):
    x = mx.nd.array(onp.array([-1.6, -0.4, 0.4, 1.6], "float32"))
    x.attach_grad()
    with mx.autograd.record():
        y = getattr(mx.nd.contrib, op)(x)
        loss = (y * mx.nd.array(onp.array([1., 2., 3., 4.], "float32"))).sum()
    loss.backward()
    assert onp.allclose(y.asnumpy(), fwd(x.asnumpy()))
    # straight-through: gradient passes unchanged
    assert onp.allclose(x.grad.asnumpy(), [1., 2., 3., 4.])


# ---------------------------------------------------------------------------
# constraint_check (numpy/np_constraint_check.cc)
# ---------------------------------------------------------------------------
def test_constraint_check():
    from mxnet_tpu.ops.registry import apply_op
    ok = apply_op("_npx_constraint_check", mx.nd.array(onp.ones(3, "float32")))
    assert bool(ok.asnumpy())
    with pytest.raises(mx.base.MXNetError, match="positive"):
        apply_op("_npx_constraint_check",
                 mx.nd.array(onp.array([1., 0.], "float32")),
                 msg="must be positive")


# ---------------------------------------------------------------------------
# mrcnn_mask_target (contrib/mrcnn_mask_target-inl.h)
# ---------------------------------------------------------------------------
def test_mrcnn_mask_target():
    rng = onp.random.RandomState(0)
    B, N, M, C, MS = 2, 3, 4, 5, 7
    rois = onp.zeros((B, N, 4), "float32")
    rois[..., 2:] = 16.0  # all ROIs cover [0,16)^2
    gt_masks = rng.rand(B, M, 32, 32).astype("float32")
    matches = rng.randint(0, M, (B, N)).astype("float32")
    cls = rng.randint(0, C, (B, N)).astype("float32")
    mt, mc = mx.nd.contrib.mrcnn_mask_target(
        mx.nd.array(rois), mx.nd.array(gt_masks), mx.nd.array(matches),
        mx.nd.array(cls), num_rois=N, num_classes=C, mask_size=(MS, MS))
    assert mt.shape == (B, N, C, MS, MS)
    assert mc.shape == (B, N, C, MS, MS)
    mcn = mc.asnumpy()
    for b in range(B):
        for n in range(N):
            for c in range(C):
                expect = 1.0 if c == int(cls[b, n]) else 0.0
                assert (mcn[b, n, c] == expect).all()
    # sampled masks are identical across the class axis and within [0, 1]
    mtn = mt.asnumpy()
    assert onp.allclose(mtn, mtn[:, :, :1])
    assert mtn.min() >= 0.0 and mtn.max() <= 1.0


# ---------------------------------------------------------------------------
# nd.image namespace (image_random.cc, resize.cc, crop.cc)
# ---------------------------------------------------------------------------
class TestImageOps:
    img = (onp.random.RandomState(0).rand(8, 6, 3) * 255).astype("float32")

    def test_to_tensor(self):
        t = mx.nd.image.to_tensor(mx.nd.array(self.img))
        assert t.shape == (3, 8, 6)
        assert onp.allclose(t.asnumpy(),
                            self.img.transpose(2, 0, 1) / 255.0, atol=1e-6)

    def test_to_tensor_batched(self):
        b = onp.stack([self.img, self.img])
        t = mx.nd.image.to_tensor(mx.nd.array(b))
        assert t.shape == (2, 3, 8, 6)

    def test_normalize(self):
        t = mx.nd.image.to_tensor(mx.nd.array(self.img))
        n = mx.nd.image.normalize(t, mean=(0.5, 0.5, 0.5), std=(0.1, 0.2, 0.3))
        exp = (self.img.transpose(2, 0, 1) / 255.0 - 0.5) / \
            onp.array([0.1, 0.2, 0.3]).reshape(3, 1, 1)
        assert onp.allclose(n.asnumpy(), exp, atol=1e-5)

    def test_resize_and_crop(self):
        r = mx.nd.image.resize(mx.nd.array(self.img), (4, 5))
        assert r.shape == (5, 4, 3)  # size=(w,h) -> (h,w,c)
        c = mx.nd.image.crop(mx.nd.array(self.img), x=1, y=2, width=3, height=4)
        assert onp.allclose(c.asnumpy(), self.img[2:6, 1:4])

    def test_flips(self):
        a = mx.nd.array(self.img)
        assert onp.allclose(mx.nd.image.flip_left_right(a).asnumpy(),
                            self.img[:, ::-1])
        assert onp.allclose(mx.nd.image.flip_top_bottom(a).asnumpy(),
                            self.img[::-1])
        rf = mx.nd.image.random_flip_left_right(a).asnumpy()
        assert onp.allclose(rf, self.img) or onp.allclose(rf, self.img[:, ::-1])

    def test_random_brightness_range(self):
        mx.random.seed(7)
        out = mx.nd.image.random_brightness(mx.nd.array(self.img), 0.5, 0.5)
        assert onp.allclose(out.asnumpy(), self.img * 0.5, atol=1e-4)

    def test_random_contrast_preserves_mean_gray(self):
        out = mx.nd.image.random_contrast(mx.nd.array(self.img), 1.0, 1.0)
        assert onp.allclose(out.asnumpy(), self.img, atol=1e-4)

    def test_saturation_gray_identity(self):
        # alpha=0 collapses to per-pixel gray replicated across channels
        out = mx.nd.image.random_saturation(mx.nd.array(self.img), 0.0, 0.0)
        o = out.asnumpy()
        assert onp.allclose(o[..., 0], o[..., 1], atol=1e-4)
        assert onp.allclose(o[..., 1], o[..., 2], atol=1e-4)

    def test_hue_zero_is_identity(self):
        # the published YIQ matrices round-trip to identity only to ~3 decimal
        # places (≤0.72 absolute on a 0-255 scale), same as the reference's
        out = mx.nd.image.random_hue(mx.nd.array(self.img), 0.0, 0.0)
        assert onp.abs(out.asnumpy() - self.img).max() < 0.75

    def test_color_jitter_and_lighting(self):
        out = mx.nd.image.random_color_jitter(mx.nd.array(self.img),
                                              0.4, 0.4, 0.4, 0.1)
        assert out.shape == self.img.shape
        al = mx.nd.image.adjust_lighting(mx.nd.array(self.img), (0., 0., 0.))
        assert onp.allclose(al.asnumpy(), self.img)
        rl = mx.nd.image.random_lighting(mx.nd.array(self.img), 0.05)
        assert rl.shape == self.img.shape


# ---------------------------------------------------------------------------
# sparse surface: square_sum, nd-level cast_storage
# ---------------------------------------------------------------------------
def test_square_sum_row_sparse():
    d = onp.zeros((6, 3), "float32")
    d[1] = [1, 2, 3]
    d[4] = [2, 0, 1]
    rsp = mx.nd.cast_storage(mx.nd.array(d), "row_sparse")
    assert float(sparse.square_sum(rsp).asnumpy()) == (d ** 2).sum()
    assert onp.allclose(sparse.square_sum(rsp, axis=1).asnumpy(),
                        (d ** 2).sum(1))
    assert onp.allclose(sparse.square_sum(rsp, axis=0).asnumpy(),
                        (d ** 2).sum(0))
    assert sparse.square_sum(rsp, axis=1, keepdims=True).shape == (6, 1)
    # dense input path
    assert onp.allclose(sparse.square_sum(mx.nd.array(d)).asnumpy(),
                        (d ** 2).sum())


# ---------------------------------------------------------------------------
# boolean-mask indexing on the np frontend (_npi_boolean_mask_assign_*)
# ---------------------------------------------------------------------------
def test_boolean_mask_getitem():
    b = mnp.array([1., 2., 3., 4.])
    assert onp.allclose(b[b > 2].asnumpy(), [3., 4.])


def test_boolean_mask_setitem_scalar():
    a = mnp.array([[1., 2.], [3., 4.]])
    a[a > 2] = 0.0
    assert onp.allclose(a.asnumpy(), [[1., 2.], [0., 0.]])


def test_boolean_mask_setitem_vector():
    b = mnp.array([1., 2., 3., 4.])
    b[b > 2] = mnp.array([9., 10.])
    assert onp.allclose(b.asnumpy(), [1., 2., 9., 10.])


def test_integer_fancy_index_unaffected():
    c = mnp.array([1., 2., 3.])
    idx = mnp.array([0, 2]).astype("int32")
    assert onp.allclose(c[idx].asnumpy(), [1., 3.])


# ---------------------------------------------------------------------------
# np.random distribution breadth
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("name,kwargs,mean,tol", [
    ("bernoulli", dict(prob=0.3), 0.3, 0.02),
    ("gumbel", {}, 0.5772, 0.05),
    ("laplace", {}, 0.0, 0.05),
    ("logistic", {}, 0.0, 0.08),
    ("pareto", dict(a=3.0), 0.5, 0.05),
    ("rayleigh", {}, onp.sqrt(onp.pi / 2), 0.05),
    ("weibull", dict(a=2.0), 0.8862, 0.03),
    ("beta", dict(a=2.0, b=3.0), 0.4, 0.02),
    ("chisquare", dict(df=4.0), 4.0, 0.12),
    ("f", dict(dfnum=5.0, dfden=10.0), 1.25, 0.12),
    ("power", dict(a=3.0), 0.75, 0.02),
    ("lognormal", {}, onp.exp(0.5), 0.1),
    ("triangular", dict(left=0., mode=1., right=2.), 1.0, 0.05),
])
def test_np_random_distribution(name, kwargs, mean, tol):
    mnp.random.seed(42)
    out = getattr(mnp.random, name)(size=(20000,), **kwargs)
    assert out.shape == (20000,)
    assert abs(float(out.asnumpy().mean()) - mean) < 3 * tol + tol


def test_np_random_multivariate_normal():
    mnp.random.seed(0)
    mv = mnp.random.multivariate_normal(
        mnp.array([0., 5.]), mnp.array([[1., 0.], [0., 1.]]), size=(2000,))
    assert mv.shape == (2000, 2)
    assert onp.allclose(mv.asnumpy().mean(0), [0., 5.], atol=0.15)


# ---------------------------------------------------------------------------
# array-parameter samplers (multisample_op.cc)
# ---------------------------------------------------------------------------
def test_sample_uniform_array_params():
    mx.random.seed(3)
    low = mx.nd.array(onp.array([0., 10.], "float32"))
    high = mx.nd.array(onp.array([1., 20.], "float32"))
    s = mx.nd.sample_uniform(low, high, shape=(4000,))
    assert s.shape == (2, 4000)
    m = s.asnumpy()
    assert abs(m[0].mean() - 0.5) < 0.05 and abs(m[1].mean() - 15.0) < 0.5
    assert m[0].min() >= 0.0 and m[0].max() <= 1.0
    assert m[1].min() >= 10.0 and m[1].max() <= 20.0


def test_sample_normal_keeps_param_shape():
    mu = mx.nd.array(onp.array([[0.], [5.]], "float32"))
    sg = mx.nd.array(onp.array([[1.], [2.]], "float32"))
    s = mx.nd.sample_normal(mu, sg, shape=(3000,))
    assert s.shape == (2, 1, 3000)
    m = s.asnumpy()
    assert abs(m[0].mean()) < 0.15 and abs(m[1].mean() - 5.0) < 0.25


def test_sample_poisson_gamma_exponential():
    mx.random.seed(11)
    lam = mx.nd.array(onp.array([1., 8.], "float32"))
    sp = mx.nd.sample_poisson(lam, shape=(3000,)).asnumpy()
    assert abs(sp[0].mean() - 1.0) < 0.15 and abs(sp[1].mean() - 8.0) < 0.4
    a = mx.nd.array(onp.array([2.0], "float32"))
    b = mx.nd.array(onp.array([3.0], "float32"))
    sg = mx.nd.sample_gamma(a, b, shape=(3000,)).asnumpy()
    assert abs(sg.mean() - 6.0) < 0.5
    se = mx.nd.sample_exponential(mx.nd.array(onp.array([4.0], "float32")),
                                  shape=(3000,)).asnumpy()
    assert abs(se.mean() - 0.25) < 0.05


def test_sample_negative_binomials():
    mx.random.seed(5)
    k = mx.nd.array(onp.array([3.0], "float32"))
    p = mx.nd.array(onp.array([0.4], "float32"))
    s = mx.nd.sample_negative_binomial(k, p, shape=(5000,)).asnumpy()
    assert abs(s.mean() - 3.0 * 0.6 / 0.4) < 0.5
    mu = mx.nd.array(onp.array([2.0], "float32"))
    alpha = mx.nd.array(onp.array([0.5], "float32"))
    g = mx.nd.sample_generalized_negative_binomial(
        mu, alpha, shape=(8000,)).asnumpy()
    assert abs(g.mean() - 2.0) < 0.25
    # variance of GNB: mu + alpha * mu^2 = 2 + 0.5*4 = 4
    assert abs(g.var() - 4.0) < 0.8


# hawkesll spelling alias
def test_hawkesll_alias():
    from mxnet_tpu.ops.registry import get_op
    assert get_op("_contrib_hawkesll") is not None
    assert get_op("_contrib_hawkes_ll") is not None


def test_boolean_mask_setitem_rowmajor_not_broadcast():
    # (2,2) with mask hitting (0,1) and (1,0): value vector must fill in
    # row-major order, NOT via a where-broadcast across rows
    a = mnp.array([[1., 4.], [5., 2.]])
    a[a > 2] = mnp.array([9., 10.])
    assert onp.allclose(a.asnumpy(), [[1., 9.], [10., 2.]])


def test_float_gather_index_not_hijacked_as_mask():
    # same-shaped float index with values outside {0,1} is a gather
    x = mx.nd.array(onp.array([10., 20., 30.], "float32"))
    idx = mx.nd.array(onp.array([0., 2., 1.], "float32"))
    assert onp.allclose(x[idx].asnumpy(), [10., 30., 20.])


def test_resize_keep_ratio_short_edge():
    img = onp.zeros((100, 200, 3), "float32")
    out = mx.nd.image.resize(mx.nd.array(img), size=50, keep_ratio=True)
    assert out.shape == (50, 100, 3)
    out = mx.nd.image.resize(mx.nd.array(onp.zeros((200, 100, 3), "float32")),
                             size=50, keep_ratio=True)
    assert out.shape == (100, 50, 3)


def test_mrcnn_requires_num_classes():
    with pytest.raises((ValueError, mx.base.MXNetError)):
        mx.nd.contrib.mrcnn_mask_target(
            mx.nd.array(onp.zeros((1, 1, 4), "float32")),
            mx.nd.array(onp.zeros((1, 1, 8, 8), "float32")),
            mx.nd.array(onp.zeros((1, 1), "float32")),
            mx.nd.array(onp.zeros((1, 1), "float32")),
            num_rois=1, mask_size=(7, 7))


def test_binary_float_index_is_take_not_mask():
    # untagged 0/1-valued float index array must still gather
    x = mx.nd.array(onp.array([10., 20., 30.], "float32"))
    idx = mx.nd.array(onp.array([0., 1., 1.], "float32"))
    assert onp.allclose(x[idx].asnumpy(), [10., 20., 20.])


def test_combined_predicate_mask():
    # & | ~ keep the predicate tag so compound masks index correctly
    a = mnp.array([1., 2., 3., 4.])
    sel = (a > 1) & (a < 4)
    assert onp.allclose(a[sel].asnumpy(), [2., 3.])
    sel2 = (a < 2) | (a > 3)
    assert onp.allclose(a[sel2].asnumpy(), [1., 4.])
    assert onp.allclose(a[~sel].asnumpy(), [1., 4.])


def test_random_contrast_per_image_mean():
    # batched contrast must use each image's own gray mean
    lo = onp.full((4, 4, 3), 10.0, "float32")
    hi = onp.full((4, 4, 3), 200.0, "float32")
    solo = mx.nd.image.random_contrast(mx.nd.array(lo), 0.5, 0.5).asnumpy()
    batched = mx.nd.image.random_contrast(
        mx.nd.array(onp.stack([lo, hi])), 0.5, 0.5).asnumpy()
    assert onp.allclose(batched[0], solo, atol=1e-4)
    assert onp.allclose(batched[1], hi, atol=1e-3)  # 0.5*200 + 0.5*200


def test_crop_out_of_bounds_raises():
    img = mx.nd.array(onp.zeros((8, 6, 3), "float32"))
    with pytest.raises((ValueError, mx.base.MXNetError)):
        mx.nd.image.crop(img, x=5, y=0, width=4, height=4)


def test_functional_comparison_is_tagged_mask():
    # functional frontend comparisons must index as masks like dunders do
    x = mx.nd.array(onp.array([10., 20., 30.], "float32"))
    m = mx.nd.broadcast_greater(x, mx.nd.array(onp.array([15., 15., 15.],
                                                         "float32")))
    assert onp.allclose(x[m].asnumpy(), [20., 30.])
