"""Benchmark: ResNet-50 training throughput (img/s) on one chip.

Reference baseline: MXNet-CUDA ResNet-50 training, batch 32, 1x V100 =
298.51 img/s (docs perf.md:244-255; BASELINE.md). The whole training step —
forward, backward, SGD-momentum update — is one fused XLA computation
(ParallelTrainStep on a 1-device mesh), bf16 compute / fp32 params.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import sys
import time

import numpy as onp

BASELINE_IMG_S = 298.51  # MXNet ResNet-50 training, batch 32, V100


def main():
    import os
    batch = int(os.environ.get("BENCH_BATCH", 32))
    steps = int(os.environ.get("BENCH_STEPS", 20))
    warmup = int(os.environ.get("BENCH_WARMUP", 3))
    model = os.environ.get("BENCH_MODEL", "resnet50_v1")

    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(model, classes=1000)
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(onp.zeros((1, 3, 224, 224), "float32")))  # shapes

    mesh = parallel.make_mesh({"dp": 1})
    step = parallel.ParallelTrainStep(
        net, gloss.SoftmaxCrossEntropyLoss(),
        mx.optimizer.SGD(learning_rate=0.05, momentum=0.9), mesh,
        compute_dtype="bfloat16")

    rng = onp.random.RandomState(0)
    xn, yn = step.place_batch(rng.rand(batch, 3, 224, 224).astype("float32"),
                              rng.randint(0, 1000, batch).astype("float32"))

    for _ in range(warmup):
        loss = step(xn, yn)
    loss.wait_to_read()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(xn, yn)
    loss.wait_to_read()
    dt = time.perf_counter() - t0

    img_s = batch * steps / dt
    print(json.dumps({"metric": "resnet50_train_img_s_per_chip",
                      "value": round(img_s, 2), "unit": "img/s",
                      "vs_baseline": round(img_s / BASELINE_IMG_S, 3)}))


if __name__ == "__main__":
    sys.exit(main())
