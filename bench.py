"""Benchmarks of record (BASELINE.json): ResNet-50 training img/s/chip and
BERT-base pretraining tokens/s/chip, one chip each.

Reference baselines:
  - ResNet-50 training, batch 32, 1x V100 = 298.51 img/s (docs perf.md:244-255).
  - BERT-base pretraining: no number is published in the reference tree
    (BASELINE.md — the fork contributes the fused attention ops,
    src/operator/contrib/transformer.cc:650-828, but the model lives in
    GluonNLP), so vs_baseline is null for that row.

Each training step — forward, backward, optimizer update — is ONE fused XLA
computation (ParallelTrainStep on a 1-device mesh), bf16 compute / fp32 params.
BERT runs the Pallas flash-attention path (mask-free full-length sequences).

Prints one JSON line per metric:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""
import json
import os
import sys
import time

import numpy as onp

BASELINE_RESNET_IMG_S = 298.51       # MXNet ResNet-50 training, batch 32, V100
BASELINE_RESNET_B128_IMG_S = 363.69  # training, batch 128, V100 (perf.md:254)
BASELINE_RESNET_INFER_IMG_S = 1233.15  # inference, batch 128, V100 (perf.md:199)


_EMITTED = []


def _emit(metric, value, unit, vs_baseline):
    row = {"metric": metric, "value": round(value, 2), "unit": unit,
           "vs_baseline": (round(vs_baseline, 3)
                           if vs_baseline is not None else None)}
    _EMITTED.append(row)
    print(json.dumps(row), flush=True)


def _time_steps(step, args, steps, warmup, reps=3,
                fetch=lambda out: float(out.asscalar())):
    """Median of `reps` timing windows of `steps` steps each. Every window is
    closed by fetching an output VALUE (not just a ready-flag sync), so a
    glitchy runtime sync can't yield a fake-fast window; the median rejects a
    remaining outlier window."""
    import statistics
    for _ in range(max(warmup, 1)):  # ≥1: `out` must exist for the fetch
        out = step(*args)
    fetch(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = step(*args)
        fetch(out)
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def bench_resnet(batches=None):
    batch = int(os.environ.get("BENCH_BATCH", 32))
    k = int(os.environ.get("BENCH_STEPS_PER_CALL", 80))
    calls = int(os.environ.get("BENCH_CALLS", 2))
    warmup = int(os.environ.get("BENCH_WARMUP", 1))
    model = os.environ.get("BENCH_MODEL", "resnet50_v1")

    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(model, classes=1000)
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(onp.zeros((1, 3, 224, 224), "float32")))  # shapes

    mesh = parallel.make_mesh({"dp": 1})
    step = parallel.ParallelTrainStep(
        net, gloss.SoftmaxCrossEntropyLoss(),
        mx.optimizer.SGD(learning_rate=0.05, momentum=0.9), mesh,
        compute_dtype="bfloat16")

    # k distinct microbatches trained per dispatch (device-side scan loop);
    # every step's forward+backward+update executes — the (k,) losses prove it
    rng = onp.random.default_rng(0)
    fetch = lambda out: float(out.asnumpy()[-1])

    def run(b):
        # float32 generation: a float64 intermediate at (k,b,3,224,224) would
        # be ~3 GB of host RAM for nothing
        placed = step.place_batch_n(
            rng.random((k, b, 3, 224, 224), dtype="float32").astype("bfloat16"),
            rng.integers(0, 1000, (k, b)).astype("float32"))
        dt = _time_steps(step.step_n, placed, calls, warmup, fetch=fetch)
        return b * k * calls / dt

    batches = batches or (batch, 128)
    if batch in batches:
        img_s = run(batch)
        _emit("resnet50_train_img_s_per_chip", img_s, "img/s",
              img_s / BASELINE_RESNET_IMG_S)
    if 128 in batches:
        # batch-128 training row (perf.md:254 config)
        img_s = run(128)
        _emit("resnet50_train_b128_img_s_per_chip", img_s, "img/s",
              img_s / BASELINE_RESNET_B128_IMG_S)


def bench_resnet_inference():
    """Forward-only throughput, batch 128 bf16 (the perf.md:188-200
    benchmark_score.py config)."""
    batch = int(os.environ.get("BENCH_INFER_BATCH", 128))
    # 60 steps/window: the per-window value-fetch RTT (~100 ms through the
    # tunnel) inflates per-call time by RTT/steps — at 20 steps that was
    # ~5 ms on a ~11 ms forward (r5 int8 experiment found it)
    steps = int(os.environ.get("BENCH_STEPS", 60))
    warmup = int(os.environ.get("BENCH_WARMUP", 3))

    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.block import pure_apply

    net = vision.get_model("resnet50_v1", classes=1000)
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")
    net(mx.nd.array(onp.zeros((1, 3, 224, 224), "bfloat16")))
    plist = list(net.collect_params().values())
    dev = jax.devices()[0]
    # cast() re-materializes params on host; pin them (and the batch) to the
    # accelerator or jax will place the whole computation on CPU
    pvals = [jax.device_put(p.data().data, dev) for p in plist]

    @jax.jit
    def fwd(params, x):
        outs, _, _ = pure_apply(net, plist, params, (x,), None, training=False)
        return outs[0]

    rng = onp.random.RandomState(0)
    x = jax.device_put(jnp.asarray(rng.rand(batch, 3, 224, 224), jnp.bfloat16),
                       dev)
    fwd(pvals, x)  # compile
    dt = _time_steps(lambda: fwd(pvals, x), (), steps, warmup,
                     fetch=lambda y: float(y[0, 0]))
    img_s = batch * steps / dt
    _emit("resnet50_infer_b128_img_s_per_chip", img_s, "img/s",
          img_s / BASELINE_RESNET_INFER_IMG_S)


def bench_bert():
    batch = int(os.environ.get("BENCH_BERT_BATCH", 64))
    seq = int(os.environ.get("BENCH_BERT_SEQ", 128))
    # K=40 measured ~8% faster per step than K=80 on this model (the longer
    # scan costs ~3 ms/step; see PERF.md round 5) — 4 calls keeps the same
    # 160-step timing window
    k = int(os.environ.get("BENCH_STEPS_PER_CALL", 40))
    calls = int(os.environ.get("BENCH_CALLS", 4))
    warmup = int(os.environ.get("BENCH_WARMUP", 1))

    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.gluon.model_zoo import bert

    from jax.sharding import PartitionSpec as P

    backbone = bert.bert_base(max_length=seq)
    model = bert.BERTForPretraining(backbone)
    model.initialize(mx.init.Normal(0.02))

    # standard BERT masking: a fixed P = floor(0.15*seq) positions per
    # sample (P=19 at seq 128); the MLM decoder runs only there
    # (~6.7x less vocab-matmul)
    n_pred = max(1, int(seq * 0.15))

    class _PretrainStep(HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def forward(self, tokens, token_types, positions):
            return self.inner(tokens, token_types, None, positions)

    wrapper = _PretrainStep(model)

    mesh = parallel.make_mesh({"dp": 1})
    step = parallel.ParallelTrainStep(
        wrapper, bert.BERTPretrainingLoss(),
        mx.optimizer.Adam(learning_rate=1e-4), mesh,
        compute_dtype="bfloat16", extra_specs=(P("dp"), P("dp")))

    rng = onp.random.RandomState(0)
    toks = rng.randint(0, 30522, (k, batch, seq)).astype("int32")
    tt = onp.zeros((k, batch, seq), "int32")
    positions = onp.sort(
        rng.rand(k, batch, seq).argsort(-1)[..., :n_pred], -1).astype("int32")
    mlm_lab = rng.randint(0, 30522, (k, batch, n_pred)).astype("int32")
    nsp_lab = rng.randint(0, 2, (k, batch)).astype("int32")
    placed = step.place_batch_n(toks, (mlm_lab, nsp_lab), tt, positions)

    dt = _time_steps(step.step_n, placed, calls, warmup,
                     fetch=lambda out: float(out.asnumpy()[-1]))
    tok_s = batch * seq * k * calls / dt
    _emit("bert_base_pretrain_tok_s_per_chip", tok_s, "tokens/s", None)


def bench_dlrm():
    """DLRM over the vocab-sharded embedding subsystem: embedding lookups/s
    through the train step, plus the dataloader-wait share of step time with
    the bare loader vs the streaming DeviceFeed (the staged share is the
    budgeted one — the feed's whole job is driving it toward zero)."""
    vocab = int(os.environ.get("BENCH_DLRM_VOCAB", 1 << 14))
    batch = int(os.environ.get("BENCH_DLRM_BATCH", 256))
    fields = int(os.environ.get("BENCH_DLRM_FIELDS", 8))
    steps = int(os.environ.get("BENCH_DLRM_STEPS", 40))
    dense_in, dim = 13, 16

    import jax
    from mxnet_tpu import parallel
    from mxnet_tpu.embedding import (DeviceFeed, DLRMTrainStep,
                                     ShardedEmbedding,
                                     synthetic_dlrm_batches)
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    n = len(jax.devices())
    mesh = parallel.make_mesh({"tp": n})
    rng = onp.random.RandomState(0)
    emb = ShardedEmbedding(
        vocab, dim, mesh, axis="tp",
        weight=rng.normal(0, 0.01, (vocab, dim)).astype("float32"))
    step = DLRMTrainStep(emb, dense_in, fields, lr=0.05, seed=0)

    raw = synthetic_dlrm_batches(steps, batch, dense_in, fields, vocab,
                                 seed=1)
    dense_all = onp.concatenate([b[0] for b in raw])
    idx_all = onp.concatenate([b[1] for b in raw])
    y_all = onp.concatenate([b[2] for b in raw])
    loader = DataLoader(ArrayDataset(dense_all, idx_all, y_all),
                        batch_size=batch)

    def tup(b):
        return (b[0].asnumpy(), b[1].asnumpy(), b[2].asnumpy())

    step(raw[0])  # compile before any timed window

    def run_unstaged():
        """Consumer-side fetch + dedup + device placement on the step path."""
        wait, it = 0.0, iter(loader)
        t0 = time.perf_counter()
        while True:
            w0 = time.perf_counter()
            try:
                b = next(it)
            except StopIteration:
                break
            bundle = step.stage(tup(b))
            wait += time.perf_counter() - w0
            step(bundle)
        return wait, time.perf_counter() - t0

    def run_staged():
        """The stager pre-places batches; the consumer mostly finds one."""
        feed = DeviceFeed(loader, stage=lambda b: step.stage(tup(b)))
        wait, it = 0.0, iter(feed)
        t0 = time.perf_counter()
        while True:
            w0 = time.perf_counter()
            try:
                bundle = next(it)
            except StopIteration:
                break
            wait += time.perf_counter() - w0
            step(bundle)
        return wait, time.perf_counter() - t0

    u_wait, u_wall = run_unstaged()
    s_wait, s_wall = run_staged()
    _emit("dlrm_emb_lookups_s", steps * batch * fields / s_wall,
          "lookups/s", None)
    _emit("dlrm_step_s_per_chip", steps / s_wall / max(1, n), "steps/s", None)
    # shares as percent so the 2-decimal _emit rounding keeps resolution
    _emit("dlrm_dataloader_wait_share_unstaged_pct",
          100.0 * u_wait / u_wall, "%", None)
    _emit("dlrm_dataloader_wait_share_pct",
          100.0 * s_wait / s_wall, "%", None)


def _section(name, fn):
    """Isolate one bench section: a crashed section must not take down the
    later ones, and its failure must be VISIBLE in the JSON stream — a
    missing metric row reads as 'not run', which is how a kernel-compile
    regression hid the BERT number for half a round."""
    try:
        fn()
        return True
    except Exception as e:  # noqa: BLE001 — report-and-continue by design
        import traceback
        traceback.print_exc()
        # full schema (value/unit/vs_baseline) so JSONL consumers parse it,
        # and routed through _EMITTED so the headline tail re-emit still
        # fires — the error row must never end up as the recorded tail line
        row = {"metric": f"{name}_error", "value": None, "unit": "error",
               "vs_baseline": None,
               "error": f"{type(e).__name__}: {e}"[:500]}
        _EMITTED.append(row)
        print(json.dumps(row), flush=True)
        return False


def main():
    # ORDER = survival priority under an external timeout: the two metrics of
    # record (resnet b32 train, bert pretrain) emit before the secondary
    # rows, so a killed run still reports the headline numbers.
    which = os.environ.get("BENCH_ONLY", "").split(",") if \
        os.environ.get("BENCH_ONLY") else ["resnet", "bert", "infer", "dlrm"]
    ok = True
    if "resnet" in which:
        ok &= _section("resnet50_train", lambda: bench_resnet(batches=(32,)))
    if "bert" in which:
        ok &= _section("bert_base_pretrain", bench_bert)
    if "resnet" in which:
        ok &= _section("resnet50_train_b128",
                       lambda: bench_resnet(batches=(128,)))
    if "infer" in which:
        ok &= _section("resnet50_infer", bench_resnet_inference)
    if "dlrm" in which:
        ok &= _section("dlrm", bench_dlrm)
    # the driver records only the TAIL of this output: re-emit JUST the two
    # metrics of record (bert, then resnet b32 last) so they are the final
    # lines, while the priority-first order above still survives an external
    # timeout mid-run. Tail rows carry "summary": true so JSONL consumers can
    # drop them instead of double-counting the duplicated measurements.
    headline = ("bert_base_pretrain_tok_s_per_chip",
                "resnet50_train_img_s_per_chip")
    rows = {r["metric"]: r for r in _EMITTED}
    tail_rows = [rows[m] for m in headline if m in rows]
    if len(_EMITTED) > len(tail_rows):
        for row in tail_rows:
            print(json.dumps({**row, "summary": True}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
