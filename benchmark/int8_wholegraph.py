"""Whole-graph int8 ResNet-50 inference experiment (VERDICT r4 #7).

The round-3 int8 path lost (0.62x bf16) because every Quantized* block
round-tripped quantize -> int8 op -> dequantize in fp32. This experiment
builds the named fix: an END-TO-END int8 dataflow — activations stay int8
between layers, inference BN is folded into per-output-channel scales, and
each conv's int32 accumulator is requantized to the next layer's int8 scale
in a fused epilogue (scale-multiply + bias + ReLU + round/clip riding the
conv fusion). Residual joins add in f32 inside the epilogue and requantize
once. v5e MXU peak: ~394 TOPS int8 vs ~197 TFLOP/s bf16, so a 2x ceiling
exists IF the graph is int8-clean.

Prints JSON lines: bf16 baseline img/s, int8 whole-graph img/s, and the
int8-vs-fp32 logit cosine similarity (sanity that the graph is faithful).
"""
import functools
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

QMAX = 127.0

# ResNet-50 v1: (blocks, c_out, c_mid, first_stride) per stage
STAGES = [(3, 256, 64, 1), (4, 512, 128, 2), (6, 1024, 256, 2),
          (3, 2048, 512, 2)]


def build_params(rng):
    """Random fp32 weights with BN pre-folded: every conv gets (w, bias)
    where w already carries gamma/sigma and bias = beta - mu*gamma/sigma."""
    def conv_w(cin, cout, k):
        w = rng.randn(cout, cin, k, k).astype("float32")
        w *= (2.0 / (cin * k * k)) ** 0.5          # He init
        scale = rng.uniform(0.5, 1.5, cout).astype("float32")  # folded BN
        bias = rng.uniform(-0.2, 0.2, cout).astype("float32")
        return w * scale[:, None, None, None], bias

    params = {"stem": conv_w(3, 64, 7)}
    cin = 64
    for si, (blocks, cout, cmid, stride) in enumerate(STAGES):
        for bi in range(blocks):
            pre = f"s{si}b{bi}"
            params[pre + "c1"] = conv_w(cin if bi == 0 else cout, cmid, 1)
            params[pre + "c2"] = conv_w(cmid, cmid, 3)
            params[pre + "c3"] = conv_w(cmid, cout, 1)
            if bi == 0:
                params[pre + "ds"] = conv_w(cin, cout, 1)
        cin = cout
    params["fc"] = (rng.randn(1000, 2048).astype("float32") * 0.02,
                    onp.zeros(1000, "float32"))
    return params


# ---------------------------------------------------------------------------
# fp32/bf16 reference forward (same folded weights) — also the calibrator
# ---------------------------------------------------------------------------
def f32_forward(params, x, collect_amax=None):
    import jax
    import jax.numpy as jnp
    from jax import lax

    dn = lax.conv_dimension_numbers((1, 1, 1, 1), (1, 1, 1, 1),
                                    ("NCHW", "OIHW", "NCHW"))

    def conv(x, name, stride=1, relu=True, add=None):
        w, b = params[name]
        p = (w.shape[2] - 1) // 2
        # accumulator dtype follows the compute dtype: forcing f32 output on
        # the bf16 run would double its conv write bytes (unfair baseline)
        y = lax.conv_general_dilated(
            x, w.astype(x.dtype), (stride, stride), [(p, p), (p, p)],
            dimension_numbers=lax.conv_dimension_numbers(
                x.shape, w.shape, ("NCHW", "OIHW", "NCHW")),
            preferred_element_type=jnp.float32 if x.dtype == jnp.float32
            else None)
        y = y + b.astype(x.dtype)[None, :, None, None]
        if add is not None:
            y = y + add.astype(y.dtype)
        if relu:
            y = jnp.maximum(y, 0)
        if collect_amax is not None:
            collect_amax(name, y)
        return y.astype(x.dtype)

    y = conv(x, "stem", stride=2)
    y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 1, 3, 3), (1, 1, 2, 2),
                          [(0, 0), (0, 0), (1, 1), (1, 1)])
    for si, (blocks, cout, cmid, stride) in enumerate(STAGES):
        for bi in range(blocks):
            pre = f"s{si}b{bi}"
            s = stride if bi == 0 else 1
            ident = conv(y, pre + "ds", stride=s, relu=False) if bi == 0 \
                else y
            h = conv(y, pre + "c1", stride=1)
            h = conv(h, pre + "c2", stride=s)
            y = conv(h, pre + "c3", stride=1, relu=True, add=ident)
    y = y.mean(axis=(2, 3))
    wfc, bfc = params["fc"]
    return y.astype(jnp.float32) @ wfc.T.astype(jnp.float32) + bfc


# ---------------------------------------------------------------------------
# whole-graph int8 forward
# ---------------------------------------------------------------------------
def quantize_params(params, amax):
    """Per-output-channel symmetric int8 weights + all the static scales the
    int8 graph needs (python floats / numpy constants, baked into the jit)."""
    qp = {}
    for name, (w, b) in params.items():
        if name == "fc":
            qp[name] = (w, b)
            continue
        wa = onp.abs(w).max(axis=(1, 2, 3)).clip(1e-6)       # (cout,)
        qw = onp.clip(onp.round(w / wa[:, None, None, None] * QMAX),
                      -QMAX, QMAX).astype(onp.int8)
        qp[name] = (qw, wa / QMAX, b)                         # sw per channel
    return qp


def int8_forward(qp, amax, x_q, sx_in):
    """x_q int8 NCHW in, logits f32 out; activations stay int8 throughout.
    Each layer: int8 conv -> int32 acc -> fused epilogue (f32 scale + bias
    [+ residual] + ReLU + round/clip -> int8)."""
    import jax.numpy as jnp
    from jax import lax

    def qconv(x_q, sx, name, stride=1, relu=True, add=None, add_scale=None):
        qw, sw, b = qp[name]
        p = (qw.shape[2] - 1) // 2
        acc = lax.conv_general_dilated(
            x_q, jnp.asarray(qw), (stride, stride), [(p, p), (p, p)],
            dimension_numbers=lax.conv_dimension_numbers(
                x_q.shape, qw.shape, ("NCHW", "OIHW", "NCHW")),
            preferred_element_type=jnp.int32)
        s_out = float(amax[name]) / QMAX
        # fused requantize epilogue: everything below is elementwise on the
        # conv output and fuses into the conv
        m = jnp.asarray(sx * sw / s_out, jnp.float32)          # (cout,)
        y = acc.astype(jnp.float32) * m[None, :, None, None] \
            + jnp.asarray(b / s_out)[None, :, None, None]
        if add is not None:
            y = y + add.astype(jnp.float32) * (add_scale / s_out)
        if relu:
            y = jnp.maximum(y, 0)
        y = jnp.clip(jnp.round(y), -QMAX, QMAX).astype(jnp.int8)
        return y, s_out

    y, s = qconv(x_q, sx_in, "stem", stride=2)
    y = lax.reduce_window(y, jnp.int8(-128), lax.max, (1, 1, 3, 3),
                          (1, 1, 2, 2), [(0, 0), (0, 0), (1, 1), (1, 1)])
    for si, (blocks, cout, cmid, stride) in enumerate(STAGES):
        for bi in range(blocks):
            pre = f"s{si}b{bi}"
            st = stride if bi == 0 else 1
            if bi == 0:
                ident, s_id = qconv(y, s, pre + "ds", stride=st, relu=False)
            else:
                ident, s_id = y, s
            h, sh = qconv(y, s, pre + "c1")
            h, sh = qconv(h, sh, pre + "c2", stride=st)
            y, s = qconv(h, sh, pre + "c3", relu=True, add=ident,
                         add_scale=s_id)
    # head in f32: global mean of int8, then the fc
    yf = y.astype(jnp.float32).mean(axis=(2, 3)) * s
    wfc, bfc = qp["fc"]
    return yf @ jnp.asarray(wfc).T + jnp.asarray(bfc)


from _timing import time_chained as _time_chained


def _time(fn, args):
    return _time_chained(fn, args, fetch=lambda o: float(o[0, 0]))


def main():
    batch = int(os.environ.get("I8_BATCH", 128))
    import jax
    import jax.numpy as jnp

    rng = onp.random.RandomState(0)
    params = build_params(rng)
    x = rng.rand(batch, 3, 224, 224).astype("float32") * 2 - 1

    # calibration: one fp32 forward collecting per-layer amax
    amax = {}
    small = jnp.asarray(x[:8])
    f32_forward(params, small,
                collect_amax=lambda n, y: amax.__setitem__(
                    n, float(jnp.abs(y).max())))

    qp = quantize_params(params, amax)
    sx_in = float(onp.abs(x).max()) / QMAX
    x_q = jnp.asarray(onp.clip(onp.round(x / sx_in), -QMAX, QMAX)
                      .astype(onp.int8))
    x_bf = jnp.asarray(x, jnp.bfloat16)

    # numeric sanity: int8 logits vs fp32 logits on the same weights
    lg_f32 = onp.asarray(f32_forward(params, jnp.asarray(x[:8])))
    lg_i8 = onp.asarray(jax.jit(functools.partial(int8_forward, qp, amax))(
        x_q[:8], sx_in))
    cos = float((lg_f32 * lg_i8).sum() /
                (onp.linalg.norm(lg_f32) * onp.linalg.norm(lg_i8) + 1e-9))
    top1 = float((lg_f32.argmax(1) == lg_i8.argmax(1)).mean())
    print(json.dumps({"check": "int8_vs_fp32", "cosine": round(cos, 4),
                      "top1_agreement": round(top1, 3)}), flush=True)

    # params as jit ARGUMENTS, not closure constants — baked-in constants
    # measured ~35% slower (layout/placement pessimization, and the same
    # HTTP-413 hazard the SSD pipeline hit with closure-captured data)
    params_dev = jax.tree_util.tree_map(jnp.asarray, params)

    @jax.jit
    def f_bf(prm, xb):
        return f32_forward(prm, xb)
    t_bf = _time(f_bf, (params_dev, x_bf))
    print(json.dumps({"mode": "bf16", "img_s": round(batch / t_bf, 0),
                      "ms": round(t_bf * 1e3, 2)}), flush=True)

    qp_dev = jax.tree_util.tree_map(jnp.asarray, qp)

    @jax.jit
    def f_i8(prm, xq):
        return int8_forward(prm, amax, xq, sx_in)
    t_i8 = _time(f_i8, (qp_dev, x_q))
    print(json.dumps({"mode": "int8_wholegraph",
                      "img_s": round(batch / t_i8, 0),
                      "ms": round(t_i8 * 1e3, 2),
                      "vs_bf16": round(t_bf / t_i8, 3)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
