"""Flash-attention backward block-size sweep at long context (VERDICT r4 #8).

Times fwd-only and fwd+bwd at S=FSW_S (default 32768), B=1, H=12, D=64,
causal bf16, for a list of backward (block_q, block_k) pairs, and reports
useful-FLOP rates. "Useful" flops follow the round-3 accounting: the
algorithmically necessary matmul flops (2 matmuls fwd, 5 bwd — the s/dp
recomputes are overhead), causal halves everything.

Usage: FSW_SWEEP="512x1024,512x512,256x512" python benchmark/flash_bwd_sweep.py
"""
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main():
    S = int(os.environ.get("FSW_S", 32768))
    B, H, D = 1, 12, 64
    reps = int(os.environ.get("FSW_REPS", 3))
    chain = int(os.environ.get("FSW_CHAIN", 4))
    sweep = os.environ.get("FSW_SWEEP", "0x0,512x512,256x512,256x1024,"
                                        "1024x512,512x256")

    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention

    rng = onp.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, S, D) * 0.1, jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, S, D) * 0.1, jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, S, D) * 0.1, jnp.bfloat16)

    # useful flops (causal): fwd 2 matmuls, bwd 5
    per_matmul = 2.0 * B * H * S * S * D / 2.0
    fwd_fl = 2 * per_matmul
    bwd_fl = 5 * per_matmul

    from _timing import time_chained

    def fetch(out):
        return jax.tree_util.tree_map(
            lambda a: float(jnp.asarray(a).ravel()[0].astype(jnp.float32)),
            out)

    def timed(fn, *args):
        return time_chained(fn, args, reps=reps, chain=chain, fetch=fetch)

    @jax.jit
    def fwd(q, k, v):
        return flash_attention(q, k, v, causal=True)

    t_fwd = timed(fwd, q, k, v)
    print(json.dumps({"which": "fwd", "ms": round(t_fwd * 1e3, 1),
                      "tf_s": round(fwd_fl / t_fwd / 1e12, 1)}), flush=True)

    for pair in sweep.split(","):
        bq, bk = (int(x) for x in pair.split("x"))
        mx.config.set("MXNET_FLASH_BWD_BLOCK_Q", bq)
        mx.config.set("MXNET_FLASH_BWD_BLOCK_K", bk)

        @jax.jit
        def step(q, k, v):
            def f(q_, k_, v_):
                return flash_attention(q_, k_, v_, causal=True) \
                    .astype(jnp.float32).sum()
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        try:
            t = timed(step, q, k, v)
        except Exception as e:  # noqa: BLE001 — sweep survives bad configs
            print(json.dumps({"bwd_blocks": pair,
                              "error": str(e)[:120]}), flush=True)
            continue
        t_bwd = t - t_fwd
        print(json.dumps({
            "bwd_blocks": pair, "fwdbwd_ms": round(t * 1e3, 1),
            "bwd_ms": round(t_bwd * 1e3, 1),
            "bwd_tf_s": round(bwd_fl / t_bwd / 1e12, 1),
            "total_useful_tf_s": round((fwd_fl + bwd_fl) / t / 1e12, 1)}),
            flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
