"""Sharded-serving scaling curve: one endpoint, 1/2/4/8-chip slices.

The r18 serving-fabric acceptance sweep. For each slice size the harness
carves a fresh gang-scheduled slice out of the visible devices
(``serving.fabric.plan_slices``), builds a ``ShardedEndpoint`` over it for
the SAME seeded MLP, registers it on an ``InferenceServer`` and drives
closed-loop clients through the dynamic batcher for a measured window.
Every size's served probe outputs are checked BITWISE against the
single-chip (unsharded ``ModelEndpoint``) reference served through the
same batcher — the fabric's numerics contract (sharding the batch axis
only re-places rows, it never changes them) holds at every point on the
curve, so the throughput numbers are comparable by construction. The
default width stays in the regime where XLA:CPU's matmul kernel choice is
identical across per-shard batch shapes; very wide layers can pick a
different (equally deterministic) blocked kernel per shape, which is a
fusion artifact of the backend, not a fabric numerics break.

Prints one JSON row per slice size::

    {"slice": 4, "img_s": 15234.1, "p50_ms": 2.1, "p95_ms": 4.0,
     "requests": 1892, "bitwise_vs_ref": true}

and a final summary row (``"summary": true``) carrying
``fabric_sharded_img_s`` — the largest slice's served throughput — which
``tools/perf_gate.py`` gates against PERF_BUDGETS.json (source
``fabric``). On the CI container every "chip" is a forced XLA:CPU host
device sharing the same cores, so the curve certifies the mechanism
(collective-free batch sharding through one cached executable per bucket)
rather than real speedup; on a real slice the same sweep records the
hardware scaling curve.

``--write-multichip PATH`` additionally records the run in the
MULTICHIP_r{N}.json driver-artifact format (n_devices/rc/ok/skipped/tail).

CLI / env knobs:
  --sizes 1,2,4,8   slice sizes to sweep (FS_SIZES; sizes beyond the
                    visible device count are skipped)
  --seconds 2.0     measured window per size           (FS_SECONDS)
  --conc 4          closed-loop clients                (FS_CONC)
  --rows 8          rows per client request            (FS_ROWS)
  --hidden 128      MLP hidden width                   (FS_HIDDEN)
  --in-dim 64       input feature dim                  (FS_IN_DIM)
  --max-batch 32    endpoint max batch size            (FS_MAX_BATCH)
"""
import argparse
import json
import os
import sys
import threading
import time

# every "chip" is a forced host device on the CPU container; the flag only
# multiplies the CPU platform, so it is harmless where real chips exist
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def _build_net(seed, in_dim, hidden, out_dim=16):
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import nn
    mx.random.seed(seed)
    onp.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(hidden, activation="relu"),
                nn.Dense(hidden, activation="relu"),
                nn.Dense(out_dim))
    net.initialize(mx.init.Xavier())
    net.hybridize()     # the bitwise reference is the TRACED forward — the
    net(nd.array(onp.zeros((2, in_dim), "float32")))  # contract's baseline
    return net


def _percentile(sorted_ms, q):
    if not sorted_ms:
        return None
    i = min(len(sorted_ms) - 1, int(round(q * (len(sorted_ms) - 1))))
    return round(sorted_ms[i], 3)


def run_slice(net, ref_out, probes, size, args):
    """One point on the curve: a ShardedEndpoint over a fresh ``size``-chip
    slice, closed-loop load for the measured window, bitwise probe check."""
    from mxnet_tpu import serving
    from mxnet_tpu.serving.fabric import ShardedEndpoint, plan_slices

    name = f"fab_scale_{size}"
    ep = ShardedEndpoint(name, net, input_shapes=(args.in_dim,),
                         dtype="float32", max_batch_size=args.max_batch,
                         slice_spec=plan_slices([size])[0])
    server = serving.InferenceServer(batch_timeout_ms=1.0,
                                     max_queue=args.max_batch * 16)
    server.register(ep)
    server.start()
    stop = threading.Event()
    lock = threading.Lock()
    lat_ms, served, errors = [], [0], [0]

    def client(ci):
        rng = onp.random.RandomState(1000 + ci)
        x = rng.randn(args.rows, args.in_dim).astype("float32")
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                server.submit(name, x).result(timeout=60)
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                lat_ms.append(dt)
                served[0] += args.rows
    try:
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(args.conc)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(args.seconds)
        stop.set()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        # numerics: served probe rows bitwise vs the reference forward
        out = server.predict(name, probes, timeout=60).asnumpy()
        bitwise = bool(onp.array_equal(out, ref_out))
    finally:
        server.stop(drain=False)
        serving.unregister(name)
    lat_ms.sort()
    return {"slice": size, "img_s": round(served[0] / wall, 1),
            "p50_ms": _percentile(lat_ms, 0.50),
            "p95_ms": _percentile(lat_ms, 0.95),
            "requests": len(lat_ms), "client_errors": errors[0],
            "bitwise_vs_ref": bitwise}


def main():
    env = os.environ.get
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--sizes", default=env("FS_SIZES", "1,2,4,8"))
    p.add_argument("--seconds", type=float,
                   default=float(env("FS_SECONDS", 2.0)))
    p.add_argument("--conc", type=int, default=int(env("FS_CONC", 4)))
    p.add_argument("--rows", type=int, default=int(env("FS_ROWS", 8)))
    p.add_argument("--hidden", type=int, default=int(env("FS_HIDDEN", 128)))
    p.add_argument("--in-dim", type=int, default=int(env("FS_IN_DIM", 64)))
    p.add_argument("--max-batch", type=int,
                   default=int(env("FS_MAX_BATCH", 32)))
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--write-multichip", default="",
                   help="also record the run as a MULTICHIP_r{N}.json "
                        "driver artifact at this path")
    args = p.parse_args()

    import jax
    n_dev = len(jax.devices())
    sizes = [int(s) for s in args.sizes.split(",") if s.strip()]
    skipped = [s for s in sizes if s > n_dev]
    sizes = [s for s in sizes if s <= n_dev]
    if skipped:
        print(json.dumps({"skipped_sizes": skipped, "n_devices": n_dev}),
              flush=True)

    from mxnet_tpu import serving
    net = _build_net(args.seed, args.in_dim, args.hidden)
    probes = onp.random.RandomState(args.seed + 1).randn(
        args.rows * 2 + 1, args.in_dim).astype("float32")
    # the numerics baseline: the single-chip reference served THROUGH the
    # batcher (same bucketing/padding path every slice size rides)
    ref_srv = serving.InferenceServer(batch_timeout_ms=1.0)
    ref_srv.register(serving.ModelEndpoint(
        "fab_scale_ref", net, input_shapes=(args.in_dim,),
        dtype="float32", max_batch_size=args.max_batch))
    ref_srv.start()
    ref_out = ref_srv.predict("fab_scale_ref", probes, timeout=60).asnumpy()
    ref_srv.stop(drain=False)
    serving.unregister("fab_scale_ref")

    rows, tail_lines = [], []
    for size in sizes:
        row = run_slice(net, ref_out, probes, size, args)
        rows.append(row)
        print(json.dumps(row), flush=True)
        tail_lines.append(
            f"fabric_scaling(slice={size}): img_s={row['img_s']:.1f} "
            f"p95_ms={row['p95_ms']} bitwise="
            f"{'OK' if row['bitwise_vs_ref'] else 'MISMATCH'}")
    ok = (bool(rows) and all(r["bitwise_vs_ref"] for r in rows)
          and all(r["client_errors"] == 0 for r in rows))
    top = max(rows, key=lambda r: r["slice"]) if rows else None
    summary = {"summary": True, "ok": ok, "n_devices": n_dev,
               "fabric_sharded_img_s": top["img_s"] if top else None,
               "fabric_top_slice": top["slice"] if top else None,
               "scaling": {str(r["slice"]): r["img_s"] for r in rows}}
    print(json.dumps(summary), flush=True)
    tail_lines.append(
        f"fabric_scaling summary: top slice={summary['fabric_top_slice']} "
        f"img_s={summary['fabric_sharded_img_s']} "
        f"curve={summary['scaling']} {'OK' if ok else 'FAIL'}")
    if args.write_multichip:
        artifact = {"n_devices": n_dev, "rc": 0 if ok else 1, "ok": ok,
                    "skipped": False,
                    "tail": "\n".join(tail_lines) + "\n"}
        with open(args.write_multichip, "w") as f:
            json.dump(artifact, f, indent=2)
        print(json.dumps({"wrote": args.write_multichip}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
