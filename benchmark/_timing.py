"""Shared chain-amortized timing for TPU benchmarks.

The tunnel's per-window value-fetch RTT (~100 ms) must be amortized over
many queued calls or it inflates per-call time (bench.py's round-5 lesson:
20 steps/window over-read an ~11 ms forward as ~16 ms). Recipe: warm once,
queue `chain` calls, close the window with ONE scalar value fetch (a ready-
flag sync alone can return early through the tunnel), median over `reps`.
"""
import statistics
import time


def scalar_fetch(out):
    """Cheapest honest sync: fetch one element's VALUE."""
    a = out[0] if isinstance(out, (tuple, list)) else out
    try:
        return float(a[(0,) * a.ndim])
    except TypeError:                      # framework NDArray
        return float(a.asnumpy().ravel()[0])


def time_chained(fn, args, reps=3, chain=40, fetch=scalar_fetch):
    """Median seconds per call of ``fn(*args)`` with chain amortization."""
    out = fn(*args)
    fetch(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(chain):
            out = fn(*args)
        fetch(out)
        ts.append((time.perf_counter() - t0) / chain)
    return statistics.median(ts)
