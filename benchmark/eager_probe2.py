"""Where do eager arrays live, and which dispatch path is slow?"""
import time, sys
import jax, jax.numpy as jnp
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import mxnet_tpu as mx

def timeit(label, f, n=8, warmup=3):
    for _ in range(warmup): f()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter(); f(); ts.append((time.perf_counter()-t0)*1e3)
    ts.sort()
    print(f"{label:52s} med={ts[len(ts)//2]:8.2f} ms min={ts[0]:8.2f}")

x = mx.nd.ones((1024, 1024))
print("default ctx:", mx.current_context())
print("x.data devices:", x.data.devices(), "committed:", x.data.committed)

tpu = jax.devices()[0]
cpu = jax.devices("cpu")[0]
xt = jax.device_put(jnp.ones((1024, 1024)), tpu)
xc = jax.device_put(jnp.ones((1024, 1024)), cpu)

timeit("eager jnp.exp on TPU-committed", lambda: float(jnp.exp(xt).ravel()[0]))
timeit("eager jnp.exp on CPU-committed", lambda: float(jnp.exp(xc).ravel()[0]))

jexp = jax.jit(jnp.exp)
jexp(xt); jexp(xc)
timeit("jit jnp.exp on TPU-committed", lambda: float(jexp(xt).ravel()[0]))
timeit("jit jnp.exp on CPU-committed", lambda: float(jexp(xc).ravel()[0]))

# is it the execute or the fetch? time without fetch but with a later sync
def nofetch():
    ys = [jexp(xt) for _ in range(10)]
    return float(ys[-1].ravel()[0])
timeit("jit exp x10 on TPU, single fetch", nofetch, n=4, warmup=1)

# donate / no ravel: fetch via np.asarray of a 1-elem slice
y = jexp(xt)
timeit("fetch only: float(y.ravel()[0]) again", lambda: float(y.ravel()[0]))
timeit("fetch only: float(y[0,0])", lambda: float(y[0, 0]))
