#!/usr/bin/env python
"""Operator micro-benchmark harness (parity: benchmark/opperf/ —
run_performance_test + the category runners + the opperf.py CLI, collapsed
into one TPU-native module).

Times eager dispatch of registered ops (forward, and backward where the op is
differentiable) with proper device sync, reporting avg/p50/max µs per op —
the tool that exposes dispatch overhead and slow kernels. The category suites
mirror the reference's nd_operations/* groupings with TPU-relevant default
shapes (batched, MXU-aligned).

Usage:
    python benchmark/opperf.py                      # standard suite
    python benchmark/opperf.py --ops dot,exp,sum    # specific ops
    python benchmark/opperf.py --json results.json
"""
import argparse
import json
import os
import sys
import time

# runnable as a plain script from anywhere: the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


# op -> (input shapes, attrs); shapes chosen MXU/VPU-friendly (128-multiples)
_SUITES = {
    "unary": {
        "exp": ([(1024, 1024)], {}),
        "log": ([(1024, 1024)], {}),
        "sqrt": ([(1024, 1024)], {}),
        "negative": ([(1024, 1024)], {}),
        "sigmoid": ([(1024, 1024)], {}),
        "tanh": ([(1024, 1024)], {}),
        "relu": ([(1024, 1024)], {}),
    },
    "binary": {
        "broadcast_add": ([(1024, 1024), (1024, 1024)], {}),
        "broadcast_mul": ([(1024, 1024), (1024, 1024)], {}),
        "broadcast_div": ([(1024, 1024), (1, 1024)], {}),
        "elemwise_add": ([(1024, 1024), (1024, 1024)], {}),
    },
    "gemm": {
        "dot": ([(1024, 1024), (1024, 1024)], {}),
        "batch_dot": ([(32, 256, 256), (32, 256, 256)], {}),
        "FullyConnected": ([(128, 1024), (1024, 1024), (1024,)],
                           {"num_hidden": 1024}),
    },
    "reduction": {
        "sum": ([(1024, 1024)], {}),
        "mean": ([(1024, 1024)], {}),
        "max": ([(1024, 1024)], {}),
        "norm": ([(1024, 1024)], {}),
    },
    "nn": {
        "Convolution": ([(32, 64, 56, 56), (64, 64, 3, 3), (64,)],
                        {"kernel": (3, 3), "num_filter": 64, "pad": (1, 1)}),
        "Pooling": ([(32, 64, 56, 56)],
                    {"kernel": (2, 2), "pool_type": "max", "stride": (2, 2)}),
        "BatchNorm": ([(32, 64, 56, 56), (64,), (64,), (64,), (64,)], {}),
        "softmax": ([(128, 1024)], {}),
        "Dropout": ([(128, 1024)], {"p": 0.5}),
    },
    "indexing": {
        "take": ([(1024, 512), (256,)], {}),
        "Embedding": ([(128, 64), (30000, 256)],
                      {"input_dim": 30000, "output_dim": 256}),
        "one_hot": ([(1024,)], {"depth": 1000}),
    },
    "sorting": {
        "sort": ([(1024, 1024)], {}),
        "argsort": ([(1024, 1024)], {}),
        "topk": ([(1024, 1024)], {"k": 10}),
    },
}


def _make_inputs(op_name, shapes, rng):
    from mxnet_tpu import nd
    arrays = []
    for i, s in enumerate(shapes):
        if op_name in ("take",) and i == 1:
            a = nd.array(rng.randint(0, 1024, s).astype("int32"))
        elif op_name == "Embedding" and i == 0:
            a = nd.array(rng.randint(0, 30000, s).astype("int32"))
        elif op_name == "one_hot":
            a = nd.array(rng.randint(0, 1000, s).astype("int32"))
        else:
            a = nd.array(rng.rand(*s).astype("float32"))
        arrays.append(a)
    return arrays


def run_performance_test(op_names=None, warmup=5, runs=25, backward=True):
    """Benchmark ops by name; returns a list of result dicts
    (run_performance_test analog, benchmark/opperf/utils/benchmark_utils.py)."""
    from mxnet_tpu import autograd
    from mxnet_tpu.ops import registry

    flat = {}
    for suite in _SUITES.values():
        flat.update(suite)
    if op_names:
        sel = {}
        for name in op_names:
            if name not in flat:
                raise KeyError(f"no benchmark config for op {name!r}; "
                               f"known: {sorted(flat)}")
            sel[name] = flat[name]
        flat = sel

    rng = onp.random.RandomState(7)
    results = []
    for name, (shapes, attrs) in flat.items():
        op = registry.get_op(name)
        arrays = _make_inputs(name, shapes, rng)
        times_f, times_b = [], []

        def fwd():
            out = registry.invoke(op, arrays, dict(attrs))
            (out[0] if isinstance(out, (list, tuple)) else out).wait_to_read()
            return out

        for _ in range(warmup):
            fwd()
        for _ in range(runs):
            t0 = time.perf_counter_ns()
            fwd()
            times_f.append((time.perf_counter_ns() - t0) / 1e3)

        if backward and op.differentiable:
            for a in arrays:
                if str(a.dtype).startswith("float"):
                    a.attach_grad()
            grads = [a for a in arrays if a.grad is not None]

            def bwd():
                with autograd.record():
                    out = registry.invoke(op, arrays, dict(attrs))
                    head = out[0] if isinstance(out, (list, tuple)) else out
                head.backward()
                for g in grads:  # sync: async dispatch must not fake the time
                    g.grad.wait_to_read()

            for _ in range(warmup):
                bwd()
            for _ in range(runs):
                t0 = time.perf_counter_ns()
                bwd()
                times_b.append((time.perf_counter_ns() - t0) / 1e3)

        row = {"operator": name,
               "avg_time_forward_us": round(onp.mean(times_f), 2),
               "p50_time_forward_us": round(onp.percentile(times_f, 50), 2),
               "max_time_forward_us": round(onp.max(times_f), 2),
               "inputs": [list(s) for s in shapes]}
        if times_b:
            row["avg_time_backward_us"] = round(onp.mean(times_b), 2)
        results.append(row)
    return results


def main():
    parser = argparse.ArgumentParser(description="mxnet_tpu operator perf")
    parser.add_argument("--ops", default=None,
                        help="comma-separated op names (default: full suite)")
    parser.add_argument("--runs", type=int, default=25)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--no-backward", action="store_true")
    parser.add_argument("--json", default=None, help="write results to file")
    args = parser.parse_args()
    ops = args.ops.split(",") if args.ops else None
    res = run_performance_test(ops, warmup=args.warmup, runs=args.runs,
                               backward=not args.no_backward)
    widths = (24, 14, 14, 14, 14)
    hdr = ("operator", "fwd avg(us)", "fwd p50(us)", "fwd max(us)", "bwd avg(us)")
    print("".join(h.ljust(w) for h, w in zip(hdr, widths)))
    for r in res:
        print("".join([
            r["operator"].ljust(widths[0]),
            str(r["avg_time_forward_us"]).ljust(widths[1]),
            str(r["p50_time_forward_us"]).ljust(widths[2]),
            str(r["max_time_forward_us"]).ljust(widths[3]),
            str(r.get("avg_time_backward_us", "-")).ljust(widths[4])]))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
