#!/usr/bin/env python
"""Operator micro-benchmark harness (parity: benchmark/opperf/ —
run_performance_test + the category runners + the opperf.py CLI, collapsed
into one TPU-native module).

Times eager dispatch of registered ops (forward, and backward where the op is
differentiable) with proper device sync, reporting avg/p50/max µs per op —
the tool that exposes dispatch overhead and slow kernels. The category suites
mirror the reference's nd_operations/* groupings with TPU-relevant default
shapes (batched, MXU-aligned).

Usage:
    python benchmark/opperf.py                      # standard suite
    python benchmark/opperf.py --ops dot,exp,sum    # specific ops
    python benchmark/opperf.py --json results.json
"""
import argparse
import json
import os
import sys
import time

# runnable as a plain script from anywhere: the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


# op -> (input shapes, attrs); shapes chosen MXU/VPU-friendly (128-multiples)
_SUITES = {
    "unary": {
        "exp": ([(1024, 1024)], {}),
        "log": ([(1024, 1024)], {}),
        "sqrt": ([(1024, 1024)], {}),
        "negative": ([(1024, 1024)], {}),
        "sigmoid": ([(1024, 1024)], {}),
        "tanh": ([(1024, 1024)], {}),
        "relu": ([(1024, 1024)], {}),
    },
    "binary": {
        "broadcast_add": ([(1024, 1024), (1024, 1024)], {}),
        "broadcast_mul": ([(1024, 1024), (1024, 1024)], {}),
        "broadcast_div": ([(1024, 1024), (1, 1024)], {}),
        "elemwise_add": ([(1024, 1024), (1024, 1024)], {}),
    },
    "gemm": {
        "dot": ([(1024, 1024), (1024, 1024)], {}),
        "batch_dot": ([(32, 256, 256), (32, 256, 256)], {}),
        "FullyConnected": ([(128, 1024), (1024, 1024), (1024,)],
                           {"num_hidden": 1024}),
    },
    "reduction": {
        "sum": ([(1024, 1024)], {}),
        "mean": ([(1024, 1024)], {}),
        "max": ([(1024, 1024)], {}),
        "norm": ([(1024, 1024)], {}),
    },
    "nn": {
        "Convolution": ([(32, 64, 56, 56), (64, 64, 3, 3), (64,)],
                        {"kernel": (3, 3), "num_filter": 64, "pad": (1, 1)}),
        "Pooling": ([(32, 64, 56, 56)],
                    {"kernel": (2, 2), "pool_type": "max", "stride": (2, 2)}),
        "BatchNorm": ([(32, 64, 56, 56), (64,), (64,), (64,), (64,)], {}),
        "softmax": ([(128, 1024)], {}),
        "Dropout": ([(128, 1024)], {"p": 0.5}),
    },
    "indexing": {
        "take": ([(1024, 512), (256,)], {}),
        "Embedding": ([(128, 64), (30000, 256)],
                      {"input_dim": 30000, "output_dim": 256}),
        "one_hot": ([(1024,)], {"depth": 1000}),
    },
    "sorting": {
        "sort": ([(1024, 1024)], {}),
        "argsort": ([(1024, 1024)], {}),
        "topk": ([(1024, 1024)], {"k": 10}),
    },
}


def _make_inputs(op_name, shapes, rng):
    from mxnet_tpu import nd
    arrays = []
    for i, s in enumerate(shapes):
        if op_name in ("take",) and i == 1:
            a = nd.array(rng.randint(0, 1024, s).astype("int32"))
        elif op_name == "Embedding" and i == 0:
            a = nd.array(rng.randint(0, 30000, s).astype("int32"))
        elif op_name == "one_hot":
            a = nd.array(rng.randint(0, 1000, s).astype("int32"))
        else:
            a = nd.array(rng.rand(*s).astype("float32"))
        arrays.append(a)
    return arrays


def _first_out(out):
    return out[0] if isinstance(out, (list, tuple)) else out


def _fetch(arr):
    """Close a timing window by fetching a VALUE — the only sync primitive the
    axon tunnel cannot fake (block_until_ready can return early; PERF.md)."""
    return float(arr.data.ravel()[0])


def _amortized_us(call, close, runs, rtt_us=0.0, windows=5):
    """Median over `windows` of: ((run `call` x runs, then one closing value
    fetch) - fetch RTT) / runs. Measures steady-state eager throughput with
    async dispatch overlapping device work — the reference engine's semantics
    (ops return immediately; SURVEY §3.1) — without putting a host<->device
    round trip inside every iteration. The closing fetch's own round-trip
    latency (`rtt_us`, ~10-100ms through the axon tunnel, ~us on directly
    attached hardware) is subtracted so the number reflects the ops."""
    meds = []
    for _ in range(windows):
        t0 = time.perf_counter_ns()
        for _ in range(runs):
            out = call()
        close(out)
        meds.append(max(0.0, (time.perf_counter_ns() - t0) / 1e3 - rtt_us) / runs)
    meds.sort()
    return meds[len(meds) // 2]


def _fetch_rtt_us(ctx, samples=7):
    """Min round-trip of fetching one value of an already-computed tiny array:
    the constant the tunnel adds to any closing fetch (min = stable floor)."""
    from mxnet_tpu import nd
    a = nd.ones((2,), ctx=ctx)
    _fetch(a)
    ts = []
    for _ in range(samples):
        t0 = time.perf_counter_ns()
        _fetch(a)
        ts.append((time.perf_counter_ns() - t0) / 1e3)
    return min(ts)


def run_performance_test(op_names=None, warmup=5, runs=25, backward=True,
                         ctx=None):
    """Benchmark ops by name; returns a list of result dicts
    (run_performance_test analog, benchmark/opperf/utils/benchmark_utils.py).

    Two columns per direction:
      - dispatch p50: host time for one eager invoke (async; what Python pays)
      - amortized avg: wall time per call over a window closed by a value
        fetch (includes device execution; the honest throughput number)
    """
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.ops import registry

    flat = {}
    for suite in _SUITES.values():
        flat.update(suite)
    if op_names:
        sel = {}
        for name in op_names:
            if name not in flat:
                raise KeyError(f"no benchmark config for op {name!r}; "
                               f"known: {sorted(flat)}")
            sel[name] = flat[name]
        flat = sel

    rng = onp.random.RandomState(7)
    results = []
    with (ctx if ctx is not None else mx.current_context()) as run_ctx:
        rtt = _fetch_rtt_us(run_ctx)
        for name, (shapes, attrs) in flat.items():
            op = registry.get_op(name)
            arrays = _make_inputs(name, shapes, rng)

            def fwd():
                return registry.invoke(op, arrays, dict(attrs))

            for _ in range(warmup):
                out = fwd()
            _fetch(_first_out(out))
            disp = []
            for _ in range(runs):
                t0 = time.perf_counter_ns()
                fwd()
                disp.append((time.perf_counter_ns() - t0) / 1e3)
            _fetch(_first_out(fwd()))
            # amortized windows use >=100 calls so RTT jitter (tens of ms
            # through the tunnel) stays small against the window total
            win = max(runs, 100)
            amort_f = _amortized_us(fwd, lambda o: _fetch(_first_out(o)), win, rtt)

            row = {"operator": name,
                   "dispatch_p50_forward_us": round(float(onp.percentile(disp, 50)), 2),
                   "avg_time_forward_us": round(amort_f, 2),
                   "inputs": [list(s) for s in shapes]}

            if backward and op.differentiable:
                for a in arrays:
                    if str(a.dtype).startswith("float"):
                        a.attach_grad()
                grads = [a for a in arrays if a.grad is not None]

                def bwd():
                    with autograd.record():
                        head = _first_out(registry.invoke(op, arrays, dict(attrs)))
                    head.backward()
                    return grads[0] if grads else head

                for _ in range(warmup):
                    g = bwd()
                if grads:
                    _fetch(g.grad if g.grad is not None else g)
                    amort_b = _amortized_us(
                        bwd, lambda g: _fetch(g.grad if g.grad is not None else g),
                        win, rtt)
                    row["avg_time_backward_us"] = round(amort_b, 2)
            results.append(row)
    return results


def main():
    parser = argparse.ArgumentParser(description="mxnet_tpu operator perf")
    parser.add_argument("--ops", default=None,
                        help="comma-separated op names (default: full suite)")
    parser.add_argument("--runs", type=int, default=25)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--no-backward", action="store_true")
    parser.add_argument("--ctx", default=None, choices=["cpu", "tpu"],
                        help="context to benchmark on (default: tpu if present)")
    parser.add_argument("--json", default=None, help="write results to file")
    args = parser.parse_args()
    ops = args.ops.split(",") if args.ops else None

    import mxnet_tpu as mx
    # tpu(0) transparently resolves to CPU on accelerator-less hosts (base.py)
    ctx = mx.cpu(0) if args.ctx == "cpu" else mx.tpu(0)
    print(f"context: {ctx} -> {ctx.jax_device()}")
    res = run_performance_test(ops, warmup=args.warmup, runs=args.runs,
                               backward=not args.no_backward, ctx=ctx)
    widths = (24, 18, 16, 16)
    hdr = ("operator", "fwd dispatch p50", "fwd amort avg", "bwd amort avg")
    print("".join(h.ljust(w) for h, w in zip(hdr, widths)))
    for r in res:
        print("".join([
            r["operator"].ljust(widths[0]),
            str(r["dispatch_p50_forward_us"]).ljust(widths[1]),
            str(r["avg_time_forward_us"]).ljust(widths[2]),
            str(r.get("avg_time_backward_us", "-")).ljust(widths[3])]))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
