"""Profile the fused ResNet-50 train step and break device time/bytes down by
fusion category (round-3 PERF.md methodology, re-runnable)."""
import glob
import os
import sys
import tempfile
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def build_step(batch=128):
    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(os.environ.get("BENCH_MODEL", "resnet50_v1"),
                           classes=1000)
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(onp.zeros((1, 3, 224, 224), "float32")))  # shapes
    mesh = parallel.make_mesh({"dp": 1})
    step = parallel.ParallelTrainStep(
        net, gloss.SoftmaxCrossEntropyLoss(),
        mx.optimizer.SGD(learning_rate=0.05, momentum=0.9), mesh,
        compute_dtype="bfloat16")
    rng = onp.random.RandomState(0)
    x = rng.rand(batch, 3, 224, 224).astype("float32")
    y = rng.randint(0, 1000, (batch,)).astype("float32")
    return step, x, y


def main():
    import jax
    step, x, y = build_step(int(os.environ.get("BENCH_BATCH", 128)))
    placed = step.place_batch(x, y)
    for _ in range(3):  # warm up + compile
        out = step.step(*placed)
    _ = float(onp.asarray((out[0] if isinstance(out, (tuple, list)) else out)
                          .asnumpy()).ravel()[0])

    tmp = tempfile.mkdtemp(prefix="xplane_")
    with jax.profiler.trace(tmp):
        for _ in range(5):
            out = step.step(*placed)
        loss_val = out[0] if isinstance(out, (tuple, list)) else out
        _ = float(onp.asarray(loss_val.asnumpy()).ravel()[0])

    pb = glob.glob(os.path.join(tmp, "**", "*.xplane.pb"), recursive=True)
    if not pb:
        print("no xplane written", tmp)
        return 1
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    xs = xplane_pb2.XSpace()
    xs.ParseFromString(open(pb[-1], "rb").read())

    cats = defaultdict(lambda: [0.0, 0.0, 0])   # time_ms, bytes, count
    rows = defaultdict(lambda: [0.0, 0.0, 0])
    for plane in xs.planes:
        if "TPU" not in plane.name and "Device" not in plane.name:
            continue
        ev_meta = plane.event_metadata
        stat_meta = plane.stat_metadata
        for line in plane.lines:
            for ev in line.events:
                name = ev_meta[ev.metadata_id].name
                dur_ms = ev.duration_ps / 1e9
                nbytes = 0
                for st in ev.stats:
                    sname = stat_meta[st.metadata_id].name
                    if sname == "bytes_accessed":
                        nbytes = st.uint64_value or st.int64_value
                low = name.lower()
                if "conv" in low and "fusion" in low or low.startswith("%conv") \
                        or "convolution" in low:
                    cat = "conv fusions"
                elif "fusion" in low:
                    cat = "loop/other fusions"
                elif "copy" in low or "bitcast" in low or "transpose" in low:
                    cat = "copies/format"
                elif "select-and-scatter" in low or "reduce-window" in low:
                    cat = "pool bwd"
                elif "all-reduce" in low:
                    cat = "collectives"
                else:
                    cat = "misc"
                cats[cat][0] += dur_ms
                cats[cat][1] += nbytes
                cats[cat][2] += 1
                rows[name][0] += dur_ms
                rows[name][1] += nbytes
                rows[name][2] += 1

    steps = 5
    print(f"{'category':22s} {'ms/step':>9s} {'GB/step':>9s} {'events':>7s}")
    tot_ms = tot_gb = 0.0
    for cat, (ms, b, n) in sorted(cats.items(), key=lambda kv: -kv[1][0]):
        print(f"{cat:22s} {ms/steps:9.2f} {b/steps/1e9:9.2f} {n//steps:7d}")
        tot_ms += ms / steps
        tot_gb += b / steps / 1e9
    print(f"{'TOTAL':22s} {tot_ms:9.2f} {tot_gb:9.2f}")
    print("\ntop 25 ops by time:")
    for name, (ms, b, n) in sorted(rows.items(), key=lambda kv: -kv[1][0])[:25]:
        print(f"  {ms/steps:8.3f} ms {b/steps/1e9:7.3f} GB x{n//steps:<4d} {name[:90]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
