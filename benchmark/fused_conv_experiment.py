"""Decision-gate experiment for the conv+BN Pallas epilogue work (round 4).

Compares the fused Pallas kernel (prologue affine+relu, 1x1 GEMM, moment
epilogue — mxnet_tpu/ops/pallas/fused_conv1x1.py) against the identical
unfused XLA chain on every distinct 1x1-conv shape of ResNet-50 at batch 128.
Timing: amortized windows closed by a value fetch (PERF.md methodology).

Run on the TPU host:  python benchmark/fused_conv_experiment.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp
import jax
import jax.numpy as jnp

from mxnet_tpu.ops.pallas.fused_conv1x1 import (
    conv1x1_bn_act, conv1x1_bn_act_reference)

# (label, M = batch*H*W, K = Cin, N = Cout) — ResNet-50 v1 @224, batch 128
SHAPES = [
    ("s2_reduce", 128 * 56 * 56, 64, 64),
    ("s2_expand", 128 * 56 * 56, 64, 256),
    ("s2_in", 128 * 56 * 56, 256, 64),
    ("s3_in", 128 * 28 * 28, 512, 128),
    ("s3_expand", 128 * 28 * 28, 128, 512),
    ("s4_in", 128 * 14 * 14, 1024, 256),
    ("s4_expand", 128 * 14 * 14, 256, 1024),
    ("s5_in", 128 * 7 * 7, 2048, 512),
    ("s5_expand", 128 * 7 * 7, 512, 2048),
]


CHAIN = 100


def _chained(fn):
    """Run CHAIN dependent kernel invocations inside ONE jit: the tunnel's
    per-dispatch floor (~1-4 ms) would otherwise swamp sub-ms kernels. The
    1e-30*acc feedback serializes iterations without changing values, and
    consuming y[0,0] keeps the y write live in the XLA reference (a real
    network always materializes y)."""
    @jax.jit
    def run(x, w, s, t):
        def body(i, carry):
            x_, acc = carry
            y, cs, cq = fn(x_, w, s, t)
            acc = acc + cs[0] + cq[0] + y[0, 0].astype(jnp.float32)
            x_ = x + (1e-30 * acc).astype(x.dtype)
            return (x_, acc)
        _, acc = jax.lax.fori_loop(0, CHAIN, body, (x, jnp.float32(0.0)))
        return acc
    return run


_RTT_MS = None


def _rtt_ms():
    """Dispatch+fetch floor of a trivial jitted computation (the constant the
    tunnel adds to every timed window)."""
    global _RTT_MS
    if _RTT_MS is None:
        f = jax.jit(lambda a: a * 2.0)
        z = jnp.float32(1.0)
        float(f(z))
        ts = []
        for _ in range(7):
            t0 = time.perf_counter()
            float(f(z))
            ts.append((time.perf_counter() - t0) * 1e3)
        _RTT_MS = min(ts)
        print(f"tunnel dispatch+fetch floor: {_RTT_MS:.1f} ms (subtracted)")
    return _RTT_MS


def _amortize(run, args, windows=5):
    rtt = _rtt_ms()
    _ = float(run(*args))
    meds = []
    for _w in range(windows):
        t0 = time.perf_counter()
        _ = float(run(*args))
        meds.append(max((time.perf_counter() - t0) * 1e3 - rtt, 0.0) / CHAIN)
    meds.sort()
    return meds[len(meds) // 2]


def main():
    rng = onp.random.RandomState(0)
    jax.jit(lambda: jnp.zeros(()))()  # wake the backend
    print(f"{'shape':12s} {'M':>8s} {'K':>5s} {'N':>5s} "
          f"{'XLA ms':>8s} {'Pallas ms':>10s} {'speedup':>8s}")
    tot_x = tot_p = 0.0
    for label, m, k, n in SHAPES:
        x = jnp.asarray(rng.rand(m, k).astype("float32") - 0.3, jnp.bfloat16)
        w = jnp.asarray(rng.rand(k, n).astype("float32") * 0.05, jnp.bfloat16)
        s = jnp.asarray(rng.rand(k).astype("float32") + 0.5)
        t = jnp.asarray(rng.rand(k).astype("float32") - 0.5)
        bm = 448 if m % 448 == 0 else 512
        tx = _amortize(_chained(conv1x1_bn_act_reference), (x, w, s, t))
        tp = _amortize(
            _chained(lambda *a: conv1x1_bn_act(*a, block_m=bm)), (x, w, s, t))
        tot_x += tx
        tot_p += tp
        print(f"{label:12s} {m:8d} {k:5d} {n:5d} {tx:8.3f} {tp:10.3f} "
              f"{tx / tp:7.2f}x")
    print(f"{'TOTAL':12s} {'':8s} {'':5s} {'':5s} {tot_x:8.3f} {tot_p:10.3f} "
          f"{tot_x / tot_p:7.2f}x")


if __name__ == "__main__":
    main()
