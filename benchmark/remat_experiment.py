"""A/B the MXNET_TRAIN_REMAT policy on the ResNet-50 b128 train step."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def run(policy, batch=128, k=40, calls=3):
    import mxnet_tpu as mx
    mx.config.set("MXNET_TRAIN_REMAT", policy)
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model("resnet50_v1", classes=1000)
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(onp.zeros((1, 3, 224, 224), "float32")))
    mesh = parallel.make_mesh({"dp": 1})
    step = parallel.ParallelTrainStep(
        net, gloss.SoftmaxCrossEntropyLoss(),
        mx.optimizer.SGD(learning_rate=0.05, momentum=0.9), mesh,
        compute_dtype="bfloat16")
    rng = onp.random.default_rng(0)
    placed = step.place_batch_n(
        rng.random((k, batch, 3, 224, 224), dtype="float32").astype("bfloat16"),
        rng.integers(0, 1000, (k, batch)).astype("float32"))
    out = step.step_n(*placed)
    _ = float(out.asnumpy()[-1])
    best = None
    for _ in range(calls):
        t0 = time.perf_counter()
        out = step.step_n(*placed)
        _ = float(out.asnumpy()[-1])
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    img_s = batch * k / best
    print(f"remat={policy:5s}  {img_s:8.1f} img/s  ({best/k*1e3:.2f} ms/step)",
          flush=True)
    return img_s


if __name__ == "__main__":
    for policy in sys.argv[1:] or ["none", "conv"]:
        run(policy)
