"""SSD-300 detection-accuracy evidence (VERDICT r3 #8).

No detection dataset can be downloaded in this environment (zero egress), so
this trains on the synthetic shapes benchmark (three geometry classes,
rejection-sampled non-occluding placements — test_utils.get_shapes_detection)
and evaluates VOC07 11-point mAP@0.5 at the reference's threshold=0.01 eval
convention. Thin wrapper over examples/ssd/train_shapes.py — the ONE
detection-accuracy pipeline — that emits the committed-evidence JSON line.

Run on the TPU host:  python benchmark/ssd_accuracy.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples",
    "ssd"))


def main():
    from train_shapes import evaluate, train
    from mxnet_tpu.test_utils import get_shapes_detection

    steps = int(os.environ.get("SSD_STEPS", 1200))
    batch = int(os.environ.get("SSD_BATCH", 32))
    lr = float(os.environ.get("SSD_LR", 1e-3))
    bf16 = os.environ.get("SSD_DTYPE", "bfloat16") == "bfloat16"
    net, ctx, imgs_per_s = train(
        steps=steps, batch_size=batch, lr=lr, bf16=bf16,
        log=lambda *a: print(*a, flush=True))
    val_imgs, val_labels = get_shapes_detection(64, size=300, seed=12345)
    mAP = evaluate(net, val_imgs, val_labels, batch, ctx)
    print(json.dumps({"metric": "ssd300_synthetic_shapes_mAP",
                      "value": round(float(mAP), 4), "unit": "mAP@0.5",
                      "steps": steps,
                      "train_imgs_per_s": round(imgs_per_s, 1)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
