"""SSD-300 detection-accuracy evidence (VERDICT r3 #8).

No detection dataset can be downloaded in this environment (zero egress), so
this trains on a deterministic synthetic shapes benchmark: 300x300 images of
filled rectangles on textured noise, 3 classes distinguished by intensity
pattern, 1-2 objects per image. Real detection learning end-to-end
(multibox target matching, localization regression, NMS decode), evaluated
with the VOC-style MApMetric. Prints one JSON line with the mAP.

Run on the TPU host:  python benchmark/ssd_accuracy.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def make_batch(rng, batch, size=300, max_objects=2):
    """Images + padded [cls, x1, y1, x2, y2] labels (normalized corners)."""
    x = rng.rand(batch, 3, size, size).astype("float32") * 0.25
    labels = onp.full((batch, max_objects, 5), -1.0, "float32")
    for b in range(batch):
        n = rng.randint(1, max_objects + 1)
        for o in range(n):
            w = rng.uniform(0.2, 0.5)
            h = rng.uniform(0.2, 0.5)
            x1 = rng.uniform(0.02, 0.95 - w)
            y1 = rng.uniform(0.02, 0.95 - h)
            cls = rng.randint(0, 3)
            labels[b, o] = [cls, x1, y1, x1 + w, y1 + h]
            px1, py1 = int(x1 * size), int(y1 * size)
            px2, py2 = int((x1 + w) * size), int((y1 + h) * size)
            patch = x[b, :, py1:py2, px1:px2]
            if cls == 0:          # bright solid
                patch[:] = 0.9
            elif cls == 1:        # dark solid
                patch[:] = 0.05
            else:                 # horizontal stripes
                patch[:] = 0.05
                patch[:, ::8, :] = 0.9
    return x, labels


def main(steps=int(os.environ.get("SSD_STEPS", 400)), batch=8,
         lr=float(os.environ.get("SSD_LR", 5e-3))):
    import mxnet_tpu as mx
    from mxnet_tpu import nd, parallel
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.model_zoo.vision.ssd import MApMetric, SSDMultiBoxLoss

    net = vision.get_model("ssd_300_vgg16", classes=3)
    net.initialize(mx.init.Xavier())
    net(nd.array(onp.zeros((1, 3, 300, 300), "float32")))  # shapes

    mesh = parallel.make_mesh({"dp": 1})
    step = parallel.ParallelTrainStep(
        net, SSDMultiBoxLoss(),
        mx.optimizer.SGD(learning_rate=lr, momentum=0.9, wd=5e-4,
                         clip_gradient=2.0), mesh,
        compute_dtype=os.environ.get("SSD_DTYPE") or None)

    rng = onp.random.RandomState(0)
    t0 = time.time()
    k = 20  # steps fused per dispatch
    for outer in range(steps // k):
        batch_imgs = onp.zeros((k, batch, 3, 300, 300), "float32")
        batch_labels = onp.zeros((k, batch, 2, 5), "float32")
        for i in range(k):
            bi, bl = make_batch(rng, batch)
            batch_imgs[i], batch_labels[i] = bi, bl
        placed = step.place_batch_n(batch_imgs, batch_labels)
        out = step.step_n(*placed)
        losses = onp.asarray(out.asnumpy())
        print(f"step {(outer + 1) * k:4d} loss {losses.mean():.4f} "
              f"({time.time() - t0:.0f}s)", flush=True)

    # ---- evaluation: VOC-style mAP on held-out synthetic images ----
    metric = MApMetric(ovp_thresh=0.5, class_names=["bright", "dark",
                                                    "stripes"])
    eval_rng = onp.random.RandomState(123)
    for _ in range(8):
        x, labels = make_batch(eval_rng, batch)
        det = net.detect(nd.array(x), threshold=0.01)
        metric.update(det, nd.array(labels))
    name, value = metric.get()
    mAP = value[-1] if isinstance(value, (list, tuple)) else value
    print(json.dumps({"metric": "ssd300_synthetic_shapes_mAP",
                      "value": round(float(mAP), 4), "unit": "mAP@0.5",
                      "steps": steps}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
