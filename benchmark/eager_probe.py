"""Instrument the eager dispatch path on the real chip (round-4 diagnosis).

Breaks down where time goes in eager exp().backward() and eager Convolution
forward, steady-state, with value-fetched timing windows.
"""
import time
import sys

import jax
import jax.numpy as jnp

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.ndarray import ndarray as ndmod


def fetch(nd_or_jax):
    a = nd_or_jax.data if hasattr(nd_or_jax, "data") else nd_or_jax
    return float(a.ravel()[0])


def timeit(label, f, n=10, warmup=3):
    for _ in range(warmup):
        f()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        f()
        ts.append((time.perf_counter() - t0) * 1e3)
    ts.sort()
    print(f"{label:45s} med={ts[len(ts)//2]:8.2f} ms  min={ts[0]:8.2f}  max={ts[-1]:8.2f}")
    return ts[len(ts) // 2]


print("devices:", jax.devices())

# --- 1. eager exp forward ---
x = mx.nd.ones((1024, 1024))
x.attach_grad()
timeit("exp fwd (fetched)", lambda: fetch(mx.nd.exp(x)))

# --- 2. eager exp backward, whole ---
def bwd():
    with autograd.record():
        y = mx.nd.exp(x)
    y.backward()
    return fetch(x.grad)

timeit("exp fwd+bwd (fetched)", bwd)

# --- 3. instrument the pieces of backward ---
import mxnet_tpu.autograd as ag

_orig_node_vjp = ag._node_vjp
_orig_write_grad = ag._write_grad
acc = {}

def timed_node_vjp(node, cots):
    t0 = time.perf_counter()
    r = _orig_node_vjp(node, cots)
    acc["node_vjp"] = acc.get("node_vjp", 0) + (time.perf_counter() - t0)
    return r

def timed_write_grad(x_, v):
    t0 = time.perf_counter()
    r = _orig_write_grad(x_, v)
    acc["write_grad"] = acc.get("write_grad", 0) + (time.perf_counter() - t0)
    return r

ag._node_vjp = timed_node_vjp
ag._write_grad = timed_write_grad

for _ in range(3):
    bwd()
acc.clear()
N = 5
t0 = time.perf_counter()
for _ in range(N):
    bwd()
tot = (time.perf_counter() - t0) / N * 1e3
print(f"backward breakdown over {N} calls: total {tot:.2f} ms/call")
for k, v in acc.items():
    print(f"  {k:20s} {v / N * 1e3:8.2f} ms/call")
ag._node_vjp = _orig_node_vjp
ag._write_grad = _orig_write_grad

# --- 3b. inside _node_vjp: is it the vjp_exec call itself? ---
from mxnet_tpu.ops import registry as reg
with autograd.record():
    y = mx.nd.exp(x)
node = y._tape_node
key_probe = {}

# replicate the cache lookup by calling _node_vjp once then timing vjp_exec directly
cot = jnp.ones(y.shape, y.data.dtype)
ag._node_vjp(node, [cot])  # populate cache
print("VJP cache size:", len(ag._VJP_CACHE))
vjp_exec = next(iter(ag._VJP_CACHE.values()))
jx = (x.data,)

def raw_vjp():
    out = vjp_exec(jx, (cot,))
    return float(out[0].ravel()[0])

timeit("raw cached vjp_exec (fetched)", raw_vjp)
autograd._STATE.tape = []

# --- 4. eager Convolution forward ---
data = mx.nd.random.uniform(shape=(32, 64, 56, 56))
w = mx.nd.random.uniform(shape=(64, 64, 3, 3))
b = mx.nd.zeros((64,))

def conv():
    out = mx.nd.Convolution(data, w, b, kernel=(3, 3), num_filter=64, pad=(1, 1))
    return fetch(out)

timeit("eager Convolution fwd (fetched)", conv)

# what does the raw jitted conv cost?
convop = reg.get_op("Convolution")
attrs = dict(kernel=(3, 3), num_filter=64, pad=(1, 1))
ex = reg._executor(convop, attrs)

def rawconv():
    return float(ex(data.data, w.data, b.data).ravel()[0])

timeit("raw cached jitted conv (fetched)", rawconv)
print("JIT cache size:", len(reg._JIT_CACHE))

# --- 5. tiny jitted op round trip for reference ---
tiny = jax.jit(lambda a: a + 1)
ta = jnp.ones((8, 8))
timeit("tiny jit roundtrip (fetched)", lambda: float(tiny(ta).ravel()[0]))

# --- 6. plain jnp dispatch (no mx wrapper) ---
timeit("plain jnp.exp (fetched)", lambda: float(jnp.exp(x.data).ravel()[0]))
