"""BERT-base pretraining step profile + lever experiments (VERDICT r4 #3).

Modes (BBL_MODE):
  baseline   the bench.py configuration (dense short-seq attention, Adam f32)
  bf16adam   Adam moments held in bf16 (halves optimizer-state HBM traffic)

BBL_PROFILE=1 adds the per-HLO-category device-time/byte ledger.
Prints one JSON line {"mode":..., "tok_s":..., "ms_step":...}.
"""
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main():
    mode = os.environ.get("BBL_MODE", "baseline")
    batch = int(os.environ.get("BBL_BATCH", 64))
    seq = int(os.environ.get("BBL_SEQ", 128))
    k = int(os.environ.get("BBL_K", 40))
    calls = int(os.environ.get("BBL_CALLS", 2))

    import mxnet_tpu as mx
    if mode == "bf16adam":
        mx.config.set("MXNET_OPT_BF16_MOMENTS", True)
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.gluon.model_zoo import bert
    from jax.sharding import PartitionSpec as P

    backbone = bert.bert_base(max_length=seq)
    model = bert.BERTForPretraining(backbone)
    model.initialize(mx.init.Normal(0.02))
    # A/B hook for the PERF.md round-5 GELU finding: gelu_tanh is the model
    # default now, so reproducing the erf arm requires BBL_GELU=gelu
    if "BBL_GELU_TANH" in os.environ:
        raise SystemExit("BBL_GELU_TANH is gone: gelu_tanh is the model "
                         "default now; use BBL_GELU=gelu for the erf arm")
    gelu = os.environ.get("BBL_GELU")
    if gelu:
        for layer in backbone.encoder._layers:
            layer.ffn._act = gelu
    n_pred = max(1, int(seq * 0.15))

    class _PretrainStep(HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def forward(self, tokens, token_types, positions):
            return self.inner(tokens, token_types, None, positions)

    wrapper = _PretrainStep(model)
    mesh = parallel.make_mesh({"dp": 1})
    step = parallel.ParallelTrainStep(
        wrapper, bert.BERTPretrainingLoss(),
        mx.optimizer.Adam(learning_rate=1e-4), mesh,
        compute_dtype="bfloat16", extra_specs=(P("dp"), P("dp")))

    rng = onp.random.RandomState(0)
    toks = rng.randint(0, 30522, (k, batch, seq)).astype("int32")
    tt = onp.zeros((k, batch, seq), "int32")
    positions = onp.sort(
        rng.rand(k, batch, seq).argsort(-1)[..., :n_pred], -1).astype("int32")
    mlm_lab = rng.randint(0, 30522, (k, batch, n_pred)).astype("int32")
    nsp_lab = rng.randint(0, 2, (k, batch)).astype("int32")
    placed = step.place_batch_n(toks, (mlm_lab, nsp_lab), tt, positions)

    out = step.step_n(*placed)
    float(out.asnumpy()[-1])
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(calls):
            out = step.step_n(*placed)
        float(out.asnumpy()[-1])
        times.append(time.perf_counter() - t0)
    dt = statistics.median(times)
    tok_s = batch * seq * k * calls / dt
    print(json.dumps({"mode": mode, "tok_s": round(tok_s, 0),
                      "ms_step": round(1000 * dt / (k * calls), 2)}),
          flush=True)

    if os.environ.get("BBL_PROFILE") == "1":
        from resnet_byteledger import _profile
        _profile(step, placed)
    return 0


if __name__ == "__main__":
    sys.exit(main())
