"""Closed-loop load generator for mxnet_tpu.serving (ISSUE r6 benchmark).

N closed-loop clients each keep exactly one request in flight against a
ModelEndpoint behind the dynamic batcher; at each concurrency level the
harness reports served img/s and request-latency p50/p99 — the curve that
shows dynamic batching converting concurrency into device-batch occupancy
(served throughput should climb toward the direct full-batch rate while p99
stays bounded by batch_timeout + step time).

Two endpoints are exercised per run: ResNet-50 bf16 and (optionally) the
``quantize_net``-produced int8 variant of the same weights — the public-API
int8 path VERDICT r5 asked to make servable.

Env knobs (benchmark/_timing.py conventions: warm first, median over reps,
one honest value-fetch per window — here the per-request futures already
synchronize, so the loadgen measures wall-clock over whole windows):

  SLG_MODEL=resnet50_v1   model-zoo name
  SLG_IMG=224             input H=W (smaller for CPU smoke runs)
  SLG_CLASSES=1000
  SLG_DTYPES=bf16,int8    comma list of {f32, bf16, int8}
  SLG_CONC=1,2,4,8,16     concurrency sweep
  SLG_SECONDS=5           measured window per level
  SLG_MAX_BATCH=32        endpoint max batch / largest bucket
  SLG_TIMEOUT_MS=5        batcher deadline
  SLG_CALIB=4             int8 calibration batches
  SLG_TELEMETRY=          when set, write the final telemetry snapshot JSON
                          here (readable live/after via tools/metrics_dump.py;
                          combine with MXNET_TELEMETRY_DUMP_PATH for
                          periodic in-run dumps)

Prints one JSON line per (dtype, concurrency):
  {"dtype":..., "conc":..., "img_s":..., "p50_ms":..., "p99_ms":...,
   "occupancy":..., "compiles":..., "batches":...}
and a final per-dtype summary line with the direct (unserved) single-batch
forward rate for reference.
"""
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def _build_net(name, classes, img, dtype):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(name, classes=classes)
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(onp.zeros((1, 3, img, img), "float32")))
    if dtype == "bf16":
        net.cast("bfloat16")
        net(mx.nd.array(onp.zeros((1, 3, img, img), "float32"))
            .astype("bfloat16"))
    elif dtype == "int8":
        from mxnet_tpu.contrib.quantization import quantize_net
        rng = onp.random.default_rng(7)
        calib_n = int(os.environ.get("SLG_CALIB", 4))
        calib = [mx.nd.array(rng.random((4, 3, img, img), dtype="float32"))
                 for _ in range(calib_n)]
        net = quantize_net(net, calib_data=calib, calib_mode="naive")
    return net


def _direct_rate(net, img, in_dtype, batch, reps=3):
    """Reference: direct full-batch forward img/s (no serving layer),
    chain-amortized per benchmark/_timing.py."""
    import mxnet_tpu as mx
    from benchmark._timing import time_chained

    x = mx.nd.array(onp.random.default_rng(0).random(
        (batch, 3, img, img), dtype="float32"))
    if in_dtype == "bfloat16":
        x = x.astype("bfloat16")
    net.hybridize()
    sec = time_chained(lambda a: net(a), (x,), reps=reps, chain=10)
    return batch / sec


def _run_level(server, name, img, np_dtype, conc, seconds):
    """Closed loop: `conc` clients, one in-flight request each."""
    stop_at = time.perf_counter() + seconds
    lat_ms = []
    served = [0] * conc
    lock = threading.Lock()
    rng = onp.random.default_rng(42)
    frames = [rng.random((3, img, img), dtype="float32").astype(np_dtype)
              for _ in range(8)]

    def client(ci):
        i = 0
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            server.predict(name, frames[(ci + i) % len(frames)], timeout=120)
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                lat_ms.append(dt)
            served[ci] += 1
            i += 1

    t_start = time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,)) for c in range(conc)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    lat_ms.sort()
    n = len(lat_ms)
    return {
        "img_s": round(sum(served) / wall, 1),
        "p50_ms": round(lat_ms[n // 2], 2) if n else None,
        "p99_ms": round(lat_ms[min(n - 1, int(n * 0.99))], 2) if n else None,
        "requests": n,
    }


def main():
    model = os.environ.get("SLG_MODEL", "resnet50_v1")
    img = int(os.environ.get("SLG_IMG", 224))
    classes = int(os.environ.get("SLG_CLASSES", 1000))
    dtypes = os.environ.get("SLG_DTYPES", "bf16,int8").split(",")
    conc_levels = [int(c) for c in
                   os.environ.get("SLG_CONC", "1,2,4,8,16").split(",")]
    seconds = float(os.environ.get("SLG_SECONDS", 5))
    max_batch = int(os.environ.get("SLG_MAX_BATCH", 32))
    timeout_ms = float(os.environ.get("SLG_TIMEOUT_MS", 5))

    import mxnet_tpu as mx  # noqa: F401  (context/init side effects)
    from mxnet_tpu import serving

    for dtype in dtypes:
        dtype = dtype.strip()
        net = _build_net(model, classes, img, dtype)
        in_dtype = "bfloat16" if dtype == "bf16" else "float32"
        name = f"{model}_{dtype}"
        ep = serving.ModelEndpoint(name, net, input_shapes=(3, img, img),
                                   dtype=in_dtype, max_batch_size=max_batch)
        server = serving.InferenceServer(batch_timeout_ms=timeout_ms,
                                         max_queue=max_batch * 8)
        server.register(ep)          # warms every bucket: no serve-time compile
        compiles_after_warmup = ep.stats.counters["compiles"]
        server.start()
        np_dtype = ep.np_dtypes[0]
        try:
            for conc in conc_levels:
                row = _run_level(server, name, img, np_dtype, conc, seconds)
                snap = serving.stats()[name]
                row.update({
                    "dtype": dtype, "conc": conc,
                    "occupancy": round(snap["batch_occupancy"], 3),
                    "compiles": snap["counters"]["compiles"],
                    "batches": snap["counters"]["batches"],
                })
                print(json.dumps(row), flush=True)
        finally:
            server.stop(drain=True)
        snap = serving.stats()[name]
        assert snap["counters"]["compiles"] == compiles_after_warmup, \
            "serving traffic recompiled beyond warmup buckets"
        direct = _direct_rate(net, img, in_dtype, max_batch)
        print(json.dumps({
            "dtype": dtype, "summary": True,
            "direct_b{}_img_s".format(max_batch): round(direct, 1),
            "buckets": list(ep.buckets),
            "compiles": snap["counters"]["compiles"],
        }), flush=True)
        serving.unregister(name)

    # one whole-process telemetry snapshot: serving latency histograms,
    # executable-cache hit/miss/compile-seconds, queue depth / occupancy,
    # train-step + dataloader families (zero here), device memory gauges
    from mxnet_tpu import telemetry
    tsnap = telemetry.snapshot()
    print(json.dumps({"telemetry_summary": telemetry.summary_line(),
                      "metric_families": len(tsnap["metrics"])}), flush=True)
    dump_path = os.environ.get("SLG_TELEMETRY", "")
    if dump_path:
        telemetry.dump(dump_path)
        print(json.dumps({"telemetry_snapshot": dump_path}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
