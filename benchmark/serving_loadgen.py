"""Closed-loop load generator for mxnet_tpu.serving (ISSUE r6 benchmark).

N closed-loop clients each keep exactly one request in flight against one or
more ModelEndpoints behind the dynamic batcher; at each concurrency level the
harness reports served img/s and request-latency p50/p95/p99 plus the
queue-wait share of the tail — the decomposition that shows whether extra
latency is scheduling (queue wait) or compute (step time). r6 adds
multi-tenant mode (``--tenants N --mix w1,w2,...``): N endpoints share the
device through the Router, traffic splits by the mix weights, and a
per-tenant latency table is emitted so SLO fairness is measurable, plus
``--serial`` to A/B the double-buffered pipeline against the serial
prepare-then-step path.

Two dtypes are exercised per single-tenant run: ResNet bf16 and (optionally)
the ``quantize_net``-produced int8 variant of the same weights — the
public-API int8 path VERDICT r5 asked to make servable.

Env knobs (benchmark/_timing.py conventions; CLI flags override env):

  SLG_MODEL=resnet50_v1   model-zoo name
  SLG_IMG=224             input H=W (smaller for CPU smoke runs)
  SLG_CLASSES=1000
  SLG_DTYPES=bf16,int8    comma list of {f32, bf16, int8}
  SLG_CONC=1,2,4,8,16     concurrency sweep
  SLG_SECONDS=5           measured window per level
  SLG_MAX_BATCH=32        endpoint max batch / largest bucket
  SLG_TIMEOUT_MS=5        batcher deadline
  SLG_CALIB=4             int8 calibration batches
  SLG_TELEMETRY=          when set, write the final telemetry snapshot JSON
                          here (readable live/after via tools/metrics_dump.py;
                          combine with MXNET_TELEMETRY_DUMP_PATH for
                          periodic in-run dumps)

r11 adds the generative phase (``--decode`` / SLG_DECODE=1): closed-loop
autoregressive clients against a DecodeEndpoint + DecodeScheduler (paged KV
cache, token-granularity continuous batching) split across a gold/bulk
tenant pair. Reports decode tok/s/chip, client-observed inter-token
p50/p95/p99 and KV-pool occupancy — the round-16 gate metrics.

  SLG_DECODE=1            run the decode phase after the image sweep
  SLG_DEC_CLIENTS=4       closed-loop decode clients (alternate gold/bulk)
  SLG_DEC_SECONDS=        measured decode window (default SLG_SECONDS)
  SLG_DEC_SEQ=64          max sequence length (prompt + generated)
  SLG_DEC_NEW=16          max new tokens per request (budgets drawn from
                          [SLG_DEC_NEW/2, SLG_DEC_NEW])
  SLG_DTYPES=none         skip the image sweep (decode-only run)

r19 adds the recommendation phase (``--dlrm`` / SLG_DLRM=1): closed-loop
single-example clients against the model-zoo DLRM behind the dynamic
batcher — the huge-QPS / tiny-compute serving profile. Reports served
req/s, embedding lookups/s, the latency/queue-wait decomposition and the
request stream's hot-row hit rate.

  SLG_DLRM=1              run the DLRM phase after the image sweep
  SLG_DLRM_CLIENTS=8      closed-loop DLRM clients
  SLG_DLRM_SECONDS=       measured DLRM window (default SLG_SECONDS)

r17 adds the elasticity benchmark (``--restart``): restart-to-first-request
time, cold (empty executable cache) vs warm (cache populated by the cold
run). The harness spawns one subprocess per phase sharing an executable
cache + compile ledger directory; each child builds the dense endpoint
(and, with the decode phase enabled, the decode engine), starts an
InferenceServer and times from process entry to the first served response.
The parent asserts the warm child performed ZERO fresh compiles (every
ledger record is a cache hit, the recompile-storm duplicate counter stays
0) and that first-request outputs are bitwise-identical across phases,
then emits the gate row ``{"restart_to_first_request_s": <warm>, ...}``.

r18 extends the restart benchmark to the serving fabric (``--fabric``):
each restart child additionally builds a mesh-sharded endpoint
(``serving.fabric.ShardedEndpoint`` on a 2-device slice) and serves one
request through it. The sharded compile trigger key carries the mesh
shape, so the warm child's zero-fresh-compiles assertion now also proves
a restarted sharded replica with the same slice shape deserializes every
bucket executable from the cache — and the sharded first-request digest
must match bitwise across phases.

r18 adds the tail-tolerance phases (``--hedge`` / ``--storm``) and
end-to-end deadlines (``--deadline-ms``). With a deadline every sweep
request carries the budget into the serving stack and the per-level row
grows a ``deadline_misses`` count (requests failed fast with
DeadlineExceeded instead of served late). ``--hedge`` runs a two-replica
ServingPool burst under an injected ``replica_straggler`` stall and emits
the hedging account the perf gate consumes: hedge rate, win rate, the
wasted-duplicate-work share (``hedge_wasted_work_pct`` — bounded by the
hedge token bucket, so the ceiling is enforced by construction) and budget
exhaustions. ``--storm`` replays a bounded retryable ``net_drop`` storm
through a single-host FrontDoor: the frontdoor retry budget must absorb
every drop, and the row carries ``storm_amplification`` (fault-site
attempts per request) and ``storm_client_error_rate`` (the ==0 gate row).

  SLG_DEADLINE_MS=0       end-to-end deadline per request (0 = none)
  SLG_HEDGE=1             run the hedged-burst phase
  SLG_STORM=1             run the retry-storm phase
  SLG_TAIL_REQUESTS=60    burst size for the hedge/storm phases

CLI:
  --tenants N       register N endpoints of the model (t0..tN-1) on ONE
                    server and emit a per-tenant latency table per level
  --mix w0,w1,...   client-traffic weights per tenant (default uniform)
  --slo-ms a,b,...  per-tenant scheduling SLO passed to register()
  --serial          pipeline=False (the pre-r6 prepare-then-step path)
  --restart         run the cold/warm restart benchmark instead of the
                    load sweep (uses the SLG_* model/size knobs)
  --fabric          with --restart: also run a mesh-sharded endpoint
                    (2-device slice) through both phases
  --conc / --seconds / --img / --max-batch / --timeout-ms / --dtypes
                    override the corresponding SLG_* env knobs

Prints one JSON line per (dtype, concurrency[, tenant]):
  {"dtype":..., "conc":..., "img_s":..., "p50_ms":..., "p99_ms":...,
   "queue_wait_p99_ms":..., "queue_wait_share_p99":..., "occupancy":...,
   "compiles":..., "batches":...}
and a final per-dtype summary line with the direct (unserved) single-batch
forward rate for reference.
"""
import argparse
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def _build_net(name, classes, img, dtype):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(name, classes=classes)
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(onp.zeros((1, 3, img, img), "float32")))
    if dtype == "bf16":
        net.cast("bfloat16")
        net(mx.nd.array(onp.zeros((1, 3, img, img), "float32"))
            .astype("bfloat16"))
    elif dtype == "int8":
        from mxnet_tpu.contrib.quantization import quantize_net
        rng = onp.random.default_rng(7)
        calib_n = int(os.environ.get("SLG_CALIB", 4))
        calib = [mx.nd.array(rng.random((4, 3, img, img), dtype="float32"))
                 for _ in range(calib_n)]
        net = quantize_net(net, calib_data=calib, calib_mode="naive")
    return net


def _direct_rate(net, img, in_dtype, batch, reps=3):
    """Reference: direct full-batch forward img/s (no serving layer),
    chain-amortized per benchmark/_timing.py."""
    import mxnet_tpu as mx
    from benchmark._timing import time_chained

    x = mx.nd.array(onp.random.default_rng(0).random(
        (batch, 3, img, img), dtype="float32"))
    if in_dtype == "bfloat16":
        x = x.astype("bfloat16")
    net.hybridize()
    sec = time_chained(lambda a: net(a), (x,), reps=reps, chain=10)
    return batch / sec


def _queue_wait_fields(snap):
    """Queue-wait decomposition of the latency tail, from a stats snapshot."""
    qw_p99 = snap["queue_wait"]["p99_us"]
    lat_p99 = snap["latency"]["p99_us"]
    return {
        "queue_wait_p99_ms": round(qw_p99 / 1e3, 2),
        "queue_wait_share_p99": round(qw_p99 / lat_p99, 3) if lat_p99 else 0.0,
    }


def _percentiles(lat_ms):
    lat_ms = sorted(lat_ms)
    n = len(lat_ms)
    if not n:
        return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
    return {
        "p50_ms": round(lat_ms[n // 2], 2),
        "p95_ms": round(lat_ms[min(n - 1, int(n * 0.95))], 2),
        "p99_ms": round(lat_ms[min(n - 1, int(n * 0.99))], 2),
    }


def _metric_total(name):
    """Sum a metric family across its label series (0.0 if unregistered)."""
    from mxnet_tpu import telemetry
    fam = telemetry.REGISTRY.get(name)
    if fam is None:
        return 0.0
    return float(sum(c.value for _, c in fam._series()))


def _run_level(server, names, img, np_dtype, conc, seconds, weights,
               deadline_ms=None):
    """Closed loop: ``conc`` clients, one in-flight request each, assigned
    to tenants proportionally to ``weights``. Returns (aggregate, per_tenant)
    where per_tenant maps name -> {latencies, served}. ``deadline_ms`` rides
    each request end-to-end; a DeadlineExceeded is counted as a miss, not a
    served request."""
    from mxnet_tpu.serving import DeadlineExceeded

    stop_at = time.perf_counter() + seconds
    lock = threading.Lock()
    per = {n: {"lat_ms": [], "served": 0, "misses": 0} for n in names}
    rng = onp.random.default_rng(42)
    frames = [rng.random((3, img, img), dtype="float32").astype(np_dtype)
              for _ in range(8)]
    # proportional client->tenant assignment (every tenant gets >= 1 client
    # when conc >= len(names))
    total_w = sum(weights)
    assign = []
    for ci in range(conc):
        acc = 0.0
        pick = names[-1]
        for name, w in zip(names, weights):
            acc += w / total_w
            if (ci + 0.5) / conc <= acc:
                pick = name
                break
        assign.append(pick)

    def client(ci):
        name = assign[ci]
        i = 0
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            try:
                server.predict(name, frames[(ci + i) % len(frames)],
                               deadline_ms=deadline_ms, timeout=120)
            except DeadlineExceeded:
                with lock:
                    per[name]["misses"] += 1
                i += 1
                continue
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                per[name]["lat_ms"].append(dt)
                per[name]["served"] += 1
            i += 1

    t_start = time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,)) for c in range(conc)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    all_lat = [d for v in per.values() for d in v["lat_ms"]]
    agg = {"img_s": round(sum(v["served"] for v in per.values()) / wall, 1),
           "requests": len(all_lat)}
    if deadline_ms is not None:
        agg["deadline_ms"] = deadline_ms
        agg["deadline_misses"] = sum(v["misses"] for v in per.values())
    agg.update(_percentiles(all_lat))
    return agg, per


def _run_decode(args):
    """Generative phase: a small TransformerLM behind the paged-KV decode
    path under multi-tenant closed-loop load. One aggregate JSON row
    (``"decode": true``) plus one per-tenant row; the aggregate carries the
    round-16 gate metrics ``tok_s_chip`` and ``intertoken_p99_ms``."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import serving
    from mxnet_tpu.gluon.model_zoo.bert import TransformerLM

    conc, seconds = args.dec_clients, args.dec_seconds
    seq_len, max_new = args.dec_seq, args.dec_new
    onp.random.seed(0)
    lm = TransformerLM(num_layers=2, units=32, hidden_size=64, num_heads=2,
                       vocab_size=64, max_length=seq_len)
    lm.initialize(mx.init.Normal(0.5))
    eng = serving.DecodeEndpoint("loadgen_lm", lm, max_seq_len=seq_len,
                                 max_batch_size=max(2, conc))
    eng.warmup()
    compiles_warm = eng.stats.snapshot()["counters"]["compiles"]
    sched = serving.DecodeScheduler(eng, poll_s=0.002) \
        .add_tenant("gold", slo_ms=20.0).add_tenant("bulk", slo_ms=200.0)
    sched.start()

    lock = threading.Lock()
    per = {t: {"gaps_ms": [], "tokens": 0, "seqs": 0}
           for t in ("gold", "bulk")}
    stop_at = time.perf_counter() + seconds

    def client(ci):
        tenant = "gold" if ci % 2 == 0 else "bulk"
        rng = onp.random.default_rng(100 + ci)
        while time.perf_counter() < stop_at:
            plen = int(rng.integers(2, max(3, seq_len // 4)))
            prompt = [int(t) for t in rng.integers(1, 64, size=plen)]
            budget = int(rng.integers(max(1, max_new // 2), max_new + 1))
            stream = sched.submit(prompt, max_new_tokens=budget,
                                  tenant=tenant)
            gaps, n, t_prev = [], 0, None
            for _ in stream:             # client-observed inter-token gaps
                now = time.perf_counter()
                if t_prev is not None:
                    gaps.append((now - t_prev) * 1e3)
                t_prev = now
                n += 1
            with lock:
                per[tenant]["gaps_ms"].extend(gaps)
                per[tenant]["tokens"] += n
                per[tenant]["seqs"] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(conc)]
    for t in threads:
        t.start()
    occ_peak = occ_sum = 0.0
    occ_n = 0
    while any(t.is_alive() for t in threads):
        o = eng.pool.occupancy()
        occ_peak, occ_sum, occ_n = max(occ_peak, o), occ_sum + o, occ_n + 1
        time.sleep(0.02)
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    sched.stop(drain=True)

    chips = max(1, jax.device_count())
    tokens = sum(v["tokens"] for v in per.values())
    all_gaps = [g for v in per.values() for g in v["gaps_ms"]]
    snap = eng.stats.snapshot()
    assert snap["counters"]["compiles"] == compiles_warm, \
        "decode traffic recompiled beyond warmup buckets"
    row = {"decode": True, "clients": conc, "tenants": 2, "chips": chips,
           "seconds": round(wall, 2),
           "seqs": sum(v["seqs"] for v in per.values()), "tokens": tokens,
           "tok_s_chip": round(tokens / wall / chips, 1)}
    row.update({f"intertoken_{k}": v
                for k, v in _percentiles(all_gaps).items()})
    row.update({
        "kv_occupancy_peak": round(occ_peak, 3),
        "kv_occupancy_mean": round(occ_sum / max(1, occ_n), 3),
        "kv_pages": eng.pool.num_pages - 1,
        "prefill_p50_ms": round(snap["prefill"]["p50_us"] / 1e3, 2),
        "step_p50_ms": round(snap["step"]["p50_us"] / 1e3, 2),
        "compiles": compiles_warm,
    })
    print(json.dumps(row), flush=True)
    for tenant in ("gold", "bulk"):     # the per-tenant inter-token table
        trow = {"decode": True, "tenant": tenant,
                "seqs": per[tenant]["seqs"],
                "tokens": per[tenant]["tokens"]}
        trow.update({f"intertoken_{k}": v
                     for k, v in _percentiles(per[tenant]["gaps_ms"]).items()})
        print(json.dumps(trow), flush=True)


def _run_dlrm(args):
    """Recommendation phase: the model-zoo DLRM behind the dynamic batcher —
    the huge-QPS / tiny-compute profile (all embedding-memory traffic,
    almost no FLOPs) that stresses admission/batching from the opposite end
    of the spectrum from decode. Multi-input endpoint: (dense float32,
    sparse int32 ids) per request. One aggregate JSON row (``"dlrm": true``)
    carrying served req/s, embedding lookups/s (req/s x fields), the
    latency/queue-wait decomposition, and the observed hot-row hit rate of
    the request stream."""
    import mxnet_tpu as mx
    from mxnet_tpu import serving
    from mxnet_tpu.embedding import HotnessTracker
    from mxnet_tpu.gluon.model_zoo import dlrm as dlrm_zoo

    conc, seconds = args.dlrm_clients, args.dlrm_seconds
    vocab, fields, dense_in = 1 << 14, 8, 13
    onp.random.seed(0)
    net = dlrm_zoo.dlrm_tiny(vocab_size=vocab, num_fields=fields,
                             dense_in=dense_in)
    net.initialize(mx.init.Normal(0.1))
    server = serving.InferenceServer(batch_timeout_ms=args.timeout_ms,
                                     max_queue=args.max_batch * 8)
    ep = serving.ModelEndpoint(
        "loadgen_dlrm", net, input_shapes=((dense_in,), (fields,)),
        dtype=("float32", "int32"), max_batch_size=args.max_batch)
    server.register(ep)
    compiles_warm = ep.stats.counters["compiles"]
    server.start()

    # skewed request stream (frequency-sorted vocab head), pre-generated
    rng = onp.random.default_rng(7)
    n_frames = 64
    head = max(1, vocab // 16)
    hot = rng.integers(0, head, (n_frames, fields))
    cold = rng.integers(0, vocab, (n_frames, fields))
    pick = rng.random((n_frames, fields)) < 0.7
    idx_frames = onp.where(pick, hot, cold).astype("int32")
    dense_frames = rng.standard_normal(
        (n_frames, dense_in)).astype("float32")
    tracker = HotnessTracker("loadgen_dlrm", vocab)
    tracker.observe(idx_frames)

    lock = threading.Lock()
    lat_ms, served = [], [0]
    stop_at = time.perf_counter() + seconds

    def client(ci):
        i = ci
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            server.predict("loadgen_dlrm",
                           (dense_frames[i % n_frames],
                            idx_frames[i % n_frames]), timeout=120)
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                lat_ms.append(dt)
                served[0] += 1
            i += 1

    t_start = time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(conc)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    server.stop(drain=True)
    snap = serving.stats()["loadgen_dlrm"]
    assert snap["counters"]["compiles"] == compiles_warm, \
        "dlrm traffic recompiled beyond warmup buckets"
    qps = served[0] / wall
    row = {"dlrm": True, "clients": conc, "seconds": round(wall, 2),
           "requests": served[0], "req_s": round(qps, 1),
           "emb_lookups_s": round(qps * fields, 1),
           "fields": fields, "vocab": vocab,
           "hot_row_hit_rate": round(tracker.hot_hit_rate(), 3),
           "occupancy": round(snap["batch_occupancy"], 3),
           "compiles": compiles_warm}
    row.update(_percentiles(lat_ms))
    row.update(_queue_wait_fields(snap))
    print(json.dumps(row), flush=True)
    serving.unregister("loadgen_dlrm")


def _tail_mlp(in_dim=8, out_dim=4, seed=0):
    """Identically-seeded tiny MLP for the tail phases — every replica
    serves bitwise-identical outputs, so hedging is numerics-safe."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import nn

    mx.random.seed(seed)
    onp.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(out_dim))
    net.initialize(mx.init.Xavier())
    net(nd.array(onp.zeros((2, in_dim), "float32")))
    return net


def _run_hedge(args):
    """Tail-tolerance hedge phase: a burst of deadline-carrying requests
    over a two-replica ServingPool while an injected ``replica_straggler``
    stalls the step boundary. Emits one ``{"tailguard": "hedge", ...}`` row
    with the perf-gate metrics: hedge rate, win rate, the wasted-duplicate-
    work share (bounded by the hedge token bucket) and budget
    exhaustions."""
    from mxnet_tpu import config, serving
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.serving import DeadlineExceeded, tailguard

    in_dim, n = 8, args.tail_requests
    deadline_ms = args.deadline_ms or 30000.0

    def factory(rid):
        srv = serving.InferenceServer(batch_timeout_ms=1.0,
                                      max_queue=max(256, n * 8))
        srv.register(serving.ModelEndpoint(
            "loadgen_hedge", _tail_mlp(in_dim), input_shapes=(in_dim,),
            max_batch_size=4))
        return srv

    saved = config.get("MXNET_HEDGE_DELAY_MIN_MS")
    config.set("MXNET_HEDGE_DELAY_MIN_MS", 25.0)
    tailguard.hedge_reset()
    ratio = float(config.get("MXNET_HEDGE_BUDGET_RATIO"))
    before = {m: _metric_total(m) for m in
              ("mxtpu_hedge_requests_total", "mxtpu_hedge_wins_total",
               "mxtpu_hedge_wasted_total", "mxtpu_hedge_cancelled_total",
               "mxtpu_hedge_budget_exhausted_total")}
    xs = onp.random.default_rng(1).standard_normal(
        (n, in_dim)).astype("float32")
    pool = serving.ServingPool(factory, initial_replicas=2)
    lat_ms, misses, errors = [], 0, []
    t0 = time.perf_counter()
    try:
        with faults.inject("replica_straggler", site="serving_dispatch",
                           every_n=5, seconds=0.2) as inj:
            futs = [pool.submit("loadgen_hedge", xs[i],
                                deadline_ms=deadline_ms) for i in range(n)]
            for f in futs:
                t1 = time.perf_counter()
                try:
                    f.result(timeout=120)
                    lat_ms.append((time.perf_counter() - t1) * 1e3)
                except DeadlineExceeded:
                    misses += 1
                except Exception as e:
                    errors.append(repr(e))
        stalls = inj.fires
    finally:
        config.set("MXNET_HEDGE_DELAY_MIN_MS", saved)
        tailguard.hedge_reset()
        pool.stop(drain=True)
        serving.unregister("loadgen_hedge")
    wall = time.perf_counter() - t0
    d = {m: _metric_total(m) - before[m] for m in before}
    hedges = d["mxtpu_hedge_requests_total"]
    row = {"tailguard": "hedge", "requests": n, "replicas": 2,
           "seconds": round(wall, 2), "stalls": stalls,
           "deadline_ms": deadline_ms, "deadline_misses": misses,
           "client_errors": len(errors),
           "hedge_rate": round(hedges / n, 4),
           "hedge_win_rate": round(
               d["mxtpu_hedge_wins_total"] / max(1.0, hedges), 4),
           "hedge_wasted_work_pct": round(
               100.0 * d["mxtpu_hedge_wasted_total"] / n, 3),
           "hedge_cancelled": d["mxtpu_hedge_cancelled_total"],
           "hedge_budget_exhausted": d["mxtpu_hedge_budget_exhausted_total"],
           "hedge_budget_ratio": ratio}
    row.update(_percentiles(lat_ms))
    print(json.dumps(row), flush=True)


def _run_storm(args):
    """Tail-tolerance storm phase: a bounded retryable ``net_drop`` storm
    at a single-host FrontDoor. The frontdoor retry budget must absorb
    every drop — ``storm_client_error_rate`` is the ==0 perf-gate row —
    and ``storm_amplification`` (fault-site attempts per request) shows the
    budget holding re-send traffic near 1x."""
    from mxnet_tpu import serving
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.serving.fabric import FrontDoor
    from mxnet_tpu.serving.tailguard import RETRY_BUDGETS

    in_dim, n = 8, args.tail_requests

    def factory(name):
        srv = serving.InferenceServer(batch_timeout_ms=1.0,
                                      max_queue=max(256, n * 8))
        srv.register(serving.ModelEndpoint(
            "loadgen_storm", _tail_mlp(in_dim), input_shapes=(in_dim,),
            max_batch_size=4))
        srv.start()
        return srv

    RETRY_BUDGETS.reset()       # the production-default budget knobs
    ex_before = _metric_total("mxtpu_retry_budget_exhausted_total")
    xs = onp.random.default_rng(2).standard_normal(
        (n, in_dim)).astype("float32")
    fd = FrontDoor([f"storm_{os.getpid()}"], factory, spawn_agents=False,
                   supervise=False)
    lat_ms, errors = [], []
    t0 = time.perf_counter()
    try:
        # the drop volume stays under the budget floor, so absorption —
        # not shed — is the contract being measured
        with faults.inject("net_drop", site="frontdoor", p=0.6,
                           times=max(1, n // 5), seed=3) as inj:
            for i in range(n):
                t1 = time.perf_counter()
                try:
                    fd.submit("loadgen_storm", xs[i],
                              deadline_ms=args.deadline_ms) \
                        .result(timeout=120)
                    lat_ms.append((time.perf_counter() - t1) * 1e3)
                except Exception as e:
                    errors.append(repr(e))
            attempts, drops = inj.calls, inj.fires
    finally:
        fd.stop(drain=True)
        serving.unregister("loadgen_storm")
        RETRY_BUDGETS.reset()
    wall = time.perf_counter() - t0
    row = {"tailguard": "storm", "requests": n, "seconds": round(wall, 2),
           "drops_absorbed": drops,
           "storm_amplification": round(attempts / float(n), 3),
           "storm_client_error_rate": round(len(errors) / float(n), 4),
           "client_errors": len(errors),
           "retry_budget_exhausted": _metric_total(
               "mxtpu_retry_budget_exhausted_total") - ex_before}
    row.update(_percentiles(lat_ms))
    print(json.dumps(row), flush=True)


def _run_restart_child(args, phase):
    """One restart-benchmark phase in THIS process: build the dense (and
    optionally decode) endpoints, start the server, serve one request each,
    and report time-from-entry plus the compile-ledger split (fresh
    compiles vs executable-cache hits). Weights and inputs are seeded so
    the first-request outputs are bitwise-comparable across phases."""
    import hashlib
    t0 = time.perf_counter()
    import mxnet_tpu as mx
    from mxnet_tpu import serving, telemetry

    onp.random.seed(0)
    net = _build_net(args.model, args.classes, args.img, "f32")
    ep = serving.ModelEndpoint(f"{args.model}_restart", net,
                               input_shapes=(3, args.img, args.img),
                               dtype="float32",
                               max_batch_size=args.max_batch)
    server = serving.InferenceServer(batch_timeout_ms=args.timeout_ms,
                                     max_queue=args.max_batch * 8)
    server.register(ep)          # warmup: compiles cold, deserializes warm
    server.start()
    frame = onp.arange(3 * args.img * args.img, dtype="float32") \
        .reshape(3, args.img, args.img) / (3 * args.img * args.img)
    out = server.predict(ep.name, frame, timeout=120)
    dense_t = time.perf_counter() - t0
    dense_digest = hashlib.sha256(
        onp.ascontiguousarray(out.asnumpy()).tobytes()).hexdigest()

    fab_t = fab_digest = None
    if args.fabric:
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.serving.fabric import ShardedEndpoint, plan_slices
        mx.random.seed(0)
        onp.random.seed(0)
        fnet = nn.HybridSequential()
        with fnet.name_scope():
            fnet.add(nn.Dense(32, activation="relu"), nn.Dense(8))
        fnet.initialize(mx.init.Xavier())
        fnet(mx.nd.array(onp.zeros((2, 16), "float32")))
        sep = ShardedEndpoint("restart_sharded", fnet, input_shapes=(16,),
                              dtype="float32", max_batch_size=4,
                              slice_spec=plan_slices([2])[0])
        server.register(sep)     # warmup: compiles cold, deserializes warm
        fout = server.predict("restart_sharded",
                              onp.arange(16, dtype="float32") / 16.0,
                              timeout=120)
        fab_t = time.perf_counter() - t0
        fab_digest = hashlib.sha256(
            onp.ascontiguousarray(fout.asnumpy()).tobytes()).hexdigest()

    dec_t = dec_digest = None
    if args.decode:
        from mxnet_tpu.gluon.model_zoo.bert import TransformerLM
        onp.random.seed(0)
        lm = TransformerLM(num_layers=2, units=32, hidden_size=64,
                           num_heads=2, vocab_size=64,
                           max_length=args.dec_seq)
        lm.initialize(mx.init.Normal(0.5))
        eng = serving.DecodeEndpoint("restart_lm", lm,
                                     max_seq_len=args.dec_seq,
                                     max_batch_size=2)
        server.register_generator(eng)
        toks = list(server.generate("restart_lm", [1, 2, 3, 4],
                                    max_new_tokens=4))
        dec_t = time.perf_counter() - t0
        dec_digest = hashlib.sha256(
            onp.asarray(toks, "int64").tobytes()).hexdigest()

    cls = telemetry.compile_ledger.summary()
    server.stop(drain=True)
    serving.unregister(ep.name)
    if args.fabric:
        serving.unregister("restart_sharded")
    if args.decode:
        serving.unregister("restart_lm")
    print(json.dumps({
        "restart_child": phase,
        "restart_to_first_request_s": round(
            max(dense_t, dec_t or 0.0, fab_t or 0.0), 3),
        "dense_first_s": round(dense_t, 3),
        "fabric_first_s": round(fab_t, 3) if fab_t is not None else None,
        "fabric_digest": fab_digest,
        "decode_first_s": round(dec_t, 3) if dec_t is not None else None,
        "compiles": cls["compiles"],
        "cache_hits": cls["cache_hits"],
        "fresh_compiles": cls["compiles"] - cls["cache_hits"],
        "duplicates": cls["duplicates"],
        "dense_digest": dense_digest,
        "decode_digest": dec_digest,
    }), flush=True)
    return 0


def _run_restart(args):
    """Parent half of ``--restart``: run the child phase twice against one
    shared executable-cache + ledger directory (cold populates, warm must
    compile nothing) and emit the perf-gate row."""
    import subprocess
    import tempfile
    cache_dir = tempfile.mkdtemp(prefix="slg-exec-cache-")
    ledger_dir = tempfile.mkdtemp(prefix="slg-ledger-")
    child_flags = ["--model", args.model, "--img", str(args.img),
                   "--classes", str(args.classes),
                   "--max-batch", str(args.max_batch),
                   "--timeout-ms", str(args.timeout_ms),
                   "--dec-seq", str(args.dec_seq),
                   "--dec-new", str(args.dec_new)]
    if args.fabric:
        child_flags.append("--fabric")
    rows = {}
    # both restart phases join the parent's trace journey: a child's root
    # spans adopt MXNET_TRACE_ID, and with a spool dir configured each
    # phase's spans land in its own spool-<pid>.jsonl next to the parent's
    from mxnet_tpu import telemetry
    from mxnet_tpu import config as _config
    # active span > operator-set MXNET_TRACE_ID > fresh id — so a harness
    # that pinned a trace id for the whole run keeps one journey
    trace_id = (telemetry.current_trace_id()
                or str(_config.get("MXNET_TRACE_ID", "") or "")
                or telemetry.new_trace_id())
    spool_dir = str(_config.get("MXNET_SPAN_SPOOL_DIR", "") or "")
    for phase in ("cold", "warm"):
        env = dict(os.environ)
        env["MXNET_EXEC_CACHE_DIR"] = cache_dir
        env["MXNET_COMPILE_LEDGER_DIR"] = ledger_dir
        # only AOT serving compiles are the contract; keep the eager jit
        # cache un-instrumented so op-level compiles don't muddy the count
        env["MXNET_COMPILE_LEDGER_EAGER"] = "0"
        env["SLG_DECODE"] = "1" if args.decode else "0"
        if args.fabric and "xla_force_host_platform_device_count" \
                not in env.get("XLA_FLAGS", ""):
            # the 2-device slice needs >1 host device; the flag only
            # multiplies the CPU platform, so it is harmless on real chips
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                " --xla_force_host_platform_device_count=8"
                                ).strip()
        env["MXNET_TRACE_ID"] = trace_id
        if spool_dir:
            env["MXNET_SPAN_SPOOL_DIR"] = spool_dir
        cmd = [sys.executable, os.path.abspath(__file__),
               "--restart-child", phase] + child_flags
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True)
        row = None
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    cand = json.loads(line)
                except ValueError:
                    continue
                if cand.get("restart_child") == phase:
                    row = cand
        if proc.returncode != 0 or row is None:
            sys.stderr.write(proc.stdout[-2000:] + proc.stderr[-2000:])
            raise SystemExit(f"restart child ({phase}) failed "
                             f"rc={proc.returncode}")
        rows[phase] = row
        print(json.dumps({"restart": phase,
                          **{k: row[k] for k in
                             ("restart_to_first_request_s", "dense_first_s",
                              "fabric_first_s", "decode_first_s",
                              "compiles", "cache_hits",
                              "fresh_compiles", "duplicates")}}),
              flush=True)
    cold, warm = rows["cold"], rows["warm"]
    assert warm["fresh_compiles"] == 0, \
        f"warm restart performed {warm['fresh_compiles']} fresh compiles " \
        "(executable cache missed)"
    assert warm["duplicates"] == 0, \
        "warm restart tripped the recompile-storm counter " \
        f"({warm['duplicates']} duplicates)"
    assert warm["cache_hits"] == cold["compiles"], \
        f"warm hit {warm['cache_hits']} entries but cold compiled " \
        f"{cold['compiles']}"
    for k in ("dense_digest", "fabric_digest", "decode_digest"):
        assert cold[k] == warm[k], \
            f"{k}: warm first-request output differs from cold " \
            f"({cold[k]} vs {warm[k]})"
    warm_s, cold_s = (warm["restart_to_first_request_s"],
                      cold["restart_to_first_request_s"])
    print(json.dumps({
        "restart_to_first_request_s": warm_s,
        "restart_cold_s": cold_s,
        "restart_speedup": round(cold_s / warm_s, 2) if warm_s else None,
        "warm_fresh_compiles": warm["fresh_compiles"],
        "warm_cache_hits": warm["cache_hits"],
        "outputs_bitwise_equal": True,
    }), flush=True)
    return 0


def _parse_args():
    env = os.environ.get
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--tenants", type=int, default=1)
    p.add_argument("--mix", default="",
                   help="comma client-traffic weights per tenant")
    p.add_argument("--slo-ms", default="",
                   help="comma per-tenant scheduling SLO (register slo_ms)")
    p.add_argument("--serial", action="store_true",
                   help="pipeline=False: serial prepare-then-step dispatch")
    p.add_argument("--model", default=env("SLG_MODEL", "resnet50_v1"))
    p.add_argument("--img", type=int, default=int(env("SLG_IMG", 224)))
    p.add_argument("--classes", type=int, default=int(env("SLG_CLASSES", 1000)))
    p.add_argument("--dtypes", default=env("SLG_DTYPES", "bf16,int8"))
    p.add_argument("--conc", default=env("SLG_CONC", "1,2,4,8,16"))
    p.add_argument("--seconds", type=float, default=float(env("SLG_SECONDS", 5)))
    p.add_argument("--max-batch", type=int,
                   default=int(env("SLG_MAX_BATCH", 32)))
    p.add_argument("--timeout-ms", type=float,
                   default=float(env("SLG_TIMEOUT_MS", 5)))
    p.add_argument("--decode", action="store_true",
                   default=env("SLG_DECODE", "") not in ("", "0"),
                   help="also run the generative decode phase "
                        "(env SLG_DECODE=1)")
    p.add_argument("--dec-clients", type=int,
                   default=int(env("SLG_DEC_CLIENTS", 4)))
    p.add_argument("--dec-seconds", type=float,
                   default=float(env("SLG_DEC_SECONDS",
                                     env("SLG_SECONDS", 5))))
    p.add_argument("--dec-seq", type=int, default=int(env("SLG_DEC_SEQ", 64)))
    p.add_argument("--dec-new", type=int, default=int(env("SLG_DEC_NEW", 16)))
    p.add_argument("--dlrm", action="store_true",
                   default=env("SLG_DLRM", "") not in ("", "0"),
                   help="run the DLRM recommendation phase after the image "
                        "sweep (env SLG_DLRM=1)")
    p.add_argument("--dlrm-clients", type=int,
                   default=int(env("SLG_DLRM_CLIENTS", 8)))
    p.add_argument("--dlrm-seconds", type=float,
                   default=float(env("SLG_DLRM_SECONDS",
                                     env("SLG_SECONDS", 5))))
    p.add_argument("--deadline-ms", type=float,
                   default=float(env("SLG_DEADLINE_MS", 0)) or None,
                   help="end-to-end deadline per request; sweep rows gain "
                        "deadline_misses (env SLG_DEADLINE_MS, 0 = none)")
    p.add_argument("--hedge", action="store_true",
                   default=env("SLG_HEDGE", "") not in ("", "0"),
                   help="run the hedged-burst tail phase (env SLG_HEDGE=1)")
    p.add_argument("--storm", action="store_true",
                   default=env("SLG_STORM", "") not in ("", "0"),
                   help="run the retry-storm tail phase (env SLG_STORM=1)")
    p.add_argument("--tail-requests", type=int,
                   default=int(env("SLG_TAIL_REQUESTS", 60)),
                   help="burst size for the hedge/storm phases")
    p.add_argument("--restart", action="store_true",
                   help="cold/warm restart-to-first-request benchmark "
                        "instead of the load sweep")
    p.add_argument("--fabric", action="store_true",
                   default=env("SLG_FABRIC", "0") == "1",
                   help="with --restart: run a mesh-sharded endpoint "
                        "(2-device slice) through both phases too")
    p.add_argument("--restart-child", default="", help=argparse.SUPPRESS)
    return p.parse_args()


def main():
    args = _parse_args()
    if args.restart_child:
        return _run_restart_child(args, args.restart_child)
    if args.restart:
        return _run_restart(args)
    return _run_sweep(args)


def _run_sweep(args):
    model, img, classes = args.model, args.img, args.classes
    dtypes = [d for d in args.dtypes.split(",")
              if d.strip() and d.strip() != "none"]
    conc_levels = [int(c) for c in str(args.conc).split(",")]
    seconds, max_batch = args.seconds, args.max_batch
    timeout_ms = args.timeout_ms
    tenants = max(1, args.tenants)
    weights = [float(w) for w in args.mix.split(",")] if args.mix \
        else [1.0] * tenants
    if len(weights) != tenants:
        raise SystemExit(f"--mix needs {tenants} weights, got {len(weights)}")
    slo_ms = [float(s) for s in args.slo_ms.split(",")] if args.slo_ms \
        else [None] * tenants
    if len(slo_ms) != tenants:
        raise SystemExit(f"--slo-ms needs {tenants} values, got {len(slo_ms)}")

    import mxnet_tpu as mx  # noqa: F401  (context/init side effects)
    from mxnet_tpu import serving

    for dtype in dtypes:
        dtype = dtype.strip()
        in_dtype = "bfloat16" if dtype == "bf16" else "float32"
        server = serving.InferenceServer(batch_timeout_ms=timeout_ms,
                                         max_queue=max_batch * 8,
                                         pipeline=not args.serial)
        names, eps, nets = [], [], []
        for ti in range(tenants):
            net = _build_net(model, classes, img, dtype)
            name = f"{model}_{dtype}" if tenants == 1 \
                else f"{model}_{dtype}_t{ti}"
            ep = serving.ModelEndpoint(name, net, input_shapes=(3, img, img),
                                       dtype=in_dtype,
                                       max_batch_size=max_batch)
            server.register(ep, slo_ms=slo_ms[ti])   # warms every bucket
            names.append(name)
            eps.append(ep)
            nets.append(net)
        compiles_after_warmup = {n: e.stats.counters["compiles"]
                                 for n, e in zip(names, eps)}
        server.start()
        np_dtype = eps[0].np_dtypes[0]
        try:
            for conc in conc_levels:
                agg, per = _run_level(server, names, img, np_dtype, conc,
                                      seconds, weights,
                                      deadline_ms=args.deadline_ms)
                snaps = serving.stats()
                agg.update({
                    "dtype": dtype, "conc": conc, "tenants": tenants,
                    "pipeline": not args.serial,
                    "occupancy": round(statistics.mean(
                        snaps[n]["batch_occupancy"] for n in names), 3),
                    "compiles": sum(snaps[n]["counters"]["compiles"]
                                    for n in names),
                    "batches": sum(snaps[n]["counters"]["batches"]
                                   for n in names),
                })
                # queue-wait decomposition over all tenants' requests
                agg.update(_queue_wait_fields(
                    snaps[names[0]] if tenants == 1 else
                    max((snaps[n] for n in names),
                        key=lambda s: s["latency"]["p99_us"])))
                print(json.dumps(agg), flush=True)
                if tenants > 1:
                    for name in names:        # the per-tenant latency table
                        row = {"tenant": name, "conc": conc,
                               "served": per[name]["served"]}
                        row.update(_percentiles(per[name]["lat_ms"]))
                        row.update(_queue_wait_fields(snaps[name]))
                        row["shed"] = snaps[name]["shed"]
                        print(json.dumps(row), flush=True)
        finally:
            server.stop(drain=True)
        snaps = serving.stats()
        for name in names:
            assert snaps[name]["counters"]["compiles"] == \
                compiles_after_warmup[name], \
                "serving traffic recompiled beyond warmup buckets"
        direct = _direct_rate(nets[0], img, in_dtype, max_batch)
        print(json.dumps({
            "dtype": dtype, "summary": True,
            "direct_b{}_img_s".format(max_batch): round(direct, 1),
            "buckets": list(eps[0].buckets),
            "compiles": sum(snaps[n]["counters"]["compiles"] for n in names),
            "prep_overlap_ratio": round(
                server.health()["prep_overlap_ratio"], 3),
        }), flush=True)
        for name in names:
            serving.unregister(name)

    if args.decode:
        _run_decode(args)

    if args.dlrm:
        _run_dlrm(args)

    if args.hedge:
        _run_hedge(args)

    if args.storm:
        _run_storm(args)

    # one whole-process telemetry snapshot: serving latency histograms,
    # executable-cache hit/miss/compile-seconds, queue depth / occupancy,
    # train-step + dataloader families (zero here), device memory gauges
    from mxnet_tpu import telemetry
    tsnap = telemetry.snapshot()
    print(json.dumps({"telemetry_summary": telemetry.summary_line(),
                      "metric_families": len(tsnap["metrics"])}), flush=True)
    # compile-ledger rollup: every serving-bucket compile of the run, the
    # distinct programs behind them, and the seconds re-spent on programs
    # the process had already compiled (what a persistent cache would save)
    cls = telemetry.compile_ledger.summary()
    print(json.dumps({"compile_ledger": {
        "compiles": cls["compiles"],
        "distinct_fingerprints": cls["distinct_fingerprints"],
        "duplicates": cls["duplicates"],
        "dup_waste_s": cls["dup_waste_s"],
        "wall_s": round(cls["lower_s"] + cls["compile_s"], 3),
    }}), flush=True)
    dump_path = os.environ.get("SLG_TELEMETRY", "")
    if dump_path:
        telemetry.dump(dump_path)
        print(json.dumps({"telemetry_snapshot": dump_path}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
