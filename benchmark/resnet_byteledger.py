"""ResNet-50 b128 HBM byte-ledger experiments (VERDICT r4 #1).

The round-4 profile named the remaining non-conv traffic: thousands of small
f32[256] param copy-starts + bf16 {0,1,3,2} layout permutes (~5 GB/step) and
f32 BN-gradient reductions riding the conv fusions. This harness measures the
two named levers, separately and together:

  RBL_MODE=baseline   the shipped configuration (bench.py path)
  RBL_MODE=auto       param_format="auto" (XLA-chosen carried-state layouts)
  RBL_MODE=bnbf16     MXNET_BN_BF16_REDUCE=1 (bf16 normalize+backward)
  RBL_MODE=both       both levers

Prints one JSON line: {"mode":..., "img_s":..., "ms_step":...}.
Optional RBL_PROFILE=1 adds the per-category device-time/byte breakdown.
"""
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main():
    mode = os.environ.get("RBL_MODE", "baseline")
    batch = int(os.environ.get("RBL_BATCH", 128))
    k = int(os.environ.get("RBL_K", 20))
    calls = int(os.environ.get("RBL_CALLS", 2))

    import mxnet_tpu as mx
    # every mode pins BOTH BN flags explicitly so the ablation table stays
    # reproducible after the round-5 default flip (r5 review):
    #   baseline/auto = round-4 shipped config (two-pass f32 promote)
    #   onepass32     = one-pass f32 moments only
    #   bnbf16/both   = the full bf16 fast path (now the package default)
    mx.config.set("MXNET_BN_BF16_REDUCE", mode in ("bnbf16", "both"))
    mx.config.set("MXNET_BN_ONEPASS", mode == "onepass32")
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model("resnet50_v1", classes=1000)
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(onp.zeros((1, 3, 224, 224), "float32")))

    mesh = parallel.make_mesh({"dp": 1})
    step = parallel.ParallelTrainStep(
        net, gloss.SoftmaxCrossEntropyLoss(),
        mx.optimizer.SGD(learning_rate=0.05, momentum=0.9), mesh,
        compute_dtype="bfloat16",
        param_format="auto" if mode in ("auto", "both") else None)

    rng = onp.random.default_rng(0)
    placed = step.place_batch_n(
        rng.random((k, batch, 3, 224, 224), dtype="float32").astype("bfloat16"),
        rng.integers(0, 1000, (k, batch)).astype("float32"))

    out = step.step_n(*placed)          # compile + warm
    float(out.asnumpy()[-1])
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(calls):
            out = step.step_n(*placed)
        float(out.asnumpy()[-1])
        times.append(time.perf_counter() - t0)
    dt = statistics.median(times)
    img_s = batch * k * calls / dt
    print(json.dumps({"mode": mode, "img_s": round(img_s, 1),
                      "ms_step": round(1000 * dt / (k * calls), 2)}),
          flush=True)

    if os.environ.get("RBL_PROFILE") == "1":
        _profile(step, placed)
    return 0


def _profile(step, placed):
    import glob
    import tempfile
    from collections import defaultdict
    import jax

    tmp = tempfile.mkdtemp(prefix="xplane_rbl_")
    with jax.profiler.trace(tmp):
        out = step.step_n(*placed)
        float(out.asnumpy()[-1])
    pb = glob.glob(os.path.join(tmp, "**", "*.xplane.pb"), recursive=True)
    if not pb:
        print("no xplane written", tmp)
        return
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    xs = xplane_pb2.XSpace()
    xs.ParseFromString(open(pb[-1], "rb").read())
    plane = next(p for p in xs.planes if p.name == "/device:TPU:0")
    sm = plane.stat_metadata

    def meta_stats(em):
        out = {}
        for st in em.stats:
            w = st.WhichOneof("value")
            if w:
                out[sm[st.metadata_id].name] = getattr(st, w)
        return out

    em_cache = {mid: (em.name, meta_stats(em))
                for mid, em in plane.event_metadata.items()}
    cats = defaultdict(lambda: [0.0, 0.0, 0])   # ms, bytes, events
    ops = defaultdict(lambda: [0.0, 0.0, 0])
    line = next(l for l in plane.lines if l.name == "XLA Ops")
    for ev in line.events:
        name, stats = em_cache[ev.metadata_id]
        cat = stats.get("hlo_category", "?")
        nbytes = stats.get("bytes_accessed", 0)
        cats[cat][0] += ev.duration_ps / 1e9
        cats[cat][1] += nbytes
        cats[cat][2] += 1
        ops[name][0] += ev.duration_ps / 1e9
        ops[name][1] += nbytes
        ops[name][2] += 1
    n_steps = placed[0].shape[0]
    print(f"  {'hlo category':28s} {'ms/step':>8s} {'GB/step':>8s} "
          f"{'ev/step':>8s}")
    tot_ms = tot_gb = 0.0
    for cat, (ms, b, cnt) in sorted(cats.items(), key=lambda kv: -kv[1][0]):
        print(f"  {cat:28s} {ms / n_steps:8.2f} {b / n_steps / 1e9:8.2f} "
              f"{cnt // n_steps:8d}")
        tot_ms += ms / n_steps
        tot_gb += b / n_steps / 1e9
    print(f"  {'TOTAL':28s} {tot_ms:8.2f} {tot_gb:8.2f}   "
          f"-> {tot_gb / (tot_ms / 1e3):6.0f} GB/s apparent")
    print("  top 15 ops by time:")
    for name, (ms, b, cnt) in sorted(ops.items(), key=lambda kv: -kv[1][0])[:15]:
        print(f"    {ms / n_steps:7.3f} ms {b / n_steps / 1e9:7.3f} GB "
              f"x{cnt // n_steps:<4d} {name[:86]}")


if __name__ == "__main__":
    sys.exit(main())
