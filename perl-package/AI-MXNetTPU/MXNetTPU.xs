/* XS glue: AI::MXNetTPU over the C training ABI (c_train_api.h) — the same
 * layering as the reference perl-package (AI::MXNet over c_api.h). */
#define PERL_NO_GET_CONTEXT
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"
#include "c_train_api.h"

static void* av_to_handles(pTHX_ AV* av, unsigned* n) {
    *n = av_len(av) + 1;
    void** out = (void**)malloc(sizeof(void*) * (*n));
    for (unsigned i = 0; i < *n; ++i) {
        SV** sv = av_fetch(av, i, 0);
        out[i] = INT2PTR(void*, SvIV(*sv));
    }
    return out;
}

MODULE = AI::MXNetTPU  PACKAGE = AI::MXNetTPU

PROTOTYPES: DISABLE

const char*
last_error()
  CODE:
    RETVAL = MXTrGetLastError();
  OUTPUT:
    RETVAL

IV
sym_variable(const char* name)
  CODE:
    void* h = NULL;
    if (MXTrSymbolVariable(name, &h) != 0) croak("%s", MXTrGetLastError());
    RETVAL = PTR2IV(h);
  OUTPUT:
    RETVAL

IV
sym_create(const char* op, const char* name, AV* inputs, const char* attrs_json)
  CODE:
    unsigned n = 0;
    void** ins = (void**)av_to_handles(aTHX_ inputs, &n);
    void* h = NULL;
    int rc = MXTrSymbolCreate(op, name, ins, n, attrs_json, &h);
    free(ins);
    if (rc != 0) croak("%s", MXTrGetLastError());
    RETVAL = PTR2IV(h);
  OUTPUT:
    RETVAL

IV
simple_bind(IV sym, const char* shapes_json)
  CODE:
    void* h = NULL;
    if (MXTrSimpleBind(INT2PTR(void*, sym), shapes_json, &h) != 0)
        croak("%s", MXTrGetLastError());
    RETVAL = PTR2IV(h);
  OUTPUT:
    RETVAL

void
list_arguments(IV exec)
  PPCODE:
    unsigned n = 0;
    char* blob = NULL;
    if (MXTrExecutorListArguments(INT2PTR(void*, exec), &n, &blob) != 0)
        croak("%s", MXTrGetLastError());
    const char* p = blob;
    for (unsigned i = 0; i < n; ++i) {
        XPUSHs(sv_2mortal(newSVpv(p, 0)));
        p += strlen(p) + 1;
    }
    MXTrBufFree(blob);

unsigned
arg_size(IV exec, const char* name)
  CODE:
    unsigned s = 0;
    if (MXTrExecutorArgSize(INT2PTR(void*, exec), name, &s) != 0)
        croak("%s", MXTrGetLastError());
    RETVAL = s;
  OUTPUT:
    RETVAL

unsigned
output_size(IV exec, unsigned index)
  CODE:
    unsigned s = 0;
    if (MXTrExecutorOutputSize(INT2PTR(void*, exec), index, &s) != 0)
        croak("%s", MXTrGetLastError());
    RETVAL = s;
  OUTPUT:
    RETVAL

void
set_arg(IV exec, const char* name, AV* values)
  CODE:
    unsigned n = av_len(values) + 1;
    float* buf = (float*)malloc(sizeof(float) * n);
    for (unsigned i = 0; i < n; ++i) {
        SV** sv = av_fetch(values, i, 0);
        buf[i] = (float)SvNV(*sv);
    }
    int rc = MXTrExecutorSetArg(INT2PTR(void*, exec), name, buf, n);
    free(buf);
    if (rc != 0) croak("%s", MXTrGetLastError());

void
get_output(IV exec, unsigned index)
  PPCODE:
    unsigned s = 0;
    if (MXTrExecutorOutputSize(INT2PTR(void*, exec), index, &s) != 0)
        croak("%s", MXTrGetLastError());
    float* buf = (float*)malloc(sizeof(float) * s);
    if (MXTrExecutorGetOutput(INT2PTR(void*, exec), index, buf, s) != 0) {
        free(buf);
        croak("%s", MXTrGetLastError());
    }
    EXTEND(SP, s);
    for (unsigned i = 0; i < s; ++i)
        PUSHs(sv_2mortal(newSVnv(buf[i])));
    free(buf);

void
get_grad(IV exec, const char* name)
  PPCODE:
    unsigned s = 0;
    if (MXTrExecutorArgSize(INT2PTR(void*, exec), name, &s) != 0)
        croak("%s", MXTrGetLastError());
    float* buf = (float*)malloc(sizeof(float) * s);
    if (MXTrExecutorGetGrad(INT2PTR(void*, exec), name, buf, s) != 0) {
        free(buf);
        croak("%s", MXTrGetLastError());
    }
    EXTEND(SP, s);
    for (unsigned i = 0; i < s; ++i)
        PUSHs(sv_2mortal(newSVnv(buf[i])));
    free(buf);

void
forward(IV exec, int is_train)
  CODE:
    if (MXTrExecutorForward(INT2PTR(void*, exec), is_train) != 0)
        croak("%s", MXTrGetLastError());

void
backward(IV exec)
  CODE:
    if (MXTrExecutorBackward(INT2PTR(void*, exec)) != 0)
        croak("%s", MXTrGetLastError());

IV
optimizer_create(const char* type, const char* params_json)
  CODE:
    void* h = NULL;
    if (MXTrOptimizerCreate(type, params_json, &h) != 0)
        croak("%s", MXTrGetLastError());
    RETVAL = PTR2IV(h);
  OUTPUT:
    RETVAL

void
optimizer_update(IV opt, IV exec, const char* name, int index)
  CODE:
    if (MXTrOptimizerUpdate(INT2PTR(void*, opt), INT2PTR(void*, exec),
                            name, index) != 0)
        croak("%s", MXTrGetLastError());
