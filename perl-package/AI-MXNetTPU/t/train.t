# Train a tiny classifier from Perl through the C training ABI — the
# reference perl-package training-flow shape (Symbol -> bind -> SGD loop).
use strict;
use warnings;
use Test::More tests => 3;
use AI::MXNetTPU;

my $data  = AI::MXNetTPU::Symbol->Variable('data');
my $label = AI::MXNetTPU::Symbol->Variable('softmax_label');
my $fc1 = AI::MXNetTPU::Symbol->create('FullyConnected', 'fc1', [$data],
                                       '{"num_hidden": 16}');
my $act = AI::MXNetTPU::Symbol->create('Activation', 'act1', [$fc1],
                                       '{"act_type": "relu"}');
my $fc2 = AI::MXNetTPU::Symbol->create('FullyConnected', 'fc2', [$act],
                                       '{"num_hidden": 4}');
my $net = AI::MXNetTPU::Symbol->create('SoftmaxOutput', 'softmax',
                                       [$fc2, $label],
                                       '{"normalization": "batch"}');
my $B = 16; my $F = 8; my $C = 4;
my $exec = $net->simple_bind('{"data": [16, 8], "softmax_label": [16]}');
my @args = $exec->list_arguments();
ok(scalar(@args) >= 6, 'arguments listed');

srand(7);
for my $name (@args) {
    next if $name eq 'data' or $name eq 'softmax_label';
    my $n = $exec->arg_size($name);
    my @w = map { ($name =~ /weight/) ? (rand() - 0.5) * 0.6 : 0 } 1 .. $n;
    $exec->set_arg($name, \@w);
}
my $sgd = AI::MXNetTPU::Optimizer->new('sgd', '{"learning_rate": 0.5}');

my (@x, @y);
sub make_batch {
    @x = (); @y = ();
    for my $i (0 .. $B - 1) {
        my $c = $i % $C;
        push @y, $c;
        for my $j (0 .. $F - 1) {
            push @x, (($j % $C) == $c ? 1.0 : 0.0) + (rand() - 0.5) * 0.4;
        }
    }
}

my ($first_acc, $last_acc);
for my $step (0 .. 39) {
    make_batch();
    $exec->set_arg('data', \@x);
    $exec->set_arg('softmax_label', \@y);
    $exec->forward(1);
    $exec->backward();
    my @p = $exec->get_output(0);
    my $correct = 0;
    for my $i (0 .. $B - 1) {
        my ($best, $bv) = (0, $p[$i * $C]);
        for my $c (1 .. $C - 1) {
            if ($p[$i * $C + $c] > $bv) { $best = $c; $bv = $p[$i * $C + $c]; }
        }
        $correct++ if $best == $y[$i];
    }
    my $acc = $correct / $B;
    $first_acc = $acc if $step == 0;
    $last_acc = $acc;
    my $idx = 0;
    for my $name (@args) {
        $sgd->update($exec, $name, $idx)
            unless $name eq 'data' or $name eq 'softmax_label';
        $idx++;
    }
}
ok($last_acc > 0.9, "trained to accuracy $last_acc");
my @g = $exec->get_grad('fc2_weight');
ok(scalar(@g) == $exec->arg_size('fc2_weight'), 'gradients readable');
