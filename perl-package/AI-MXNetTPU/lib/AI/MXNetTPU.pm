package AI::MXNetTPU;
# Perl language binding (parity surface: the reference perl-package
# AI::MXNet Symbol/Executor/Optimizer training flow over the C API; here a
# compact OO layer over the libmxtpu_train C ABI via XS glue, MXNetTPU.xs).
use strict;
use warnings;
our $VERSION = '2.0.0';
require XSLoader;
XSLoader::load('AI::MXNetTPU', $VERSION);

package AI::MXNetTPU::Symbol;
sub Variable {
    my ($class, $name) = @_;
    return bless { h => AI::MXNetTPU::sym_variable($name) }, $class;
}
sub create {
    my ($class, $op, $name, $inputs, $attrs_json) = @_;
    my @hs = map { $_->{h} } @$inputs;
    return bless {
        h => AI::MXNetTPU::sym_create($op, $name, \@hs, $attrs_json // '')
    }, $class;
}
sub simple_bind {
    my ($self, $shapes_json) = @_;
    return bless { h => AI::MXNetTPU::simple_bind($self->{h}, $shapes_json) },
        'AI::MXNetTPU::Executor';
}

package AI::MXNetTPU::Executor;
sub list_arguments { my ($self) = @_;
    return AI::MXNetTPU::list_arguments($self->{h}); }
sub arg_size { my ($self, $n) = @_;
    return AI::MXNetTPU::arg_size($self->{h}, $n); }
sub set_arg { my ($self, $n, $vals) = @_;
    AI::MXNetTPU::set_arg($self->{h}, $n, $vals); }
sub get_output { my ($self, $i) = @_;
    return AI::MXNetTPU::get_output($self->{h}, $i // 0); }
sub get_grad { my ($self, $n) = @_;
    return AI::MXNetTPU::get_grad($self->{h}, $n); }
sub forward { my ($self, $train) = @_;
    AI::MXNetTPU::forward($self->{h}, $train ? 1 : 0); }
sub backward { my ($self) = @_;
    AI::MXNetTPU::backward($self->{h}); }

package AI::MXNetTPU::Optimizer;
sub new {
    my ($class, $type, $params_json) = @_;
    return bless {
        h => AI::MXNetTPU::optimizer_create($type, $params_json // '')
    }, $class;
}
sub update { my ($self, $exec, $name, $index) = @_;
    AI::MXNetTPU::optimizer_update($self->{h}, $exec->{h}, $name, $index); }

1;
