"""Per-tensor statistics monitor (public surface parity:
python/mxnet/monitor.py Monitor — interval/stat_func/pattern/sort/monitor_all,
install/tic/toc/toc_print).

TPU-native design, built on this repo's instrumentation-sink pattern (the same
shape as profiler._dispatch_profiled): a ``Monitor`` is a *sink* of
``(step, name, lazy stat)`` samples organised as per-name channels. Sources
push into the sink; the sink never blocks:

* graph executors: ``Executor.set_monitor_callback`` feeds activations as the
  DAG is walked (``<node>_output``, and ``<node>_input<i>`` with
  ``monitor_all``);
* parameter/aux snapshots: drained from each installed executor's
  ``arg_dict``/``aux_dict`` when a window closes.

Stat values stay device-lazy (one small reduction appended to the async
stream per tensor); nothing synchronises until the window is rendered in
``toc``. This keeps monitoring off the dispatch critical path — the property
the reference gets from computing stats inside the engine workers.
"""
from __future__ import annotations

import logging
import re
from collections import OrderedDict

__all__ = ["Monitor"]


def _mean_abs(x):
    """Default statistic: mean absolute value, as an on-device scalar."""
    from . import ndarray as F
    return F.norm(x) / (x.size ** 0.5)


def _render(stat):
    """Format one captured stat (NDArray | list of NDArray) as the tab-joined
    string surface the reference's log readers expect."""
    from .ndarray.ndarray import NDArray
    vals = stat if isinstance(stat, (list, tuple)) else [stat]
    pieces = []
    for v in vals:
        if isinstance(v, NDArray):
            v = v.asscalar() if v.size == 1 else v.asnumpy()
        pieces.append(str(v))
    return "\t".join(pieces) + "\t"


class Monitor:
    """Watch outputs, weights and gradients of bound executors.

    ``interval`` — tic calls between open collection windows; ``stat_func`` —
    statistic per tensor (default mean absolute value); ``pattern`` — regex
    filter on tensor names; ``sort`` — render channels in name order;
    ``monitor_all`` — record op inputs too, not only outputs.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        self.interval = interval
        self.stat_func = stat_func or _mean_abs
        self.sort = sort
        self.monitor_all = monitor_all
        self.re_prog = re.compile(pattern)
        self.step = 0
        self.activated = False       # window state; public for parity
        self._channels: "OrderedDict[str, list]" = OrderedDict()
        self._sources = []           # installed executors (param snapshots)

    # -- sink --------------------------------------------------------------
    def _capture(self, name, array):
        """Record one sample if the window is open and the name matches."""
        if not (self.activated and self.re_prog.match(name)):
            return
        from . import autograd
        with autograd.pause():       # stat reductions stay off the grad tape
            stat = self.stat_func(array)
        self._channels.setdefault(name, []).append((self.step, stat))

    # -- sources -----------------------------------------------------------
    def install(self, exe):
        """Attach a bound Executor as a sample source."""
        exe.set_monitor_callback(self._capture, self.monitor_all)
        self._sources.append(exe)

    def _snapshot_params(self):
        """Push one sample per matching argument/aux of every source."""
        for exe in self._sources:
            for mapping in (exe.arg_dict, exe.aux_dict):
                for name, arr in mapping.items():
                    self._capture(name, arr)

    # -- window control ----------------------------------------------------
    def tic(self):
        """Advance one step; open a collection window every `interval` steps."""
        if self.step % self.interval == 0:
            self._channels.clear()
            self.activated = True
        self.step += 1

    def toc(self):
        """Close the window and render it: list of (step, name, stat-string)."""
        if not self.activated:
            return []
        self._snapshot_params()
        self.activated = False
        names = sorted(self._channels) if self.sort else list(self._channels)
        rows = [(step, name, _render(stat))
                for name in names
                for step, stat in self._channels[name]]
        self._channels.clear()
        return rows

    def toc_print(self):
        """Close the window and log every rendered row."""
        for step, name, text in self.toc():
            logging.info("Batch: %7d %30s %s", step, name, text)
