"""Monitor outputs, weights, and gradients for debugging (parity:
python/mxnet/monitor.py:32 Monitor — interval/stat_func/pattern/sort/
monitor_all surface, install → tic → forward → toc(_print) workflow).

TPU-native: the reference registers a ctypes callback the C++ executor fires
per op; here the graph Executor calls the monitor callback as it walks the
symbol DAG (symbol/executor.py:_eval_graph), with the same name convention
(``<node>_output``, plus ``<node>_input<i>`` under ``monitor_all``). Stats
stay lazy jax values until ``toc`` syncs them, mirroring the reference's
async stat computation.
"""
from __future__ import annotations

import logging
import re
from math import sqrt

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    """Monitor inputs, outputs, weights and gradients of bound executors.

    Parameters
    ----------
    interval : int
        Number of batches between collections.
    stat_func : callable(NDArray) -> NDArray, optional
        Statistic; default mean absolute value ``norm(x)/sqrt(size)``.
    pattern : str
        Regex selecting tensor names to monitor.
    sort : bool
        Sort results by name in ``toc``.
    monitor_all : bool
        Also monitor op inputs, not just outputs.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        if stat_func is None:
            def asum_stat(x):
                from . import ndarray as nd_mod
                return nd_mod.norm(x) / sqrt(x.size)
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all

        def stat_helper(name, array):
            if not self.activated or not self.re_prog.match(name):
                return
            from . import autograd
            with autograd.pause():  # stats must not land on the gradient tape
                self.queue.append((self.step, name, self.stat_func(array)))
        self.stat_helper = stat_helper

    def install(self, exe):
        """Install the callback into an Executor (symbol.bind result)."""
        exe.set_monitor_callback(self.stat_helper, self.monitor_all)
        self.exes.append(exe)

    def tic(self):
        """Start collecting for the current batch; call before forward."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Finish collecting; returns list of (step, name, value-string)."""
        if not self.activated:
            return []
        for exe in self.exes:
            for name, array in zip(exe._symbol.list_arguments(),
                                   exe.arg_arrays):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name, self.stat_func(array)))
            for name, array in zip(exe._symbol.list_auxiliary_states(),
                                   exe.aux_arrays):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name, self.stat_func(array)))
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            s = ""
            for v in v_list:
                s += (str(v.asscalar()) if v.size == 1 else str(v.asnumpy())) \
                    + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        """Finish collecting and log the results."""
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, v)
