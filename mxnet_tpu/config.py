"""Environment flag registry (parity: the reference's MXNET_* env-var config
system — docs/faq/env_var.md over dmlc::GetEnv call sites in src/).

Typed, documented, centrally-registered flags: ``config.get("MXNET_...")``
reads the process environment with the registered default and type, and
``config.describe()`` lists every knob (the env_var.md analog). Subsystems
read through here so behavior-affecting env vars are discoverable instead of
scattered ad-hoc ``os.environ`` lookups.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict

from .base import MXNetError

__all__ = ["register", "get", "set", "describe", "list_flags"]

_REGISTRY: Dict[str, dict] = {}
_OVERRIDES: Dict[str, Any] = {}
_LOCK = threading.Lock()


def register(name, default, type_=None, doc=""):
    """Register a flag with its default, type and documentation."""
    if type_ is None:
        type_ = type(default) if default is not None else str
    with _LOCK:
        _REGISTRY[name] = {"default": default, "type": type_, "doc": doc}
    return name


def _coerce(name, raw, type_):
    try:
        if type_ is bool:
            return str(raw).lower() in ("1", "true", "yes", "on")
        return type_(raw)
    except (TypeError, ValueError) as e:
        raise MXNetError(f"{name}={raw!r}: expected {type_.__name__}") from e


def get(name, default=None):
    """Read a flag: set() override > process env > registered default."""
    spec = _REGISTRY.get(name)
    if name in _OVERRIDES:
        return _OVERRIDES[name]
    raw = os.environ.get(name)
    if raw is None:
        if spec is not None:
            return spec["default"]
        return default
    return _coerce(name, raw, spec["type"] if spec else
                   (type(default) if default is not None else str))


def set(name, value):  # noqa: A001 — mirrors the reference's setter naming
    """Override a flag for this process (takes precedence over the env)."""
    _OVERRIDES[name] = value


def list_flags():
    return sorted(_REGISTRY)


def describe():
    """Human-readable flag table (env_var.md analog)."""
    lines = []
    for name in list_flags():
        spec = _REGISTRY[name]
        cur = get(name)
        lines.append(f"{name} (default {spec['default']!r}, "
                     f"current {cur!r}): {spec['doc']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# flags consumed by this framework (kept to knobs that actually do something)
# ---------------------------------------------------------------------------
register("MXNET_ENGINE_TYPE", "ThreadedEngine", str,
         "Engine for host tasks: ThreadedEngine (native C++ pool) or "
         "NaiveEngine (synchronous Python fallback).")
register("MXNET_CPU_WORKER_NTHREADS", 4, int,
         "Worker threads of the host-task dependency engine.")
register("MXNET_CPU_PRIORITY_NTHREADS", 4, int,
         "Decode/augment threads of the native image pipeline default.")
register("MXNET_EXEC_BULK_EXEC_TRAIN", True, bool,
         "Accepted for parity; op bulking is subsumed by XLA fusion.")
register("MXNET_PROFILER_AUTOSTART", False, bool,
         "Start the profiler at import (profiler.cc autostart parity).")
register("MXNET_USE_SIGNAL_HANDLER", True, bool,
         "Install the crash backtrace logger (faulthandler; the "
         "initialize.cc SegfaultLogger analog).")
register("MXNET_SAFE_ACCUMULATION", True, bool,
         "Accumulate reductions over bf16/fp16 inputs in fp32.")
register("MXNET_PRNG_IMPL", "auto", str,
         "PRNG generator: threefry2x32 (alias: threefry) | rbg | unsafe_rbg "
         "| auto. auto = rbg on accelerators (hardware-friendly; +13% "
         "measured BERT pretraining throughput vs threefry dropout-bit "
         "generation), threefry on CPU (bit-reproducible test runs).")
register("MXNET_STORAGE_FALLBACK_LOG_VERBOSE", True, bool,
         "Log when a sparse op densifies an operand (executor fallback log).")
register("MXNET_HOME", os.path.join("~", ".mxnet"), str,
         "Root for datasets/model downloads.")
register("MXNET_P3_SLICE_SIZE", 1 << 20, int,
         "p3 kvstore: elements per wire slice (priority propagation).")
register("MXNET_TRAIN_REMAT", "none", str,
         "ParallelTrainStep rematerialization policy: none | conv (save only "
         "conv outputs, recompute BN/ReLU chains in backward) | full.")
register("MXNET_BN_ONEPASS", "auto", str,
         "BatchNorm: compute batch moments in ONE pass over the input "
         "(f32-accumulated E[x^2]-mu^2, clamped) instead of the two-pass "
         "mean-then-variance form — saves a full activation read per BN "
         "layer in forward. Default 'auto': one-pass only for sub-f32 "
         "inputs (bf16/f16, which cannot represent the |mean|/std ratios "
         "where E[x^2]-mu^2 catastrophically cancels); f32/f64 inputs use "
         "the two-pass reference form (ADVICE r5: one-pass at f32 with "
         "mean~300/std~0.01 clamps var to 0 and silently mis-scales). Set "
         "1/0 to force one-pass/two-pass for every dtype. The bf16 fast "
         "path (MXNET_BN_BF16_REDUCE) is inherently one-pass and ignores "
         "this flag; to get the two-pass f32 formulation on bf16 inputs, "
         "set MXNET_BN_BF16_REDUCE=0 AND this flag to 0.")
register("MXNET_BN_BF16_REDUCE", True, bool,
         "BatchNorm: when the input is bfloat16, keep every materialized "
         "tensor bf16 and apply the normalize with f32 scale/shift "
         "in-register (cuDNN fp16-AMP BatchNorm semantics: half tensors, "
         "float stats and f32 gradient accumulation; always one-pass "
         "moments). Measured 2204->2660 img/s on ResNet-50 b128 v5e. Set 0 "
         "to run bf16 inputs through the f32-promoted path (whose moment "
         "form MXNET_BN_ONEPASS then controls).")
register("MXNET_FLASH_BWD_BLOCK_Q", 0, int,
         "Flash-attention Pallas BACKWARD kernels: q-block size override "
         "(0 = inherit the forward's block_q). The backward tiles carry "
         "~3x the forward's VMEM working set, so its optimum differs. "
         "Consulted at kernel-build time and the built executable is "
         "cached per op/shape signature — set BEFORE the first backward "
         "at a given shape; later changes do not rebuild cached kernels "
         "(same trace-time semantics as MXNET_TRAIN_REMAT).")
register("MXNET_FLASH_BWD_BLOCK_K", 0, int,
         "Flash-attention Pallas backward: k-block size override "
         "(0 = inherit the forward's block_k). Trace-time semantics: see "
         "MXNET_FLASH_BWD_BLOCK_Q.")
register("MXNET_OPT_BF16_MOMENTS", False, bool,
         "Adam/AdamW: store the first/second moments in bfloat16 (EMA "
         "arithmetic still runs on in-register f32 upcasts). Halves the "
         "optimizer-state HBM traffic per step. Off by default: the second "
         "moment's tiny EMA increments ((1-beta2)*g^2) round away against a "
         "bf16-stored v once v is ~2^9 times larger, biasing v low on long "
         "horizons. Short-horizon convergence gate: tests/test_optimizer_ops"
         ".py::test_adam_bf16_moments_close_and_converges.")
register("MXNET_JIT_CACHE_SIZE", 4096, int,
         "Capacity (entries) of the eager per-(op, static-attrs) jit "
         "executable LRU cache (ops/registry.py). Each entry retains a "
         "jax.jit wrapper plus its compiled executables; bounding it keeps "
         "long-running eager workloads with per-iteration-varying attrs "
         "(slice bounds, pad widths, reshape targets) from growing host "
         "memory without bound. Eviction recompiles on next use.")
register("MXNET_KVSTORE_ASYNC_MAX_STALENESS", -1, int,
         "dist_async: max whole-model push rounds a worker may run ahead of "
         "the slowest (SSP bound); -1 = unbounded, the reference's pure "
         "async-apply behavior.")
register("MXNET_KVSTORE_HEARTBEAT_DIR", "", str,
         "Shared dir for worker heartbeat files (ps-lite heartbeat analog); "
         "empty disables failure detection.")
register("MXNET_KVSTORE_HEARTBEAT_INTERVAL", 5, int,
         "Seconds between heartbeat file touches.")
register("MXNET_TELEMETRY_DUMP_PATH", "", str,
         "When set, start a background telemetry reporter at import that "
         "writes the full metrics snapshot to this path every "
         "MXNET_TELEMETRY_DUMP_INTERVAL seconds (JSON; Prometheus text "
         "exposition if the path ends in .prom). tools/metrics_dump.py "
         "reads/watches the file while the run is live.")
register("MXNET_TELEMETRY_DUMP_INTERVAL", 10.0, float,
         "Seconds between background telemetry snapshot dumps/log lines.")
register("MXNET_CKPT_KEEP", 3, int,
         "CheckpointManager: newest checkpoints retained after each save "
         "(the corrupt-fallback chain depth); 0 disables rotation.")
register("MXNET_CKPT_ASYNC", False, bool,
         "CheckpointManager default: snapshot synchronously but write/fsync "
         "in a background thread, overlapping checkpoint IO with compute "
         "(wait() joins and surfaces write errors).")
register("MXNET_CKPT_WAIT_TIMEOUT_S", 120.0, float,
         "CheckpointManager.wait()/save() bound on joining an outstanding "
         "async checkpoint write; past it wait() raises instead of hanging "
         "shutdown behind a wedged writer (<= 0 = unbounded).")
register("MXNET_PREEMPT_DEADLINE_S", 30.0, float,
         "PreemptionGuard grace budget: the preemption force-flush (join "
         "async checkpoint writes + final save + marker) is measured "
         "against this; a flush that cannot beat it is recorded as "
         "deadline_exceeded in PREEMPTED.json and "
         "mxtpu_preemptions_total.")
register("MXNET_SUPERVISOR_POLL_S", 0.05, float,
         "PoolSupervisor liveness-poll interval: how often the serving "
         "worker/prep threads are checked for death or a wedged in-flight "
         "batch (stall detection itself rides the Watchdog).")
register("MXNET_CKPT_FSYNC", True, bool,
         "CheckpointManager: fsync every checkpoint file and directory "
         "rename (the crash-consistency barrier). Disable only for "
         "throwaway test directories.")
register("MXNET_RETRY_MAX_ATTEMPTS", 3, int,
         "RetryPolicy: total attempts (1 = no retries) for retryable "
         "failures (device OOM, UNAVAILABLE, transient compile errors); "
         "fatal errors (shape/dtype mismatch) never retry.")
register("MXNET_RETRY_BASE_MS", 50.0, float,
         "RetryPolicy: backoff before the first retry, milliseconds.")
register("MXNET_RETRY_MAX_MS", 2000.0, float,
         "RetryPolicy: backoff cap, milliseconds.")
register("MXNET_RETRY_MULTIPLIER", 2.0, float,
         "RetryPolicy: exponential backoff multiplier per attempt.")
register("MXNET_RETRY_JITTER", 0.1, float,
         "RetryPolicy: relative jitter (+/- fraction) on each backoff, drawn "
         "from a seeded generator so chaos runs replay exactly.")
register("MXNET_WATCHDOG_STALL_S", 30.0, float,
         "Watchdog: a watched region (device step, serving batch) alive "
         "longer than this counts as a stall — mxtpu_watchdog_stalls_total "
         "fires and the owner's stall callback runs (the serving server "
         "degrades its circuit breaker).")
register("MXNET_WATCHDOG_POLL_S", 0.0, float,
         "Watchdog monitor poll interval; 0 = auto (stall_s/4, clamped to "
         "[0.01, 0.25]s).")
register("MXNET_CIRCUIT_DEGRADED_AFTER", 3, int,
         "CircuitBreaker: consecutive failures before HEALTHY -> DEGRADED "
         "(admission tightens to half the queue bound).")
register("MXNET_CIRCUIT_OPEN_AFTER", 6, int,
         "CircuitBreaker: consecutive failures before -> OPEN (all "
         "admissions shed with ServerOverloadError until cooldown).")
register("MXNET_CIRCUIT_COOLDOWN_S", 5.0, float,
         "CircuitBreaker: seconds OPEN before HALF_OPEN probing begins.")
register("MXNET_NUMERICS_CHECK_EVERY_N", 10, int,
         "NumericsGuard: steps between boundary reads of the retained "
         "on-device health scalars (loss / global grad norm / all-finite "
         "flag). Detection lags by up to this many steps; the read is a "
         "scalar D2H fetch of long-completed values, never a pipeline "
         "stall — lower it for tighter detection, raise it for less host "
         "chatter.")
register("MXNET_NUMERICS_POLICY", "auto", str,
         "NumericsGuard recovery policy: skip (rewind to the last clean "
         "boundary snapshot and replay the window minus the offending "
         "batch — bitwise-equal to never having trained on it) | "
         "quarantine (skip + fingerprint/dump the batch and positionally "
         "exclude it from the DataLoader forever) | rewind (restore the "
         "last good checkpoint and fast-forward the loader past the "
         "poisoned window) | auto (skip first offenders, quarantine a "
         "fingerprint's second offense, rewind when exclusion cannot "
         "repair the window).")
register("MXNET_NUMERICS_SPIKE_ZSCORE", 8.0, float,
         "NumericsGuard: EWMA z-score above which a loss/grad-norm reading "
         "counts as a spike (one-sided; falling loss never flags).")
register("MXNET_NUMERICS_WARMUP_STEPS", 20, int,
         "NumericsGuard: accepted readings before the spike detector arms "
         "(early-training loss is legitimately wild).")
register("MXNET_NUMERICS_EWMA_ALPHA", 0.05, float,
         "NumericsGuard: EWMA smoothing factor for the loss/grad-norm "
         "mean/variance band.")
register("MXNET_NUMERICS_MAX_RECOVERIES", 4, int,
         "NumericsGuard: exclusion-replay attempts per window before the "
         "guard gives up (raises NumericsError, or rewinds under "
         "policy=auto with a CheckpointManager attached).")
register("MXNET_NUMERICS_QUARANTINE_DIR", "", str,
         "NumericsGuard: directory where quarantined batches are dumped "
         "(npz + json fingerprint/position metadata) for postmortem; empty "
         "disables the dump (positional exclusion still happens).")
register("MXNET_SDC_CHECK_EVERY_N", 0, int,
         "NumericsGuard SDC screening: steps between window re-executions "
         "(restore snapshot, replay retained batches with their exact RNG "
         "keys, compare parameter digests — deterministic XLA makes any "
         "mismatch a silent-data-corruption suspect). 0 disables; the "
         "effective cadence rounds up to a multiple of "
         "MXNET_NUMERICS_CHECK_EVERY_N. Screening cost is one extra "
         "window of compute per cadence.")
register("MXNET_SDC_BUNDLE_DIR", "", str,
         "NumericsGuard: directory where SDC repro bundles land (pre-state "
         "+ batches + RNG keys + both digests; tools/replay_step.py "
         "re-executes them). Empty skips bundle writing.")
register("MXNET_SERVING_DRAIN_TIMEOUT_S", 30.0, float,
         "InferenceServer.stop(drain=True): max seconds to wait for the "
         "drain; past it pending requests are abandoned (failed with "
         "ServerClosedError, counted in mxtpu_drain_abandoned_total) so a "
         "wedged endpoint can never hang shutdown forever.")
register("MXNET_SERVING_PIPELINE_DEPTH", 1, int,
         "InferenceServer prep/execute overlap depth: how many prepared "
         "batches the prep loop may run ahead of the execute loop. Depth d "
         "keeps d+1 staging parities alive (host buffers + device inputs); "
         "1 reproduces classic double-buffering. The serial fallback "
         "(pipeline=False) ignores it.")
register("MXNET_SERVING_ZEROCOPY", True, bool,
         "Batch assembly writes request rows straight into preallocated "
         "per-(bucket, parity) staging buffers instead of numpy "
         "concatenate+pad — zero intermediate host copies on the ingest "
         "path. Off falls back to concat (the bitwise-identical slow "
         "path).")
register("MXNET_FABRIC_VNODES", 64, int,
         "Serving front door: virtual nodes per host on the consistent-"
         "hash tenant routing ring. More vnodes spread tenants more "
         "evenly; fewer make the ring cheaper to walk.")
register("MXNET_FABRIC_HEARTBEAT_S", 0.2, float,
         "Serving front door: host agent heartbeat/dump cadence (seconds). "
         "Each tick touches the host's heartbeat file, re-attributes "
         "goodput and rewrites its telemetry dump for the fleet pane.")
register("MXNET_FABRIC_HOST_TIMEOUT_S", 2.0, float,
         "Serving front door: FrontDoor.check_hosts() declares a host dead "
         "when its agent heartbeat is older than this many seconds (or the "
         "agent process exited) and fails it over like kill_host().")
register("MXNET_KV_PAGE_SIZE", 16, int,
         "Paged KV cache: token positions per page. Small pages waste less "
         "tail allocation per sequence but grow page tables; the page size "
         "is baked into the decode executables' scatter/gather indexing, "
         "so changing it recompiles.")
register("MXNET_KV_POOL_PAGES", 256, int,
         "Paged KV cache: total pages preallocated per pool (page 0 is the "
         "reserved scratch page, so usable pages are N-1). Bounds the "
         "number of concurrent sequences times their page footprint; "
         "reserve() past it raises KVPoolExhausted and the scheduler keeps "
         "the sequence queued.")
register("MXNET_KV_DEFRAG_RATIO", 0.0, float,
         "Paged KV cache: auto-compaction threshold on the fragmentation "
         "spread (highest live page id / pages in use); free() triggers "
         "defrag() when the spread exceeds it. 0 (default) disables "
         "auto-compaction (explicit defrag() still works; compaction is a "
         "pure page copy, bitwise-invisible to decode output).")
register("MXNET_DECODE_MAX_BATCH", 8, int,
         "Decode scheduler: max sequences advanced per decode step (top of "
         "the pow2 decode-bucket ladder; every bucket compiles one "
         "decode-step executable at warmup).")
register("MXNET_DECODE_MAX_TOKENS", 64, int,
         "Decode scheduler: default generation budget (max_new_tokens) for "
         "submit() calls that do not specify one. The whole budget's KV "
         "pages are reserved at admission, so a running sequence can never "
         "hit pool exhaustion mid-generation.")
register("MXNET_DECODE_STREAM_BUFFER", 64, int,
         "TokenStream: buffered tokens per client stream before "
         "backpressure pauses the sequence (pages kept, not stepped; "
         "resumes when the consumer drains below half).")
register("MXNET_DECODE_SLO_MS", 100.0, float,
         "Decode scheduler: default per-tenant inter-token SLO "
         "(milliseconds between consecutive tokens of one sequence) used "
         "for EDF admission slack; tenants can override at add_tenant(). "
         "0 disables deadline pricing (FIFO admission).")
register("MXNET_FLIGHT_DIR", "", str,
         "FlightRecorder: directory where trigger-driven flight bundles "
         "(ring contents + metrics snapshot + knob/env fingerprint + "
         "thread stacks) are written, with rotation. Empty keeps the rings "
         "recording but disables automatic bundle dumps; explicit "
         "flight.dump() still works. Also arms the unhandled-exception "
         "crash hooks at import when set.")
register("MXNET_FLIGHT_SPANS", 512, int,
         "FlightRecorder: capacity of the finished-span ring buffer.")
register("MXNET_FLIGHT_EVENTS", 256, int,
         "FlightRecorder: capacity of the structured-event ring buffer "
         "(telemetry.event: breaker transitions, retries, failovers, "
         "hot-swaps, numerics anomalies, preemptions, SLO alerts).")
register("MXNET_FLIGHT_REQUESTS", 128, int,
         "FlightRecorder: capacity of the completed-serving-request ring "
         "(keyed by trace id).")
register("MXNET_FLIGHT_KEEP", 8, int,
         "FlightRecorder: newest bundles retained per directory; older "
         "flight-*.json files are rotated away after each dump.")
register("MXNET_FLIGHT_MIN_INTERVAL_S", 1.0, float,
         "FlightRecorder: per-trigger-kind dump rate limit; a re-trigger "
         "of the same kind inside the interval records the event but "
         "skips the bundle (mxtpu_flight_dumps_suppressed_total).")
register("MXNET_DEBUG_PORT", 0, int,
         "Debug server: TCP port for the localhost HTTP introspection "
         "pages (/metricsz /healthz /statusz /tracez /flightz). 0 (the "
         "default) disables the server entirely.")
register("MXNET_DEBUG_HOST", "127.0.0.1", str,
         "Debug server: bind address. Keep it loopback unless a scrape "
         "sidecar genuinely lives off-host — the pages expose knobs and "
         "thread stacks.")
register("MXNET_SLO_TARGET", 0.999, float,
         "SLO monitor: default objective target (fraction of requests "
         "under the endpoint's slo_ms) when server.register() does not "
         "pass one explicitly.")
register("MXNET_SLO_FAST_WINDOW_S", 300.0, float,
         "SLO monitor: fast burn-rate window (seconds) — catches a sharp "
         "latency regression within minutes.")
register("MXNET_SLO_SLOW_WINDOW_S", 3600.0, float,
         "SLO monitor: slow burn-rate window (seconds) — de-bounces the "
         "fast window so blips never page.")
register("MXNET_SLO_BURN_THRESHOLD", 10.0, float,
         "SLO monitor: burn-rate multiple (bad_ratio / error_budget) both "
         "windows must exceed before the alert fires / the breaker "
         "escalates.")
register("MXNET_SLO_MIN_EVENTS", 10, int,
         "SLO monitor: minimum requests in the fast window before an "
         "alert may fire (no paging on a sample of three).")
register("MXNET_SLO_ESCALATE", False, bool,
         "SLO monitor: when a burn alert fires, force the offending "
         "tenant's circuit breaker to DEGRADED so admission tightens "
         "before the queue melts. Off by default (alert-only).")
register("MXNET_COMPILE_LEDGER_DIR", "", str,
         "Compile ledger: directory for the append-only per-process "
         "ledger-<pid>.jsonl files (one CompileRecord per XLA compile, "
         "atomic line appends, shared across processes for cross-process "
         "duplicate detection). Empty keeps the in-memory ring + metrics "
         "but writes no files.")
register("MXNET_COMPILE_LEDGER_KEEP", 64, int,
         "Compile ledger: CompileRecords served by recent() — the window "
         "the /compilez page and every flight bundle snapshot.")
register("MXNET_COMPILE_LEDGER_TEXT_MAX_BYTES", 32 << 20, int,
         "Compile ledger: byte budget for retained canonicalized module "
         "texts (module-<fingerprint>.mlir beside the ledger records — the "
         "offline corpus mxlint --ir and autotune feature extraction "
         "read). Content-addressed dedup means each distinct program is "
         "stored once; when the directory's retained texts would exceed "
         "the budget, new texts are skipped (counted in "
         "mxtpu_compile_text_retained_total{outcome=over_budget}). "
         "Negative disables the bound.")
register("MXNET_IR_GUARD", "", str,
         "Live IR guard over every lower_and_compile: '' (off — the "
         "zero-cost donation assertion still counts detections in "
         "mxtpu_ir_guard_total), 'warn' (check guarded rules IR1000/"
         "IR1001, emit RuntimeWarning + ir_guard flight event), 'raise' "
         "(same, then raise IRGuardError so a dropped donation or "
         "baked-in weights cannot ship). Guard infrastructure errors are "
         "always fail-open; only a real finding under 'raise' fails the "
         "compile. Rule catalog: STATIC_ANALYSIS.md.")
register("MXNET_COMPILE_LEDGER_EAGER", "auto", str,
         "Compile ledger: instrument the eager jit cache ('1'/'0'; 'auto' "
         "follows MXNET_COMPILE_LEDGER_DIR). Instrumentation AOT-compiles "
         "per aval signature to observe each compile; the default eager "
         "hot path is untouched when off.")
register("MXNET_MEM_TRACK", True, bool,
         "Memstats: maintain the HBM holder registry (endpoint params / "
         "bucket executables / donated train state / numerics snapshots) "
         "and reconcile it against device.memory_stats(). 0 turns "
         "register() into a no-op.")
register("MXNET_MEM_HOLDERS_KEEP", 32, int,
         "Memstats: ranked holders shown in breakdown() — the /memz page, "
         "OOM flight bundles; the rest fold into an omitted-bytes line.")
register("MXNET_PERF_SENTINEL", True, bool,
         "Perf sentinel: feed train-step and serving-step latencies into "
         "per-stream EWMA drift detectors that fire a perf_regression "
         "flight event on sustained regression. 0 disables.")
register("MXNET_PERF_EWMA_ALPHA", 0.05, float,
         "Perf sentinel: baseline EWMA smoothing factor (the fast 'now' "
         "track uses 4x this).")
register("MXNET_PERF_REGRESSION_RATIO", 1.5, float,
         "Perf sentinel: fast-track / baseline ratio that counts as "
         "regressed; must hold for MXNET_PERF_SUSTAIN_N consecutive "
         "observations to fire.")
register("MXNET_PERF_SUSTAIN_N", 8, int,
         "Perf sentinel: consecutive over-ratio observations required "
         "before the perf_regression trigger fires (one spike never "
         "pages).")
register("MXNET_PERF_WARMUP_N", 50, int,
         "Perf sentinel: observations per stream before the detector "
         "arms — compile-time outliers and cold caches train the "
         "baseline instead of firing it.")
register("MXNET_EXEC_CACHE_DIR", "", str,
         "Executable cache: directory for serialized compiled executables "
         "(content-addressed by StableHLO fingerprint + device topology + "
         "runtime versions; shareable across processes and hosts). Every "
         "lower_and_compile() site checks it before compiling and "
         "populates it after — a warm restart compiles nothing. Empty "
         "disables the cache.")
register("MXNET_EXEC_CACHE_MAX_BYTES", 1 << 30, int,
         "Executable cache: byte budget for the on-disk store. After "
         "every write the least-recently-used entries (payload mtime, "
         "touched on hit) are evicted until the store fits. 0 disables "
         "eviction.")
register("MXNET_AUTOSCALE_MIN_REPLICAS", 1, int,
         "Autoscaler: floor on the serving replica count — scale-down "
         "never drains below it.")
register("MXNET_AUTOSCALE_MAX_REPLICAS", 4, int,
         "Autoscaler: ceiling on the serving replica count — scale-up "
         "stops here however hard the SLO burns.")
register("MXNET_AUTOSCALE_POLL_S", 1.0, float,
         "Autoscaler: control-loop poll interval (seconds) between "
         "signal reads (SLO burn rate + queue depth).")
register("MXNET_AUTOSCALE_UP_N", 2, int,
         "Autoscaler hysteresis: consecutive over-pressure polls required "
         "before a scale-up (one hot poll never scales).")
register("MXNET_AUTOSCALE_DOWN_N", 5, int,
         "Autoscaler hysteresis: consecutive idle polls required before "
         "a scale-down (draining a replica is the expensive direction).")
register("MXNET_AUTOSCALE_COOLDOWN_S", 10.0, float,
         "Autoscaler: minimum seconds between scaling actions — the "
         "fleet settles (queues redistribute, burn windows refill) "
         "before the next decision.")
register("MXNET_AUTOSCALE_QUEUE_HIGH", 0.5, float,
         "Autoscaler: queue-pressure scale-up threshold as a fraction of "
         "the per-replica queue bound (pending rows / max rows, worst "
         "endpoint, averaged over replicas).")
register("MXNET_AUTOSCALE_QUEUE_LOW", 0.05, float,
         "Autoscaler: queue-pressure floor below which (with no active "
         "burn alert) idle polls count toward scale-down.")
register("MXNET_EMB_REPLICATE_MAX_BYTES", 1 << 20, int,
         "Embedding planner: tables at or under this footprint are "
         "replicated per shard instead of vocab-partitioned — a full copy "
         "is cheaper than any exchange for small tables.")
register("MXNET_EMB_ROWWISE_HOT_FRACTION", 0.25, float,
         "Embedding planner: when a table's observed top-K hot rows take "
         "at least this share of lookups, partition it row-wise (cyclic "
         "layout) so a frequency-sorted vocab's hot head spreads across "
         "shards instead of concentrating on shard 0.")
register("MXNET_EMB_HOT_TOPK", 64, int,
         "Embedding planner: K for the hot-row hit-rate statistic "
         "(mxtpu_emb_hot_row_hit_rate) the row-wise decision reads.")
register("MXNET_EMB_HOTNESS_CAP", 1 << 16, int,
         "Embedding planner: rows of the (frequency-sorted) vocab head "
         "the HotnessTracker keeps exact counters for; hits past the cap "
         "count only toward the total.")
register("MXNET_EMB_FEED_DEPTH", 2, int,
         "DeviceFeed: staged-batch buffer depth (2 = double-buffered; the "
         "stager runs at most this many batches ahead of the consumer).")
register("MXNET_SPAN_SPOOL_DIR", "", str,
         "Span spool: directory for per-pid append-only span JSONL files "
         "(spool-<pid>.jsonl) — the cross-process raw material "
         "tools/trace_journey.py assembles into one timeline per trace "
         "id. Empty (the default) keeps the spool in-memory only: span "
         "exits pay a bounded buffer append and no file I/O ever runs.")
register("MXNET_SPAN_SPOOL_MAX_BYTES", 8 << 20, int,
         "Span spool: size cap per spool file; exceeding it rotates the "
         "file to spool-<pid>.jsonl.1 (one generation kept) before the "
         "append. 0 disables rotation.")
register("MXNET_SPAN_SPOOL_FLUSH_N", 32, int,
         "Span spool: buffered spans per flush — the spool drains to disk "
         "in one O_APPEND write every this-many spans (and at interpreter "
         "exit), never per-span.")
register("MXNET_TRACE_ID", "", str,
         "Trace inheritance: a trace id handed to a child process at "
         "spawn (ServingPool warm restarts, loadgen --restart phases, "
         "chaos subprocesses). The child's first root span joins this "
         "trace instead of minting a fresh id, so one logical request is "
         "one journey across process boundaries. Read once per process.")
register("MXNET_FLEET_DUMP_GLOB", "", str,
         "Fleet collector: glob of telemetry snapshot JSON files "
         "(telemetry.dump() / MXNET_TELEMETRY_DUMP_PATH outputs) from "
         "sibling processes to merge into the fleet view alongside the "
         "live in-process registry.")
register("MXNET_GOODPUT_PEAK_FLOPS", 0.0, float,
         "Goodput ledger: peak device FLOP/s for the roofline fraction in "
         "the per-executable utilization estimate (achieved flops/s over "
         "this). 0 (the default) reports achieved rates only.")
register("MXNET_GOODPUT_PEAK_GBS", 0.0, float,
         "Goodput ledger: peak device memory bandwidth (bytes/s) for the "
         "roofline fraction of the bytes-accessed rate. 0 reports "
         "achieved rates only.")
register("MXNET_COSTMODEL_PATH", "", str,
         "Cost model: path of the trained artifact JSON "
         "(tools/autotune.py --train writes one). When set, the model is "
         "loaded lazily (sha256 + schema verified, mtime-cached) and its "
         "predictions price every cold StepCostEWMA bucket and the "
         "autoscaler's warm-up lead. Empty (the default) disables the "
         "prior entirely — all scheduling behaves exactly pre-model.")
register("MXNET_COSTMODEL_PRIOR", True, bool,
         "Cost model: master switch for the learned prior. False keeps "
         "the artifact loadable (for /costz and offline tools) but makes "
         "every EWMA fall back to the legacy row-ratio pricing.")
register("MXNET_COSTMODEL_BLEND_N", 5, int,
         "Cost model: observations per bucket over which a prior-priced "
         "estimate blends linearly into the measured EWMA. After this "
         "many observations the prior's weight is exactly zero — measured "
         "always wins. 0 disables blending (prior prices only "
         "never-observed buckets).")
register("MXNET_COSTMODEL_STEP_RECORDS", True, bool,
         "Cost model: append rate-limited kind=\"step\" records (measured "
         "step wall per trigger key) into the compile-ledger JSONL files "
         "— the training corpus for the step_us target. Power-of-two "
         "observation counts are logged (plus one per 256 steady-state), "
         "so a million-step serve costs ~4k lines. Only active when "
         "MXNET_COMPILE_LEDGER_DIR is set.")
register("MXNET_COSTMODEL_DRIFT_BAND", 4.0, float,
         "Cost model: residual drift band. A measured/predicted ratio "
         "outside [1/band, band] counts toward the drift streak; sustained "
         "excursions fire the cost_model_drift flight event (stale-model "
         "alarm).")
register("MXNET_COSTMODEL_DRIFT_SUSTAIN_N", 8, int,
         "Cost model: consecutive out-of-band residuals (per site) before "
         "cost_model_drift fires. The detector latches per episode — one "
         "event per sustained excursion, re-armed when a residual returns "
         "in-band.")
