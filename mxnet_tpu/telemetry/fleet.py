"""Fleet collector — one pane over every replica's and process's registry.

PRs 12–13 made the deployment a *fleet* (ServingPool replicas, SLO-driven
autoscaling, warm-restarted processes, supervised worker generations) while
every observability surface stayed per-process. This module is the missing
aggregation layer:

  - :func:`merge_snapshots` folds N registry snapshots (the in-process
    registry, sibling processes' ``telemetry.dump()`` files, a
    ``/metricsz?json=1`` scrape) into ONE snapshot-shaped dict where every
    series gains a ``replica`` label — renderable by the same
    ``prometheus_from_snapshot`` / ``metrics_dump`` code paths that render a
    single process.
  - :func:`merge_histogram_series` is the correctness kernel: for identical
    bucket ladders, cross-replica merging is an element-wise bucket-count
    sum, so the merged quantiles are exactly the quantiles of the
    concatenated observations (the property the tier-1 test pins).
  - :class:`FleetCollector` adds the live half: the local registry, dump
    files (``MXNET_FLEET_DUMP_GLOB``), attached ServingPools/Autoscalers
    (via ``debug_server``'s weak registries), and a fleet-level health
    rollup — worst-of replica health + autoscaler state + supervisor worker
    epochs — exported as ``mxtpu_fleet_*`` gauges and the ``/fleetz`` page.

Offline rendering: ``tools/fleet_report.py``.
"""
from __future__ import annotations

import glob as _glob
import json
import os
import time
from typing import Dict, List, Optional, Sequence

from .metrics import REGISTRY, _quantile_from_buckets

__all__ = ["merge_histogram_series", "merge_snapshots", "FleetCollector",
           "health_rollup", "collect"]

_FLEET_PROCESSES = REGISTRY.gauge(
    "mxtpu_fleet_processes",
    "Processes/replicas folded into the last fleet collection (the local "
    "registry counts as one).")
_FLEET_REPLICAS = REGISTRY.gauge(
    "mxtpu_fleet_replicas",
    "Serving replicas across every attached ServingPool, by state "
    "(rotation / draining).",
    labelnames=("state",))
_FLEET_HEALTH = REGISTRY.gauge(
    "mxtpu_fleet_health",
    "Fleet health rollup: 0 = ok, 1 = degraded, 2 = down (worst-of "
    "replica health + autoscaler + supervisor state).")

_HEALTH_RANK = {"ok": 0, "degraded": 1, "down": 2}


def merge_histogram_series(bounds: Sequence[float],
                           entries: Sequence[Dict]) -> Dict:
    """Merge histogram series sharing one bucket ladder into one series.

    Element-wise bucket-count sums: because each observation lands in
    exactly one bucket, summing counts per bucket is *exactly* the histogram
    of the concatenated observations — merged quantiles equal the quantiles
    a single replica would have reported had it seen every observation.
    """
    n_buckets = len(bounds) + 1          # + the +Inf overflow bucket
    counts = [0] * n_buckets
    n = 0
    total = 0.0
    mn: Optional[float] = None
    mx = 0.0
    for s in entries:
        bc = s.get("bucket_counts") or []
        if len(bc) != n_buckets:
            raise ValueError(
                f"bucket ladder mismatch: series has {len(bc)} buckets, "
                f"ladder implies {n_buckets}")
        for i, c in enumerate(bc):
            counts[i] += c
        sn = int(s.get("count", 0))
        n += sn
        total += float(s.get("sum", 0.0))
        if sn:
            smin = float(s.get("min", 0.0))
            mn = smin if mn is None else min(mn, smin)
            mx = max(mx, float(s.get("max", 0.0)))
    return {
        "count": n,
        "sum": total,
        "mean": (total / n) if n else 0.0,
        "min": mn if mn is not None else 0.0,
        "max": mx,
        "p50": _quantile_from_buckets(bounds, counts, n, 50, mx),
        "p95": _quantile_from_buckets(bounds, counts, n, 95, mx),
        "p99": _quantile_from_buckets(bounds, counts, n, 99, mx),
        "bucket_counts": counts,
    }


def merge_snapshots(snaps: Dict[str, Dict], replica_label: str = "replica",
                    merged_series: bool = True) -> Dict:
    """Fold ``{replica_name: snapshot}`` into one snapshot-shaped dict.

    Every series gains a ``replica=<name>`` label, so same-name series from
    different replicas never collide and per-replica values stay visible.
    With ``merged_series`` (the default), each histogram family additionally
    grows one ``replica=ALL`` series per distinct label set — the
    bucket-merged fleet view whose quantiles are the true cross-replica
    quantiles — and each counter family an ``ALL`` sum. Families whose
    bucket ladders differ across replicas keep their per-replica series but
    skip the ``ALL`` row (merging mismatched ladders would fabricate data).
    """
    out: Dict = {"ts": time.time(), "metrics": {},
                 "replicas": sorted(snaps.keys())}
    fams: Dict[str, Dict] = out["metrics"]
    for rep in sorted(snaps.keys()):
        snap = snaps[rep] or {}
        for name, fam in (snap.get("metrics") or {}).items():
            dst = fams.get(name)
            if dst is None:
                dst = fams[name] = {
                    "type": fam.get("type", "untyped"),
                    "help": fam.get("help", ""),
                    "label_names": [replica_label] +
                                   list(fam.get("label_names", [])),
                    "series": [],
                }
                if "bucket_bounds" in fam:
                    dst["bucket_bounds"] = list(fam["bucket_bounds"])
            for s in fam.get("series", []):
                entry = dict(s)
                entry["labels"] = {replica_label: rep,
                                   **(s.get("labels") or {})}
                # mismatched ladders can't be cross-checked per series
                # here; remember the source ladder for the ALL pass
                entry["_bounds"] = fam.get("bucket_bounds")
                dst["series"].append(entry)
    if merged_series:
        for name, fam in fams.items():
            _add_all_series(fam, replica_label)
    for fam in fams.values():
        for s in fam["series"]:
            s.pop("_bounds", None)
    return out


def _add_all_series(fam: Dict, replica_label: str):
    """Append the ``replica=ALL`` rollup series per distinct label set."""
    groups: Dict[tuple, List[Dict]] = {}
    for s in fam["series"]:
        key = tuple(sorted((k, v) for k, v in s["labels"].items()
                           if k != replica_label))
        groups.setdefault(key, []).append(s)
    for key, group in sorted(groups.items()):
        if len(group) < 2:
            continue
        labels = {replica_label: "ALL", **dict(key)}
        if fam["type"] == "histogram":
            bounds = fam.get("bucket_bounds")
            if bounds is None or any(s.get("_bounds") != bounds
                                     for s in group):
                continue
            try:
                merged = merge_histogram_series(bounds, group)
            except ValueError:
                continue
            merged["labels"] = labels
            fam["series"].append(merged)
        elif fam["type"] == "counter":
            fam["series"].append({
                "labels": labels,
                "value": sum(float(s.get("value", 0)) for s in group)})
        # gauges: summing or averaging fabricates a value no process
        # reported — per-replica rows only


def _load_dump(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def health_rollup() -> Dict:
    """Worst-of fleet health from everything attached to the debug layer:
    per-server ``health()``, per-pool replica membership, autoscaler
    cooldown/hysteresis, supervisor worker epochs."""
    from . import debug_server as _dbg

    status = "ok"
    servers = []
    for srv in _dbg.attached_servers():
        try:
            h = srv.health()
        except Exception as e:
            h = {"state": f"error: {e}"}
        st = str(h.get("state", "?"))
        s = "ok" if st in ("serving", "running", "ok") else \
            ("down" if st in ("stopped", "closed") else "degraded")
        servers.append({"state": st, "status": s, "health": h})
        if _HEALTH_RANK.get(s, 1) > _HEALTH_RANK[status]:
            status = s
    pools = []
    rotation = draining = 0
    for pool in _dbg.attached_pools():
        try:
            psnap = pool.snapshot()
        except Exception as e:
            psnap = {"error": str(e)}
        pools.append(psnap)
        for r in psnap.get("replicas", []):
            if r.get("state") == "rotation":
                rotation += 1
            else:
                draining += 1
    autoscalers = []
    for asc in _dbg.attached_autoscalers():
        try:
            autoscalers.append(asc.snapshot())
        except Exception as e:
            autoscalers.append({"error": str(e)})
    epochs = {}
    for srv in _dbg.attached_servers():
        try:
            h = srv.health()
            if "worker_epoch" in h:
                epochs[str(id(srv))] = {
                    "worker_epoch": h.get("worker_epoch"),
                    "failovers": h.get("failovers")}
        except Exception:
            pass
    _FLEET_REPLICAS.labels("rotation").set(rotation)
    _FLEET_REPLICAS.labels("draining").set(draining)
    _FLEET_HEALTH.set(_HEALTH_RANK[status])
    return {"status": status, "servers": servers, "pools": pools,
            "replicas": {"rotation": rotation, "draining": draining},
            "autoscalers": autoscalers, "supervisor_epochs": epochs}


class FleetCollector:
    """Merge the local registry with sibling processes' snapshot dumps.

    Sources:
      - the live in-process registry (``include_local``, label
        ``local-<pid>``);
      - explicit ``add_snapshot(label, snap)`` / ``add_file(path)``;
      - every file matching ``MXNET_FLEET_DUMP_GLOB`` (or an explicit
        ``glob`` argument) at :meth:`collect` time — the reporter dump
        files subprocesses already write.
    """

    def __init__(self, include_local: bool = True,
                 local_label: Optional[str] = None,
                 glob: Optional[str] = None):
        self.include_local = include_local
        self.local_label = local_label or f"local-{os.getpid()}"
        self._glob = glob
        self._snaps: Dict[str, Dict] = {}

    def add_snapshot(self, label: str, snap: Dict) -> "FleetCollector":
        self._snaps[str(label)] = snap
        return self

    def add_file(self, path: str,
                 label: Optional[str] = None) -> "FleetCollector":
        snap = _load_dump(path)
        if snap is not None:
            self.add_snapshot(label or os.path.basename(path), snap)
        return self

    def _dump_glob(self) -> str:
        if self._glob is not None:
            return self._glob
        try:
            from .. import config
            return str(config.get("MXNET_FLEET_DUMP_GLOB", "") or "")
        except Exception:
            return ""

    def collect(self) -> Dict:
        """One fleet view: merged metrics + per-source freshness + the
        health rollup. Refreshes the ``mxtpu_fleet_*`` gauges."""
        snaps = dict(self._snaps)
        pattern = self._dump_glob()
        if pattern:
            for path in sorted(_glob.glob(pattern)):
                snap = _load_dump(path)
                if snap is not None:
                    snaps.setdefault(os.path.basename(path), snap)
        health = health_rollup()   # before the local snapshot: the fleet
        # gauges it refreshes should be visible in this collection
        if self.include_local:
            snaps[self.local_label] = REGISTRY.snapshot()
        _FLEET_PROCESSES.set(len(snaps))
        sources = {
            label: {"ts": snap.get("ts"),
                    "age_s": (round(time.time() - snap["ts"], 3)
                              if snap.get("ts") else None),
                    "families": len(snap.get("metrics") or {})}
            for label, snap in snaps.items()}
        return {"ts": time.time(),
                "processes": len(snaps),
                "sources": sources,
                "merged": merge_snapshots(snaps),
                "health": health}


def collect(**kw) -> Dict:
    """One-shot :class:`FleetCollector` collection (the ``/fleetz`` page)."""
    return FleetCollector(**kw).collect()
