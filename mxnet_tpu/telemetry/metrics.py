"""Thread-safe metrics registry: Counter / Gauge / Histogram with labels.

Design (the TensorFlow-Serving / Prometheus client model, PAPERS.md: fleet
counters are what make a fast kernel stack operable):

  - one process-wide ``MetricsRegistry`` (``telemetry.REGISTRY``); subsystems
    get-or-create metric *families* at import time and bump pre-bound label
    children on the hot path — no dict lookup, no string formatting, one
    short lock per bump (same sink discipline as profiler/monitor).
  - metric names are linted at registration (``^mxtpu_[a-z0-9_]+$``, unique
    per process) so a rename can never silently break a dashboard.
  - ``snapshot()`` renders the whole registry as one JSON-able dict;
    ``prometheus_text()`` renders the text exposition format
    (``# HELP``/``# TYPE`` + samples) scrapable by any Prometheus agent.

Histograms use fixed log-spaced buckets (powers of two in microseconds by
default: 1 us .. ~17.9 min over 30 bounds) so p50/p95/p99 are recoverable at
~constant relative error without retaining samples, and every histogram in
the process shares the same bucket layout — cross-metric ratios stay honest.
"""
from __future__ import annotations

import json
import re
import threading
import time
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "DEFAULT_BUCKETS", "METRIC_NAME_RE"]

# dashboards key on metric names: lint them at registration, not at scrape.
# mxlint rule MET300 (mxnet_tpu.analysis, STATIC_ANALYSIS.md) enforces the
# same pattern statically on literal names, so violations gate in review
# before any process ever registers them; this runtime check remains the
# authority for dynamically-built names.
METRIC_NAME_RE = re.compile(r"^mxtpu_[a-z0-9_]+$")

# fixed log-spaced duration buckets: 2^(k/2) microseconds (ratio ~1.41,
# quantile error <=~19%), 1 us .. ~25 min over 62 bounds
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    round(2.0 ** (k / 2.0), 3) for k in range(62))


def _quantile_from_buckets(bounds: Sequence[float], counts: Sequence[int],
                           n: int, p: float, max_seen: float) -> float:
    """Approximate p-quantile (p in [0,100]) as the geometric midpoint of the
    bucket holding the rank; the +Inf bucket reports the observed max."""
    if n == 0:
        return 0.0
    rank = max(1, int(round(p / 100.0 * n)))
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank:
            if i >= len(bounds):          # +Inf overflow bucket
                return max_seen
            hi = bounds[i]
            lo = bounds[i - 1] if i > 0 else hi / 2.0
            return (lo * hi) ** 0.5
    return max_seen


class _Child:
    """One labeled time series. Base for counter/gauge children."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    @property
    def value(self) -> float:
        return self._value


class _CounterChild(_Child):
    def inc(self, n: float = 1.0):
        if n < 0:
            raise MXNetError("counters only go up; use a Gauge")
        with self._lock:
            self._value += n


class _GaugeChild(_Child):
    def set(self, v: float):
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0):
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0):
        with self._lock:
            self._value -= n


class _HistogramChild:
    __slots__ = ("_lock", "bounds", "counts", "n", "total", "min", "max")

    def __init__(self, bounds: Tuple[float, ...]):
        self._lock = threading.Lock()
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)   # last = +Inf overflow
        self.n = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def observe(self, v: float):
        v = float(v)
        idx = bisect_left(self.bounds, v)
        with self._lock:
            self.counts[idx] += 1
            self.n += 1
            self.total += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def percentile(self, p: float) -> float:
        with self._lock:
            return _quantile_from_buckets(self.bounds, self.counts, self.n,
                                          p, self.max)

    def summary(self) -> Dict[str, float]:
        with self._lock:
            n, total = self.n, self.total
            counts = list(self.counts)
            mx = self.max
            mn = self.min if n else 0.0
        return {
            "count": n,
            "sum": total,
            "mean": (total / n) if n else 0.0,
            "min": mn,
            "max": mx,
            "p50": _quantile_from_buckets(self.bounds, counts, n, 50, mx),
            "p95": _quantile_from_buckets(self.bounds, counts, n, 95, mx),
            "p99": _quantile_from_buckets(self.bounds, counts, n, 99, mx),
        }


class _MetricFamily:
    """A named metric plus its labeled children. ``labels()`` interns the
    child so hot paths bind it once and never re-resolve."""

    kind = "untyped"
    _child_cls = _CounterChild

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()
        if not self.labelnames:
            self._default = self._make_child()
            self._children[()] = self._default
        else:
            self._default = None

    def _make_child(self):
        return self._child_cls()

    def labels(self, *labelvalues, **labelkv):
        if labelkv:
            try:
                labelvalues = tuple(str(labelkv[k]) for k in self.labelnames)
            except KeyError as e:
                raise MXNetError(
                    f"{self.name}: missing label {e.args[0]!r} "
                    f"(expects {self.labelnames})") from None
        else:
            labelvalues = tuple(str(v) for v in labelvalues)
        if len(labelvalues) != len(self.labelnames):
            raise MXNetError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {labelvalues}")
        child = self._children.get(labelvalues)
        if child is None:
            with self._lock:
                child = self._children.setdefault(labelvalues,
                                                  self._make_child())
        return child

    def _series(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    # unlabeled convenience passthroughs -----------------------------------
    def _default_child(self):
        if self._default is None:
            raise MXNetError(f"{self.name} is labeled {self.labelnames}; "
                             "call .labels(...) first")
        return self._default


class Counter(_MetricFamily):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, n: float = 1.0):
        self._default_child().inc(n)

    @property
    def value(self):
        return self._default_child().value


class Gauge(_MetricFamily):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, v: float):
        self._default_child().set(v)

    def inc(self, n: float = 1.0):
        self._default_child().inc(n)

    def dec(self, n: float = 1.0):
        self._default_child().dec(n)

    @property
    def value(self):
        return self._default_child().value


class Histogram(_MetricFamily):
    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=None):
        self.buckets = tuple(float(b) for b in (buckets or DEFAULT_BUCKETS))
        if list(self.buckets) != sorted(self.buckets):
            raise MXNetError(f"{name}: histogram buckets must be ascending")
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float):
        self._default_child().observe(v)

    def percentile(self, p: float) -> float:
        return self._default_child().percentile(p)

    def summary(self):
        return self._default_child().summary()


class MetricsRegistry:
    """Process-wide metric registry. get-or-create semantics: re-registering
    the same (name, kind, labelnames) returns the existing family, so every
    module can declare its metrics idempotently at import time."""

    def __init__(self):
        self._metrics: Dict[str, _MetricFamily] = {}
        self._lock = threading.Lock()

    # -- registration -------------------------------------------------------
    def _register(self, cls, name, help, labelnames, **kw) -> _MetricFamily:
        if not METRIC_NAME_RE.match(name):
            raise MXNetError(
                f"metric name {name!r} fails the lint "
                f"{METRIC_NAME_RE.pattern!r}: all metrics are namespaced "
                "mxtpu_ and lowercase so dashboards never break on a rename")
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (existing.kind != cls.kind
                        or existing.labelnames != tuple(labelnames)):
                    raise MXNetError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.labelnames}; got "
                        f"{cls.kind}{tuple(labelnames)}")
                return existing
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets)

    # -- introspection ------------------------------------------------------
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def get(self, name: str) -> Optional[_MetricFamily]:
        with self._lock:
            return self._metrics.get(name)

    def lint_names(self) -> List[str]:
        """Return lint violations (empty = clean). Registration already
        enforces the pattern; this re-checks the live registry so CI can
        assert the invariant end-to-end."""
        bad = []
        for name in self.names():
            if not METRIC_NAME_RE.match(name):
                bad.append(f"{name}: fails {METRIC_NAME_RE.pattern}")
        return bad

    # -- export -------------------------------------------------------------
    def snapshot(self) -> Dict:
        """The whole registry as one JSON-able dict."""
        out = {"ts": time.time(), "metrics": {}}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            series = []
            for labelvalues, child in m._series():
                entry = {"labels": dict(zip(m.labelnames, labelvalues))}
                if m.kind == "histogram":
                    entry.update(child.summary())
                    with child._lock:
                        # raw per-bucket counts (last = +Inf overflow): a
                        # snapshot file round-trips to full Prometheus
                        # exposition (tools/metrics_dump.py --prom)
                        entry["bucket_counts"] = list(child.counts)
                else:
                    entry["value"] = child.value
                series.append(entry)
            fam = {
                "type": m.kind, "help": m.help,
                "label_names": list(m.labelnames), "series": series,
            }
            if m.kind == "histogram":
                fam["bucket_bounds"] = list(m.buckets)
            out["metrics"][m.name] = fam
        return out

    def snapshot_json(self, **dumps_kw) -> str:
        return json.dumps(self.snapshot(), **dumps_kw)

    def prometheus_text(self) -> str:
        """Prometheus text exposition (version 0.0.4)."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for labelvalues, child in m._series():
                base = dict(zip(m.labelnames, labelvalues))
                if m.kind == "histogram":
                    with child._lock:
                        counts = list(child.counts)
                        n, total = child.n, child.total
                    cum = 0
                    for bound, c in zip(child.bounds, counts):
                        cum += c
                        lines.append(_sample(f"{m.name}_bucket",
                                             {**base, "le": _fmt(bound)}, cum))
                    lines.append(_sample(f"{m.name}_bucket",
                                         {**base, "le": "+Inf"}, n))
                    lines.append(_sample(f"{m.name}_sum", base, total))
                    lines.append(_sample(f"{m.name}_count", base, n))
                else:
                    lines.append(_sample(m.name, base, child.value))
        return "\n".join(lines) + "\n"

    def _reset_for_tests(self):
        """Drop every registered metric (tests only: instrumented modules
        re-create their families lazily via get-or-create)."""
        with self._lock:
            self._metrics.clear()


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _escape_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r"\""))


def _sample(name: str, labels: Dict[str, str], value) -> str:
    if labels:
        inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                         for k, v in labels.items())
        return f"{name}{{{inner}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


def _fmt_value(v) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def prometheus_from_snapshot(snap: Dict) -> str:
    """Render a :meth:`MetricsRegistry.snapshot` dict (e.g. read back from a
    dump file) as Prometheus text exposition — the offline face of
    :meth:`MetricsRegistry.prometheus_text`."""
    lines: List[str] = []
    for name, fam in sorted(snap.get("metrics", {}).items()):
        kind = fam.get("type", "untyped")
        if fam.get("help"):
            lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {kind}")
        bounds = fam.get("bucket_bounds", [])
        for s in fam.get("series", []):
            base = dict(s.get("labels", {}))
            if kind == "histogram":
                counts = s.get("bucket_counts", [])
                cum = 0
                for bound, c in zip(bounds, counts):
                    cum += c
                    lines.append(_sample(f"{name}_bucket",
                                         {**base, "le": _fmt(bound)}, cum))
                lines.append(_sample(f"{name}_bucket",
                                     {**base, "le": "+Inf"}, s.get("count", 0)))
                lines.append(_sample(f"{name}_sum", base, s.get("sum", 0.0)))
                lines.append(_sample(f"{name}_count", base, s.get("count", 0)))
            else:
                lines.append(_sample(name, base, s.get("value", 0)))
    return "\n".join(lines) + "\n"


# the process-wide default registry
REGISTRY = MetricsRegistry()
