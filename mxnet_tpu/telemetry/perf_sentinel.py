"""Perf-regression sentinel: EWMA drift detection over step latencies.

The perf gate (tools/perf_gate.py) enforces budgets at release time; this
module watches the *running* fleet. Every train-step and serving-step
latency observation feeds a per-stream :class:`DriftDetector`: a slow EWMA
tracks the baseline, a fast EWMA tracks "now", and when the fast track sits
above ``baseline * MXNET_PERF_REGRESSION_RATIO`` for
``MXNET_PERF_SUSTAIN_N`` consecutive observations the sentinel emits a
``perf_regression`` flight event (bundle-dumping when a flight directory is
configured) and bumps ``mxtpu_perf_regressions_total``. One spike never
fires — sustained drift does.

After firing, the detector re-baselines at the regressed level: the alert
is edge-triggered (one event per regression episode, not one per step), and
a later *further* regression fires again.

Hot-path cost: one lock, a handful of float ops — noise against a device
step. Disable entirely with MXNET_PERF_SENTINEL=0.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from .metrics import REGISTRY

__all__ = ["DriftDetector", "PerfSentinel", "SENTINEL", "observe"]

_REGRESSIONS = REGISTRY.counter(
    "mxtpu_perf_regressions_total",
    "Sustained latency regressions detected by the EWMA drift sentinel, "
    "by stream (train_step / serving_step.<endpoint>).",
    labelnames=("stream",))
_BASELINE = REGISTRY.gauge(
    "mxtpu_perf_baseline_us",
    "The drift sentinel's slow-EWMA baseline latency per stream.",
    labelnames=("stream",))


def _cfg(name, default):
    try:
        from .. import config
        return config.get(name, default)
    except Exception:
        return default


class DriftDetector:
    """EWMA drift detector for one latency stream (microseconds)."""

    __slots__ = ("stream", "alpha", "ratio", "sustain_n", "warmup_n",
                 "n", "baseline", "fast", "streak", "fired")

    def __init__(self, stream: str, alpha: float, ratio: float,
                 sustain_n: int, warmup_n: int):
        self.stream = stream
        self.alpha = alpha
        self.ratio = ratio
        self.sustain_n = max(1, sustain_n)
        self.warmup_n = max(1, warmup_n)
        self.n = 0
        self.baseline: Optional[float] = None   # slow EWMA
        self.fast: Optional[float] = None       # fast EWMA (4x alpha)
        self.streak = 0
        self.fired = 0

    def observe(self, dur_us: float) -> bool:
        """Feed one latency; True when this observation fires a regression."""
        d = float(dur_us)
        self.n += 1
        if self.baseline is None:
            self.baseline = self.fast = d
            return False
        fast_alpha = min(1.0, self.alpha * 4.0)
        self.fast += fast_alpha * (d - self.fast)
        if self.n <= self.warmup_n:
            # warmup: both tracks converge, nothing can fire
            self.baseline += self.alpha * (d - self.baseline)
            return False
        if self.fast > self.baseline * self.ratio:
            self.streak += 1
            if self.streak >= self.sustain_n:
                # edge-trigger: re-baseline at the regressed level so the
                # alert fires once per episode
                self.streak = 0
                self.fired += 1
                self.baseline = self.fast
                return True
        else:
            self.streak = 0
            self.baseline += self.alpha * (d - self.baseline)
        return False

    def snapshot(self) -> Dict:
        return {"stream": self.stream, "n": self.n,
                "baseline_us": self.baseline, "fast_us": self.fast,
                "streak": self.streak, "fired": self.fired}


class PerfSentinel:
    """Per-stream drift detectors behind one lock; knobs read at stream
    creation (a new stream after ``config.set`` picks up new values)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._streams: Dict[str, DriftDetector] = {}

    def observe(self, stream: str, dur_us: float):
        """Feed one latency observation; fires the flight trigger on
        sustained regression. Never raises."""
        try:
            if not bool(_cfg("MXNET_PERF_SENTINEL", True)):
                return
            with self._lock:
                det = self._streams.get(stream)
                if det is None:
                    det = DriftDetector(
                        stream,
                        alpha=float(_cfg("MXNET_PERF_EWMA_ALPHA", 0.05)),
                        ratio=float(_cfg("MXNET_PERF_REGRESSION_RATIO", 1.5)),
                        sustain_n=int(_cfg("MXNET_PERF_SUSTAIN_N", 8)),
                        warmup_n=int(_cfg("MXNET_PERF_WARMUP_N", 50)))
                    self._streams[stream] = det
                prev_baseline = det.baseline
                fired = det.observe(dur_us)
                baseline = det.baseline
                fast = det.fast
            _BASELINE.labels(stream).set(baseline or 0.0)
            if fired:
                _REGRESSIONS.labels(stream).inc()
                # report against the pre-episode baseline: firing re-baselines
                # the detector, so det.baseline is already the regressed level
                ref = prev_baseline or baseline
                from . import flight as _flight
                _flight.trigger(
                    "perf_regression", stream=stream,
                    baseline_us=round(ref or 0.0, 1),
                    current_us=round(fast or 0.0, 1),
                    ratio=round((fast / ref) if ref else 0.0, 3))
        except Exception:
            pass

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return {s: d.snapshot() for s, d in self._streams.items()}

    def reset(self):
        with self._lock:
            self._streams.clear()


SENTINEL = PerfSentinel()


def observe(stream: str, dur_us: float):
    """Module-level hook the train/serving step paths call."""
    SENTINEL.observe(stream, dur_us)
