"""Background reporting: periodic logger, snapshot files, device memory.

``periodic_logger(interval)`` starts a daemon thread that, every ``interval``
seconds, samples device memory gauges and emits a one-line summary through
``logging`` (and optionally writes the full JSON snapshot to a file that
``tools/metrics_dump.py`` — or any sidecar scraper — can read while the run
is still going). Runs entirely device-get-free: the only device interaction
is ``device.memory_stats()``, a host-side PJRT query.

Auto-start: setting ``MXNET_TELEMETRY_DUMP_PATH`` makes every process start
a periodic reporter at import (interval ``MXNET_TELEMETRY_DUMP_INTERVAL``),
so long-running jobs are observable without code changes.
"""
from __future__ import annotations

import atexit
import json
import logging
import os
import threading
import time
from typing import Optional

from .metrics import REGISTRY

__all__ = ["sample_device_memory", "periodic_logger", "PeriodicReporter",
           "dump", "summary_line"]

_LOG = logging.getLogger("mxnet_tpu.telemetry")

_DEVICE_MEMORY = REGISTRY.gauge(
    "mxtpu_device_memory_bytes",
    "Per-device memory stats from PJRT device.memory_stats() "
    "(bytes_in_use / peak_bytes_in_use / bytes_limit), sampled host-side.",
    labelnames=("device", "stat"))

_SAMPLE_STATS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                 "largest_alloc_size")


def sample_device_memory() -> int:
    """Refresh ``mxtpu_device_memory_bytes`` from every device that exposes
    ``memory_stats()`` (TPU/GPU backends do; CPU returns None). Returns the
    number of devices sampled. Never raises: observability must not take a
    training job down."""
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return 0
    sampled = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        sampled += 1
        label_dev = f"{d.platform}:{d.id}"
        for key in _SAMPLE_STATS:
            if key in stats:
                _DEVICE_MEMORY.labels(label_dev, key).set(stats[key])
    return sampled


def summary_line() -> str:
    """One human-readable line of the load-bearing series (the heartbeat the
    periodic logger prints)."""
    snap = REGISTRY.snapshot()
    parts = []

    def total(name):
        fam = snap["metrics"].get(name)
        if not fam:
            return None
        return sum(s.get("value", s.get("count", 0)) for s in fam["series"])

    for label, name in (("req", "mxtpu_serving_requests_total"),
                        ("batches", "mxtpu_serving_batches_total"),
                        ("steps", "mxtpu_train_steps_total"),
                        ("jit_miss", "mxtpu_jit_cache_misses_total"),
                        ("compile_s", "mxtpu_serving_compile_seconds_total")):
        v = total(name)
        if v:
            parts.append(f"{label}={v:g}")
    spans = snap["metrics"].get("mxtpu_span_duration_us")
    if spans:
        for s in spans["series"]:
            if s["count"]:
                parts.append(f"{s['labels'].get('name', '?')}"
                             f".p50={s['p50'] / 1e3:.2f}ms")
    return "telemetry: " + (" ".join(parts) if parts else "no activity")


def dump(path: str, prometheus: bool = False):
    """Atomically write the current snapshot (JSON, or Prometheus text) to
    ``path`` — the file ``tools/metrics_dump.py`` reads."""
    payload = (REGISTRY.prometheus_text() if prometheus
               else json.dumps(REGISTRY.snapshot(), indent=1, sort_keys=True))
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, path)


class PeriodicReporter:
    """Daemon-thread reporter; ``stop()`` (or context-exit) halts it."""

    def __init__(self, interval: float = 10.0, path: Optional[str] = None,
                 logger: Optional[logging.Logger] = None,
                 prometheus: bool = False):
        if interval <= 0:
            raise ValueError("interval must be > 0")
        self.interval = float(interval)
        self.path = path
        self.prometheus = prometheus
        self._log = logger or _LOG
        self._stop = threading.Event()
        self._stopped = False
        self._stop_lock = threading.Lock()
        # interpreter exit between ticks would silently drop the final
        # interval's snapshot — atexit guarantees one last dump lands
        atexit.register(self.stop)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mxtpu-telemetry-reporter")
        self._thread.start()

    def _tick(self):
        sample_device_memory()
        self._log.info("%s", summary_line())
        if self.path:
            try:
                dump(self.path, prometheus=self.prometheus)
            except OSError as e:
                self._log.warning("telemetry dump to %s failed: %s",
                                  self.path, e)

    def _run(self):
        while not self._stop.wait(self.interval):
            self._tick()

    def stop(self, final_tick: bool = True):
        """Stop the reporter; by default take one last sample/dump so the
        file on disk reflects end-of-run state. Idempotent: the atexit hook
        and an explicit stop() cannot double-tick."""
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        atexit.unregister(self.stop)
        self._stop.set()
        self._thread.join(timeout=self.interval + 5)
        if final_tick:
            self._tick()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def periodic_logger(interval: float = 10.0, path: Optional[str] = None,
                    logger: Optional[logging.Logger] = None,
                    prometheus: bool = False) -> PeriodicReporter:
    """Start a background reporter; returns its handle (call ``.stop()``)."""
    return PeriodicReporter(interval, path=path, logger=logger,
                            prometheus=prometheus)


def _autostart() -> Optional[PeriodicReporter]:
    """Env-driven reporter start (called once from mxnet_tpu/__init__)."""
    from .. import config
    path = config.get("MXNET_TELEMETRY_DUMP_PATH")
    if not path:
        return None
    interval = config.get("MXNET_TELEMETRY_DUMP_INTERVAL")
    return periodic_logger(interval, path=path,
                           prometheus=path.endswith(".prom"))
