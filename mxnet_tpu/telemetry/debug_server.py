"""Live HTTP introspection for a running fleet process (stdlib-only).

Off by default; ``MXNET_DEBUG_PORT`` (or an explicit ``DebugServer(port)``)
starts a ``ThreadingHTTPServer`` on localhost serving the -z pages every
production RPC server grows eventually:

  /metricsz   Prometheus text exposition (``telemetry.prometheus_text()``)
  /healthz    JSON liveness: 200 when every attached InferenceServer is
              running and no circuit is OPEN, else 503 — a load balancer
              can point straight at it
  /statusz    human summary: per-endpoint latency quantiles from the
              histogram buckets, batch occupancy, prep/step overlap, queue
              depths, SLO burn rates, checkpoint staleness, flight state
  /tracez     recent finished spans grouped by trace id (flight span ring)
  /flightz    flight bundle listing; ``/flightz?dump=1`` triggers a manual
              bundle right now
  /compilez   compile-ledger view: totals per site, duplicate-fingerprint
              waste, recent records ranked by compile seconds
  /costz      learned cost model: active artifact version + holdout
              metrics, top feature importances, per-site residual drift
              state, per-endpoint predicted-vs-measured bucket tables
  /memz       HBM attribution: device memory_stats() (refreshed on demand)
              reconciled against the registered holder table
  /fleetz     fleet plane (JSON): merged per-replica metrics (local registry
              + MXNET_FLEET_DUMP_GLOB snapshot files), worst-of health
              rollup across attached servers/pools/autoscalers, and the
              goodput wall-time attribution + utilization estimates

``/metricsz?json=1`` serves the registry snapshot as JSON — the same shape
``telemetry.dump()`` writes — so a FleetCollector in another process can
scrape this one instead of reading its dump file.

The handler only ever *reads* — registry snapshots, ring copies, ``health()``
dicts — so scraping cannot perturb serving beyond a snapshot's cost, and
concurrent scrapes are safe by construction (each request gets its own
handler thread; shared state is behind the registry/ring locks).
"""
from __future__ import annotations

import json
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from .metrics import REGISTRY
from . import flight as _flight

__all__ = ["DebugServer", "attach", "detach", "attached_servers",
           "attach_pool", "detach_pool", "attached_pools",
           "attach_autoscaler", "detach_autoscaler", "attached_autoscalers"]

_SCRAPES = REGISTRY.counter(
    "mxtpu_debug_requests_total",
    "Debug-server HTTP requests served, by page.",
    labelnames=("page",))

# InferenceServers that want to appear on /healthz + /statusz register here
# (weakly: a dead server drops off the page instead of pinning memory).
_ATTACHED: "weakref.WeakValueDictionary[int, object]" = \
    weakref.WeakValueDictionary()
# ServingPools and Autoscalers get their own weak registries: a pooled
# deployment's replica membership and scaling state render on the same
# pages, and drop off when the pool is garbage-collected.
_ATTACHED_POOLS: "weakref.WeakValueDictionary[int, object]" = \
    weakref.WeakValueDictionary()
_ATTACHED_AUTOSCALERS: "weakref.WeakValueDictionary[int, object]" = \
    weakref.WeakValueDictionary()
_ATTACH_LOCK = threading.Lock()


def _cfg(name, default):
    try:
        from .. import config
        return config.get(name, default)
    except Exception:
        return default


def attach(server):
    """Expose an InferenceServer on /healthz and /statusz (idempotent)."""
    with _ATTACH_LOCK:
        _ATTACHED[id(server)] = server


def detach(server):
    with _ATTACH_LOCK:
        _ATTACHED.pop(id(server), None)


def attached_servers() -> List[object]:
    with _ATTACH_LOCK:
        return list(_ATTACHED.values())


def attach_pool(pool):
    """Expose a ServingPool (replica membership, per-replica load) on
    /healthz, /statusz and /fleetz (idempotent, weak)."""
    with _ATTACH_LOCK:
        _ATTACHED_POOLS[id(pool)] = pool


def detach_pool(pool):
    with _ATTACH_LOCK:
        _ATTACHED_POOLS.pop(id(pool), None)


def attached_pools() -> List[object]:
    with _ATTACH_LOCK:
        return list(_ATTACHED_POOLS.values())


def attach_autoscaler(asc):
    """Expose an Autoscaler (cooldown, hysteresis poll counts, action
    history) on /statusz and /fleetz (idempotent, weak)."""
    with _ATTACH_LOCK:
        _ATTACHED_AUTOSCALERS[id(asc)] = asc


def detach_autoscaler(asc):
    with _ATTACH_LOCK:
        _ATTACHED_AUTOSCALERS.pop(id(asc), None)


def attached_autoscalers() -> List[object]:
    with _ATTACH_LOCK:
        return list(_ATTACHED_AUTOSCALERS.values())


# -- page renderers (module functions so tests can call them directly) --------

def healthz() -> "tuple[int, Dict]":
    """(http_status, body): 200 iff every attached server is running with no
    OPEN circuit. A process with nothing attached is alive by definition."""
    servers = attached_servers()
    body: Dict = {"ok": True, "servers": []}
    for srv in servers:
        try:
            h = srv.health()
        except Exception as e:
            body["servers"].append({"error": repr(e)})
            body["ok"] = False
            continue
        entry = {"state": h.get("state"), "circuit": h.get("circuit"),
                 "endpoints": sorted(h.get("endpoints", {}))}
        body["servers"].append(entry)
        if h.get("state") != "running" or h.get("circuit") == "open":
            body["ok"] = False
    pools = attached_pools()
    if pools:
        body["pools"] = []
        for pool in pools:
            try:
                ps = pool.snapshot()
            except Exception as e:
                body["pools"].append({"error": repr(e)})
                body["ok"] = False
                continue
            body["pools"].append({
                "replicas": ps.get("size", 0),
                "rotation": [r.get("rid") for r in ps.get("replicas", [])],
                "queue_pressure": ps.get("queue_pressure")})
            if not ps.get("size"):
                body["ok"] = False
    return (200 if body["ok"] else 503), body


def _fmt_us(v: float) -> str:
    if v >= 1e6:
        return f"{v / 1e6:.2f}s"
    if v >= 1e3:
        return f"{v / 1e3:.2f}ms"
    return f"{v:.0f}us"


def _gauge_series(snap: Dict, name: str):
    fam = snap["metrics"].get(name)
    if not fam:
        return []
    return [(s.get("labels", {}), s.get("value", 0.0))
            for s in fam["series"]]


def statusz() -> str:
    """The one-page human summary an on-call engineer reads first."""
    from .reporter import sample_device_memory
    sample_device_memory()
    snap = REGISTRY.snapshot()
    lines = [f"mxnet_tpu statusz  ts={time.strftime('%Y-%m-%d %H:%M:%S')}"]

    lines.append("")
    lines.append("== serving ==")
    servers = attached_servers()
    if not servers:
        lines.append("(no InferenceServer attached)")
    for srv in servers:
        try:
            h = srv.health()
        except Exception as e:
            lines.append(f"server: health() failed: {e!r}")
            continue
        lines.append(
            f"server: state={h.get('state')} circuit={h.get('circuit')} "
            f"worker_epoch={h.get('worker_epoch')} "
            f"failovers={h.get('failovers')} "
            f"watchdog_stalls={h.get('watchdog_stalls')} "
            f"prep_overlap_ratio={h.get('prep_overlap_ratio', 0):.2f}")
        for name, ep in sorted(h.get("endpoints", {}).items()):
            lines.append(
                f"  endpoint {name}: circuit={ep.get('circuit')} "
                f"pending={ep.get('pending_requests')} "
                f"rows={ep.get('pending_rows')} "
                f"slo_ms={ep.get('slo_ms')} "
                f"weights_epoch={ep.get('weights_epoch')}")

    pools = attached_pools()
    autoscalers = attached_autoscalers()
    if pools or autoscalers:
        lines.append("")
        lines.append("== serving pool ==")
        for pool in pools:
            try:
                ps = pool.snapshot()
            except Exception as e:
                lines.append(f"pool: snapshot() failed: {e!r}")
                continue
            lines.append(f"pool: replicas={ps.get('size', 0)} "
                         f"queue_pressure={ps.get('queue_pressure', 0):.3f}")
            for r in ps.get("replicas", []):
                lines.append(f"  replica {r.get('rid')}: "
                             f"state={r.get('state')} load={r.get('load')}")
        for asc in autoscalers:
            try:
                asnap = asc.snapshot()
            except Exception as e:
                lines.append(f"autoscaler: snapshot() failed: {e!r}")
                continue
            lines.append(
                f"autoscaler: replicas "
                f"[{asnap.get('min_replicas')}..{asnap.get('max_replicas')}] "
                f"over_polls={asnap.get('over_polls')}/{asnap.get('up_n')} "
                f"idle_polls={asnap.get('idle_polls')}/{asnap.get('down_n')} "
                f"cooldown={'yes' if asnap.get('in_cooldown') else 'no'} "
                f"(cooldown_s={asnap.get('cooldown_s')} "
                f"last_action_age_s={asnap.get('last_action_age_s')})")
            for act in asnap.get("actions", [])[-5:]:
                lines.append(f"  action: {act.get('action')} "
                             f"rid={act.get('rid')} -> "
                             f"replicas={act.get('replicas')}")

    lat = snap["metrics"].get("mxtpu_serving_request_latency_us")
    if lat and any(s.get("count") for s in lat["series"]):
        lines.append("")
        lines.append("== request latency (from histogram buckets) ==")
        for s in lat["series"]:
            if not s.get("count"):
                continue
            ep = s.get("labels", {}).get("endpoint", "?")
            lines.append(
                f"  {ep}: n={s['count']} p50={_fmt_us(s['p50'])} "
                f"p95={_fmt_us(s['p95'])} p99={_fmt_us(s['p99'])} "
                f"mean={_fmt_us(s['mean'])} max={_fmt_us(s['max'])}")

    rows = []
    for labels, v in _gauge_series(snap, "mxtpu_serving_queue_depth"):
        rows.append(f"  queue_depth{{{labels.get('endpoint', '?')}}}={v:g}")
    for labels, v in _gauge_series(snap, "mxtpu_serving_batch_occupancy"):
        rows.append(f"  occupancy{{{labels.get('endpoint', '?')}}}={v:.2f}")
    for _labels, v in _gauge_series(snap, "mxtpu_serving_prep_overlap_ratio"):
        rows.append(f"  prep_overlap_ratio={v:.2f}")
    if rows:
        lines.append("")
        lines.append("== queues / pipeline ==")
        lines.extend(rows)

    from . import slo as _slo
    objectives = _slo.MONITOR.snapshot()
    if objectives:
        lines.append("")
        lines.append("== slo burn ==")
        for st in objectives:
            alert = "ALERT" if st["alert_active"] else "ok"
            lines.append(
                f"  {st['endpoint']}: fast={st['fast_burn']:.2f}x "
                f"slow={st['slow_burn']:.2f}x [{alert}] "
                f"target={st['target']:.4%} "
                f"threshold={_fmt_us(st['threshold_us'])}")

    ck = _gauge_series(snap, "mxtpu_checkpoint_last_step")
    if ck:
        lines.append("")
        lines.append("== checkpoint ==")
        for labels, v in ck:
            label = ",".join(f"{k}={val}" for k, val in sorted(labels.items()))
            lines.append(f"  last_step{{{label}}}={v:g}")
        saves = _gauge_series(snap, "mxtpu_checkpoint_saves_total")
        for labels, v in saves:
            lines.append(f"  saves_total={v:g}")

    lines.append("")
    lines.append("== flight recorder ==")
    d = _flight.RECORDER.directory
    lines.append(f"  dir={d or '(unset: ring-only, no bundles)'} "
                 f"spans={len(_flight.RECORDER._spans)} "
                 f"events={len(_flight.RECORDER._events)} "
                 f"requests={len(_flight.RECORDER._requests)}")
    for ev in _flight.recent_events()[-5:]:
        lines.append(f"  last: {ev['kind']} "
                     f"@{time.strftime('%H:%M:%S', time.localtime(ev['ts']))}"
                     f" {ev['attrs']}")
    return "\n".join(lines) + "\n"


def tracez(limit_traces: int = 50) -> str:
    """Recent finished spans grouped by trace id, newest trace first."""
    spans = _flight.recent_spans()
    by_trace: Dict[str, List[Dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    groups = sorted(by_trace.items(),
                    key=lambda kv: max(s["t0_us"] for s in kv[1]),
                    reverse=True)[:limit_traces]
    lines = [f"tracez: {len(spans)} spans in ring, {len(by_trace)} traces "
             f"(showing {len(groups)})"]
    for trace_id, group in groups:
        group.sort(key=lambda s: s["t0_us"])
        t0 = group[0]["t0_us"]
        lines.append("")
        lines.append(f"trace {trace_id}")
        for s in group:
            dur = s["dur_us"] if s["dur_us"] is not None else 0
            attrs = f" {s['attrs']}" if s["attrs"] else ""
            lines.append(f"  +{(s['t0_us'] - t0) / 1e3:9.3f}ms "
                         f"{_fmt_us(dur):>10} {s['name']}{attrs}")
    return "\n".join(lines) + "\n"


def flightz(do_dump: bool = False) -> Dict:
    body: Dict = {"dir": _flight.RECORDER.directory or None}
    if do_dump:
        body["dumped"] = _flight.dump(trigger="flightz")
    d = _flight.RECORDER.directory
    body["bundles"] = [
        {"path": p, "bytes": _safe_size(p)} for p in _flight.list_bundles(d)
    ] if d else []
    body["recent_events"] = _flight.recent_events()[-20:]
    return body


def _fmt_bytes(v: float) -> str:
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024.0 or unit == "GiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{v:.0f}B"
        v /= 1024.0
    return f"{v:.1f}GiB"


def compilez(top_n: int = 20) -> str:
    """Compile-ledger page: process totals, per-site breakdown, duplicate
    waste, and the recent records ranked by compile seconds."""
    from . import compile_ledger as _ledger
    s = _ledger.summary()
    records = _ledger.recent()
    lines = [f"compilez  ts={time.strftime('%Y-%m-%d %H:%M:%S')} "
             f"ledger_dir={_ledger.ledger_dir() or '(unset: ring-only)'}"]
    lines.append("")
    lines.append(
        f"compiles={s['compiles']} distinct={s['distinct_fingerprints']} "
        f"duplicates={s['duplicates']} dup_waste_s={s['dup_waste_s']:.3f} "
        f"cache_hits={s.get('cache_hits', 0)} "
        f"lower_s={s['lower_s']:.3f} compile_s={s['compile_s']:.3f}")
    try:
        from ..cache import executable_cache as _xcache
        cs = _xcache.stats()
        if cs["enabled"]:
            lines.append(
                f"exec_cache: hits={cs['hits']} misses={cs['misses']} "
                f"hit_rate={cs['hit_rate'] if cs['hit_rate'] is not None else '-'} "
                f"stores={cs['stores']} evictions={cs['evictions']} "
                f"bytes={_fmt_bytes(cs['bytes'])} "
                f"deserialize_s={cs['deserialize_s']:.3f} dir={cs['dir']}")
        else:
            lines.append("exec_cache: disabled (MXNET_EXEC_CACHE_DIR unset)")
    except Exception:
        pass
    by_site: Dict[str, Dict[str, float]] = {}
    for r in records:
        st = by_site.setdefault(r["site"], {"n": 0, "dup": 0, "hit": 0,
                                            "s": 0.0})
        st["n"] += 1
        st["dup"] += 1 if r.get("duplicate") else 0
        st["hit"] += 1 if r.get("cache_hit") else 0
        st["s"] += r["lower_s"] + r["compile_s"]
    if by_site:
        lines.append("")
        lines.append("== per site ==")
        for site, st in sorted(by_site.items()):
            lines.append(f"  {site}: n={st['n']:.0f} dup={st['dup']:.0f} "
                         f"cache_hit={st['hit']:.0f} wall_s={st['s']:.3f}")
    ranked = sorted(records, key=lambda r: r["lower_s"] + r["compile_s"],
                    reverse=True)[:top_n]
    if ranked:
        lines.append("")
        lines.append(f"== top {len(ranked)} by wall seconds ==")
        for r in ranked:
            fp = (r.get("fingerprint") or "?")[:12]
            flops = r.get("flops")
            ba = r.get("bytes_accessed")
            ratio = (f" flops/byte={flops / ba:.2f}"
                     if flops and ba else "")
            dup = " DUP" if r.get("duplicate") else ""
            hit = " HIT" if r.get("cache_hit") else ""
            key = ",".join(f"{k}={v}" for k, v in sorted(r["key"].items()))
            lines.append(
                f"  {fp} {r['site']:<14} lower={r['lower_s'] * 1e3:8.1f}ms "
                f"compile={r['compile_s'] * 1e3:8.1f}ms{ratio}{dup}{hit} "
                f"[{key}]")
    return "\n".join(lines) + "\n"


def costz(top_n: int = 12) -> str:
    """Cost-observatory page: the active model artifact (version, holdout
    metrics, top feature importances per target), the residual drift state
    per site, and each attached endpoint's predicted-vs-measured bucket
    table (prior, measured EWMA, blended estimate)."""
    from . import costmodel as _costmodel
    snap = _costmodel.snapshot()
    lines = [f"costz  ts={time.strftime('%Y-%m-%d %H:%M:%S')} "
             f"path={snap.get('path') or '(unset)'} "
             f"prior_enabled={snap.get('prior_enabled')}"]
    lines.append("")
    info = snap.get("model")
    if info is None:
        why = snap.get("error")
        lines.append("model: none active"
                     + (f" (load error: {why})" if why else ""))
    else:
        lines.append(f"model: version={info['version']} "
                     f"schema={info['schema']} "
                     f"n_samples={info.get('n_samples')} "
                     f"source={info.get('source') or '-'}")
        m = _costmodel.active_model()
        for target, met in sorted((info.get("targets") or {}).items()):
            lines.append(
                f"  {target}: n_train={met.get('n_train')} "
                f"n_holdout={met.get('n_holdout')} "
                f"holdout_mape={met.get('holdout_mape', '-')} "
                f"row_ratio_mape={met.get('row_ratio_mape', '-')}")
            if m is not None:
                imp = ", ".join(f"{n}={w:+.3f}"
                                for n, w in m.importances(target, top_n))
                lines.append(f"    importances: {imp}")
    res = snap.get("residuals") or {}
    if res:
        lines.append("")
        lines.append("== residual drift (measured / predicted) ==")
        for site, st in sorted(res.items()):
            lines.append(
                f"  {site}: band={st['band']} sustain_n={st['sustain_n']} "
                f"streak={st['streak']} latched={st['latched']} "
                f"fired={st['fired']}")
            for b, info_b in sorted(st.get("buckets", {}).items(),
                                    key=lambda kv: int(kv[0])):
                lines.append(
                    f"    bucket {b}: predicted_us="
                    f"{info_b.get('predicted_us', '-')} "
                    f"measured_us={info_b.get('measured_us', '-')} "
                    f"ratio={info_b.get('ratio', '-')} "
                    f"n={info_b.get('n', 0):.0f}")
    for srv in attached_servers():
        try:
            h = srv.health()
        except Exception:
            continue
        for name, ep in sorted((h.get("endpoints") or {}).items()):
            sc = ep.get("step_cost")
            if not sc:
                continue
            lines.append("")
            lines.append(f"== {name} step cost (blend_n={sc['blend_n']} "
                         f"prior={sc['prior']}) ==")
            for b, info_b in sorted(sc.get("buckets", {}).items()):
                meas = info_b.get("measured_us")
                prior = info_b.get("prior_us")
                lines.append(
                    f"  bucket {b}: est_us={info_b.get('est_us', 0):.1f} "
                    f"measured_us={'-' if meas is None else f'{meas:.1f}'} "
                    f"prior_us={'-' if prior is None else f'{prior:.1f}'} "
                    f"n={info_b.get('n', 0)}")
    return "\n".join(lines) + "\n"


def memz() -> str:
    """HBM-attribution page. Refreshes the device-memory gauges on demand
    (the page IS the scrape) before reconciling the holder table."""
    from .reporter import sample_device_memory
    from . import memstats as _memstats
    sample_device_memory()
    bd = _memstats.breakdown()
    lines = [f"memz  ts={time.strftime('%Y-%m-%d %H:%M:%S')}"]
    lines.append("")
    lines.append("== devices (memory_stats vs attributed holders) ==")
    if not bd["devices"]:
        lines.append("  (backend reports no memory_stats; holders only)")
    for dev, st in sorted(bd["devices"].items()):
        lines.append(
            f"  {dev}: in_use={_fmt_bytes(st['bytes_in_use'])} "
            f"peak={_fmt_bytes(st['peak_bytes_in_use'])} "
            f"attributed={_fmt_bytes(st['attributed'])} "
            f"unattributed={_fmt_bytes(st['unattributed'])}")
    lines.append("")
    lines.append(f"== holders (top {len(bd['holders'])} of "
                 f"{bd['holders_total']}, "
                 f"attributed={_fmt_bytes(bd['attributed_bytes'])}) ==")
    for h in bd["holders"]:
        dev = f" dev={h['device']}" if h["device"] else ""
        lines.append(f"  {_fmt_bytes(h['bytes']):>10}  "
                     f"peak={_fmt_bytes(h['peak_bytes']):>10}  "
                     f"{h['subsystem']}/{h['holder']}{dev}")
    if bd["holders_omitted_bytes"]:
        lines.append(f"  ... omitted holders: "
                     f"{_fmt_bytes(bd['holders_omitted_bytes'])}")
    return "\n".join(lines) + "\n"


def fleetz() -> Dict:
    """The fleet pane as one JSON document: merged per-replica metrics
    (local registry + MXNET_FLEET_DUMP_GLOB snapshot files), the worst-of
    health rollup, and this process's goodput attribution + per-executable
    utilization estimates. ``tools/fleet_report.py`` renders the offline
    equivalent from dump files alone."""
    from . import fleet as _fleet
    from . import goodput as _goodput
    body = _fleet.collect()
    body["goodput"] = {
        "wall_s": round(_goodput.wall_seconds(), 3),
        "buckets": {k: round(v, 3)
                    for k, v in _goodput.account().items()},
    }
    body["utilization"] = _goodput.utilization()
    return body


def _safe_size(p: str) -> Optional[int]:
    import os
    try:
        return os.path.getsize(p)
    except OSError:
        return None


class _Handler(BaseHTTPRequestHandler):
    # one access-log line per scrape would swamp real logs: stay quiet
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def _send(self, status: int, body: str, ctype: str = "text/plain"):
        data = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", f"{ctype}; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 — http.server API
        url = urlparse(self.path)
        page = url.path.rstrip("/") or "/"
        try:
            if page == "/metricsz":
                q = parse_qs(url.query)
                if q.get("json", ["0"])[0] in ("1", "true", "yes"):
                    # snapshot JSON (the telemetry.dump() shape): the scrape
                    # form of a reporter dump file, for FleetCollectors in
                    # other processes
                    from . import snapshot
                    self._send(200, json.dumps(snapshot(), indent=1,
                                               sort_keys=True),
                               ctype="application/json")
                else:
                    from . import prometheus_text
                    self._send(200, prometheus_text())
            elif page == "/healthz":
                status, body = healthz()
                self._send(status, json.dumps(body, indent=1),
                           ctype="application/json")
            elif page == "/statusz":
                self._send(200, statusz())
            elif page == "/tracez":
                self._send(200, tracez())
            elif page == "/flightz":
                q = parse_qs(url.query)
                body = flightz(do_dump=q.get("dump", ["0"])[0] in
                               ("1", "true", "yes"))
                self._send(200, json.dumps(body, indent=1, default=repr),
                           ctype="application/json")
            elif page == "/compilez":
                self._send(200, compilez())
            elif page == "/costz":
                self._send(200, costz())
            elif page == "/memz":
                self._send(200, memz())
            elif page == "/fleetz":
                self._send(200, json.dumps(fleetz(), indent=1, default=repr),
                           ctype="application/json")
            elif page == "/":
                self._send(200, "mxnet_tpu debug server\n"
                                "pages: /metricsz[?json=1] /healthz "
                                "/statusz /tracez /flightz[?dump=1] "
                                "/compilez /costz /memz /fleetz\n")
            else:
                self._send(404, f"no such page: {page}\n")
                return
            _SCRAPES.labels(page.lstrip("/") or "index").inc()
        except BrokenPipeError:
            pass
        except Exception as e:
            try:
                self._send(500, f"debug page {page} failed: {e!r}\n")
            except Exception:
                pass


class DebugServer:
    """Localhost HTTP introspection server. ``port=0`` binds an ephemeral
    port (tests); read ``.port`` for the actual one."""

    def __init__(self, port: Optional[int] = None, host: Optional[str] = None):
        if port is None:
            port = int(_cfg("MXNET_DEBUG_PORT", 0))
        if host is None:
            host = str(_cfg("MXNET_DEBUG_HOST", "127.0.0.1"))
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "DebugServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="mxtpu-debug-server")
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def _autostart() -> Optional[DebugServer]:
    """Env-driven start (called once from mxnet_tpu/__init__): a nonzero
    MXNET_DEBUG_PORT makes every process self-introspectable."""
    port = int(_cfg("MXNET_DEBUG_PORT", 0))
    if port <= 0:
        return None
    try:
        return DebugServer(port).start()
    except OSError:
        # port taken (multi-process on one host): introspection is
        # best-effort, never fatal
        return None
