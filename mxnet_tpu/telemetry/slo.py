"""Per-tenant SLO objectives with multi-window burn-rate alerting.

An *objective* is "fraction of requests under ``threshold_us`` must be at
least ``target``" (e.g. 99.9% under 50 ms). The monitor classifies every
completed request as good or bad, aggregates per-second buckets over two
sliding windows — a fast window (minutes: catches a sharp regression before
the queue melts) and a slow window (an hour: filters blips) — and computes
the **burn rate**: ``bad_ratio / error_budget`` where the error budget is
``1 - target``. Burn 1.0 means the tenant consumes its budget exactly at the
sustainable pace; burn 14 on a 99.9% objective means the monthly budget is
gone in ~2 days. Following SRE practice the alert fires only when *both*
windows burn above ``MXNET_SLO_BURN_THRESHOLD`` — the fast window gives
latency, the slow window gives de-bounce — and latches until the fast window
recovers, so a single breach episode is one alert, not a firehose.

On alert: ``mxtpu_slo_alerts_total`` bumps, a ``slo_burn_alert`` flight
event is recorded, and — when ``MXNET_SLO_ESCALATE`` is on and the objective
carries the tenant's breaker — the breaker is forced DEGRADED so admission
tightens on the *offending* tenant only (serving sheds its excess instead of
letting it melt every queue).

Objectives are registered from ``InferenceServer.register(slo_ms=...)``;
the process-wide monitor is ``slo.MONITOR``.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .metrics import REGISTRY
from . import flight as _flight

__all__ = ["Objective", "SLOMonitor", "MONITOR"]

_GOOD = REGISTRY.counter(
    "mxtpu_slo_good_total",
    "Requests that met their endpoint's latency objective.",
    labelnames=("endpoint",))
_BAD = REGISTRY.counter(
    "mxtpu_slo_bad_total",
    "Requests that missed their endpoint's latency objective (too slow or "
    "failed).",
    labelnames=("endpoint",))
_BURN = REGISTRY.gauge(
    "mxtpu_slo_burn_rate",
    "Error-budget burn rate (bad_ratio / (1 - target)) per window: 1.0 = "
    "budget consumed exactly at the sustainable pace.",
    labelnames=("endpoint", "window"))
_ALERT_ACTIVE = REGISTRY.gauge(
    "mxtpu_slo_alert_active",
    "1 while an endpoint's multi-window burn alert is latched, else 0.",
    labelnames=("endpoint",))
_ALERTS = REGISTRY.counter(
    "mxtpu_slo_alerts_total",
    "Burn-rate alert episodes fired (both windows over threshold).",
    labelnames=("endpoint",))
_ESCALATIONS = REGISTRY.counter(
    "mxtpu_slo_escalations_total",
    "Burn alerts that escalated the offending tenant's breaker to DEGRADED "
    "(MXNET_SLO_ESCALATE).",
    labelnames=("endpoint",))


def _cfg(name, default):
    try:
        from .. import config
        return config.get(name, default)
    except Exception:
        return default


class Objective:
    """One endpoint's latency objective plus its sliding good/bad buckets."""

    __slots__ = ("name", "threshold_us", "target", "breaker", "buckets",
                 "alert_active", "_good", "_bad", "_burn_fast", "_burn_slow",
                 "_active_g")

    def __init__(self, name: str, threshold_us: float, target: float,
                 breaker=None):
        if not 0.0 < target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1), got {target}")
        self.name = name
        self.threshold_us = float(threshold_us)
        self.target = float(target)
        self.breaker = breaker
        # (second, good, bad) per-second aggregation, oldest first
        self.buckets: deque = deque()
        self.alert_active = False
        self._good = _GOOD.labels(name)
        self._bad = _BAD.labels(name)
        self._burn_fast = _BURN.labels(name, "fast")
        self._burn_slow = _BURN.labels(name, "slow")
        self._active_g = _ALERT_ACTIVE.labels(name)
        self._active_g.set(0)

    def window_totals(self, window_s: float, now: float):
        """(good, bad) over the trailing ``window_s`` seconds."""
        lo = now - window_s
        good = bad = 0
        for sec, g, b in reversed(self.buckets):
            if sec < lo:
                break
            good += g
            bad += b
        return good, bad


class SLOMonitor:
    """Process-wide burn-rate monitor. Windows/threshold/escalation re-read
    their knobs on every check unless pinned at construction, so tests and
    live operators can retune without a restart."""

    def __init__(self, target: Optional[float] = None,
                 fast_window_s: Optional[float] = None,
                 slow_window_s: Optional[float] = None,
                 burn_threshold: Optional[float] = None,
                 min_events: Optional[int] = None,
                 escalate: Optional[bool] = None,
                 time_fn=time.monotonic):
        self._target = target
        self._fast = fast_window_s
        self._slow = slow_window_s
        self._threshold = burn_threshold
        self._min_events = min_events
        self._escalate = escalate
        self._now = time_fn
        self._lock = threading.Lock()
        self._objectives: Dict[str, Objective] = {}

    # -- knob-backed settings ----------------------------------------------
    @property
    def fast_window_s(self) -> float:
        return self._fast if self._fast is not None else \
            float(_cfg("MXNET_SLO_FAST_WINDOW_S", 300.0))

    @property
    def slow_window_s(self) -> float:
        return self._slow if self._slow is not None else \
            float(_cfg("MXNET_SLO_SLOW_WINDOW_S", 3600.0))

    @property
    def burn_threshold(self) -> float:
        return self._threshold if self._threshold is not None else \
            float(_cfg("MXNET_SLO_BURN_THRESHOLD", 10.0))

    @property
    def min_events(self) -> int:
        return self._min_events if self._min_events is not None else \
            int(_cfg("MXNET_SLO_MIN_EVENTS", 10))

    @property
    def escalate(self) -> bool:
        return self._escalate if self._escalate is not None else \
            bool(_cfg("MXNET_SLO_ESCALATE", False))

    # -- registration -------------------------------------------------------
    def register(self, name: str, threshold_us: float,
                 target: Optional[float] = None, breaker=None) -> Objective:
        """Register (or replace) an endpoint's objective. ``target`` falls
        back to MXNET_SLO_TARGET."""
        if target is None:
            target = self._target if self._target is not None else \
                float(_cfg("MXNET_SLO_TARGET", 0.999))
        obj = Objective(name, threshold_us, target, breaker=breaker)
        with self._lock:
            self._objectives[name] = obj
        return obj

    def unregister(self, name: str):
        with self._lock:
            self._objectives.pop(name, None)

    def get(self, name: str) -> Optional[Objective]:
        with self._lock:
            return self._objectives.get(name)

    def objectives(self) -> List[Objective]:
        with self._lock:
            return list(self._objectives.values())

    # -- recording ----------------------------------------------------------
    def record(self, name: str, latency_us: float, ok: bool = True):
        """Classify one completed request; no-op for endpoints without an
        objective. Also runs the burn check for this objective."""
        obj = self.get(name)
        if obj is None:
            return
        good = bool(ok) and latency_us <= obj.threshold_us
        now = self._now()
        sec = int(now)
        with self._lock:
            if obj.buckets and obj.buckets[-1][0] == sec:
                s, g, b = obj.buckets[-1]
                obj.buckets[-1] = (s, g + good, b + (not good))
            else:
                obj.buckets.append((sec, int(good), int(not good)))
                lo = now - self.slow_window_s - 1
                while obj.buckets and obj.buckets[0][0] < lo:
                    obj.buckets.popleft()
        (obj._good if good else obj._bad).inc()
        self.check(obj, now)

    # -- burn check / alerting ----------------------------------------------
    @staticmethod
    def _burn(good: int, bad: int, target: float) -> float:
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / (1.0 - target)

    def check(self, obj: Objective, now: Optional[float] = None) -> dict:
        """Recompute both windows' burn rates, update gauges, and fire /
        clear the latched alert. Returns the computed state (for tests and
        /statusz)."""
        if now is None:
            now = self._now()
        with self._lock:
            fg, fb = obj.window_totals(self.fast_window_s, now)
            sg, sb = obj.window_totals(self.slow_window_s, now)
        fast = self._burn(fg, fb, obj.target)
        slow = self._burn(sg, sb, obj.target)
        obj._burn_fast.set(fast)
        obj._burn_slow.set(slow)
        thr = self.burn_threshold
        breaching = (fg + fb >= self.min_events and fast >= thr
                     and slow >= thr)
        if breaching and not obj.alert_active:
            obj.alert_active = True
            obj._active_g.set(1)
            _ALERTS.labels(obj.name).inc()
            escalated = False
            if self.escalate and obj.breaker is not None:
                try:
                    obj.breaker.force_degraded(
                        f"slo burn {fast:.1f}x fast / {slow:.1f}x slow "
                        f"(threshold {thr:g}x)")
                    escalated = True
                    _ESCALATIONS.labels(obj.name).inc()
                except Exception:
                    pass
            _flight.event("slo_burn_alert", endpoint=obj.name,
                          fast_burn=round(fast, 3), slow_burn=round(slow, 3),
                          threshold=thr, target=obj.target,
                          escalated=escalated)
        elif obj.alert_active and fast < thr:
            obj.alert_active = False
            obj._active_g.set(0)
            _flight.event("slo_burn_clear", endpoint=obj.name,
                          fast_burn=round(fast, 3))
        return {"endpoint": obj.name, "fast_burn": fast, "slow_burn": slow,
                "alert_active": obj.alert_active,
                "fast_events": fg + fb, "slow_events": sg + sb}

    def check_all(self) -> List[dict]:
        return [self.check(obj) for obj in self.objectives()]

    def snapshot(self) -> List[dict]:
        """Objective states for /statusz."""
        out = []
        for obj in self.objectives():
            st = self.check(obj)
            st.update(threshold_us=obj.threshold_us, target=obj.target)
            out.append(st)
        return out

    def _reset_for_tests(self):
        with self._lock:
            self._objectives.clear()


# the process-wide monitor InferenceServer.register() feeds
MONITOR = SLOMonitor()
