"""HBM attribution: who owns the bytes ``device.memory_stats()`` reports.

The raw per-device gauge (``mxtpu_device_memory_bytes``) says *how much* HBM
is in use; this module says *whose* it is. Subsystems register a
:class:`Holder` for every pool of device memory they pin — endpoint
parameters and per-bucket executables, the ParallelTrainStep's donated
train state, NumericsGuard snapshots, prepared pipeline batches — either
with a static byte count or with a ``sizer`` callback evaluated at
reconcile time (holders keep only a weakref to their owner, so a dead
endpoint drops off the table instead of pinning itself).

``reconcile()`` folds the holder table against ``device.memory_stats()``:
per-device attributed bytes, the unattributed residual (allocator slack,
XLA scratch, anything nobody registered), and live/peak gauges. The ranked
``breakdown()`` is what an OOM post-mortem needs — RESOURCE_EXHAUSTED
classified by RetryPolicy fires an ``oom`` flight trigger whose bundle
carries this table, and the ``/memz`` debug page serves it live.

CPU backends return ``None`` from ``memory_stats()``; reconciliation then
reports holders only (tests inject synthetic device stats).
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

from .metrics import REGISTRY

__all__ = ["Holder", "register", "nbytes_of", "holders", "reconcile",
           "breakdown", "reset"]

_HOLDER_BYTES = REGISTRY.gauge(
    "mxtpu_mem_holder_bytes",
    "Live device bytes attributed to one registered holder "
    "(endpoint params, bucket executables, train state, numerics "
    "snapshots, prepared batches).",
    labelnames=("subsystem", "holder"))
_HOLDER_PEAK = REGISTRY.gauge(
    "mxtpu_mem_holder_peak_bytes",
    "High-water mark of one holder's attributed bytes.",
    labelnames=("subsystem", "holder"))
_ATTRIBUTED = REGISTRY.gauge(
    "mxtpu_mem_attributed_bytes",
    "Sum of holder bytes per device label at the last reconcile.",
    labelnames=("device",))
_UNATTRIBUTED = REGISTRY.gauge(
    "mxtpu_mem_unattributed_bytes",
    "device.memory_stats() bytes_in_use minus attributed bytes (allocator "
    "slack, XLA scratch, unregistered pins); persistent growth here is a "
    "leak nobody owns.",
    labelnames=("device",))

_LOCK = threading.Lock()
_HOLDERS: Dict[tuple, "Holder"] = {}


def _cfg(name, default):
    # narrow: only the circular-import window during interpreter startup
    # (config not importable yet) falls back to the built-in default
    try:
        from .. import config
    except ImportError:
        return default
    return config.get(name, default)


def _enabled() -> bool:
    return bool(_cfg("MXNET_MEM_TRACK", True))


def nbytes_of(tree) -> int:
    """Total device bytes of every array leaf in ``tree`` (anything with an
    ``nbytes``; NDArrays unwrap to their jax data). Never raises."""
    total = 0
    stack = [tree]
    while stack:
        x = stack.pop()
        if x is None:
            continue
        if isinstance(x, dict):
            stack.extend(x.values())
            continue
        if isinstance(x, (list, tuple)):
            stack.extend(x)
            continue
        data = getattr(x, "data", None)
        if data is not None and hasattr(data, "nbytes") \
                and not hasattr(x, "nbytes"):
            x = data
        try:
            nb = x.nbytes
        except Exception:
            continue
        if isinstance(nb, (int, float)):
            total += int(nb)
    return total


class Holder:
    """One registered pool of pinned device memory."""

    __slots__ = ("subsystem", "name", "device", "_nbytes", "peak", "ts",
                 "_owner", "_sizer", "_released")

    def __init__(self, subsystem: str, name: str, nbytes: int = 0,
                 device: str = "", owner: Any = None,
                 sizer: Optional[Callable[[Any], int]] = None):
        self.subsystem = str(subsystem)
        self.name = str(name)
        self.device = str(device)
        self._nbytes = int(nbytes)
        self.peak = int(nbytes)
        self.ts = time.time()
        self._owner = weakref.ref(owner) if owner is not None else None
        self._sizer = sizer
        self._released = False

    def current(self) -> Optional[int]:
        """Live byte count; None when the owner died (prune me)."""
        if self._released:
            return None
        if self._sizer is not None:
            owner = None
            if self._owner is not None:
                owner = self._owner()
                if owner is None:
                    return None
            try:
                self._nbytes = int(self._sizer(owner) if self._owner
                                   is not None else self._sizer(None))
            except Exception:
                pass          # keep the last good figure
        elif self._owner is not None and self._owner() is None:
            return None
        self.peak = max(self.peak, self._nbytes)
        return self._nbytes

    def update(self, nbytes: int):
        """Set a static holder's byte count (and bump its peak/gauges)."""
        self._nbytes = int(nbytes)
        self.peak = max(self.peak, self._nbytes)
        self.ts = time.time()
        try:
            _HOLDER_BYTES.labels(self.subsystem, self.name).set(self._nbytes)
            _HOLDER_PEAK.labels(self.subsystem, self.name).set(self.peak)
        except Exception:
            pass

    def release(self):
        """Drop the holder (freed its memory); the gauge child zeros."""
        self._released = True
        with _LOCK:
            _HOLDERS.pop((self.subsystem, self.name), None)
        try:
            _HOLDER_BYTES.labels(self.subsystem, self.name).set(0)
        except Exception:
            pass


class _NullHolder(Holder):
    """Returned when MXNET_MEM_TRACK=0: accepts the API, records nothing."""

    def __init__(self):
        super().__init__("disabled", "disabled")

    def current(self):
        return None

    def update(self, nbytes: int):
        pass

    def release(self):
        pass


def register(subsystem: str, name: str, nbytes: int = 0, device: str = "",
             owner: Any = None,
             sizer: Optional[Callable[[Any], int]] = None) -> Holder:
    """Register (or replace) the holder ``(subsystem, name)``.

    ``sizer(owner)`` makes the holder live: evaluated at every reconcile so
    the table tracks state that changes shape (donated train state, growing
    executable caches) without per-step bookkeeping. ``owner`` is held
    weakly; once it is collected the holder prunes itself.
    """
    if not _enabled():
        return _NullHolder()
    h = Holder(subsystem, name, nbytes=nbytes, device=device, owner=owner,
               sizer=sizer)
    with _LOCK:
        _HOLDERS[(h.subsystem, h.name)] = h
    if sizer is None:
        h.update(nbytes)
    return h


def holders() -> List[Dict]:
    """The live holder table, largest first; dead holders are pruned."""
    with _LOCK:
        items = list(_HOLDERS.values())
    rows = []
    for h in items:
        nb = h.current()
        if nb is None:
            with _LOCK:
                _HOLDERS.pop((h.subsystem, h.name), None)
            try:
                _HOLDER_BYTES.labels(h.subsystem, h.name).set(0)
            except Exception:
                pass
            continue
        try:
            _HOLDER_BYTES.labels(h.subsystem, h.name).set(nb)
            _HOLDER_PEAK.labels(h.subsystem, h.name).set(h.peak)
        except Exception:
            pass
        rows.append({"subsystem": h.subsystem, "holder": h.name,
                     "device": h.device, "bytes": nb, "peak_bytes": h.peak})
    rows.sort(key=lambda r: r["bytes"], reverse=True)
    return rows


def _device_stats() -> Dict[str, Dict[str, int]]:
    """{'cpu:0': {'bytes_in_use': ..., 'peak_bytes_in_use': ...}, ...} from
    PJRT; empty on backends that don't report (CPU)."""
    out: Dict[str, Dict[str, int]] = {}
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return out
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[f"{d.platform}:{d.id}"] = dict(stats)
    return out


def reconcile(device_stats: Optional[Dict[str, Dict[str, int]]] = None
              ) -> Dict[str, Dict[str, int]]:
    """Fold the holder table against per-device memory stats.

    Returns ``{device: {bytes_in_use, peak_bytes_in_use, attributed,
    unattributed}}``. Holders whose ``device`` label matches a reported
    device attribute there; holders with no/unknown device labels attribute
    to every reported device is wrong — they land under the pseudo-device
    ``"unassigned"`` instead, so the residual stays honest. ``device_stats``
    is injectable for tests (CPU reports nothing).
    """
    rows = holders()
    stats = _device_stats() if device_stats is None else dict(device_stats)
    attributed: Dict[str, int] = {}
    for r in rows:
        dev = r["device"] if r["device"] in stats else "unassigned"
        attributed[dev] = attributed.get(dev, 0) + r["bytes"]
    out: Dict[str, Dict[str, int]] = {}
    for dev, st in stats.items():
        in_use = int(st.get("bytes_in_use", 0))
        attr = attributed.get(dev, 0)
        out[dev] = {
            "bytes_in_use": in_use,
            "peak_bytes_in_use": int(st.get("peak_bytes_in_use", 0)),
            "attributed": attr,
            "unattributed": in_use - attr,
        }
        try:
            _ATTRIBUTED.labels(dev).set(attr)
            _UNATTRIBUTED.labels(dev).set(in_use - attr)
        except Exception:
            pass
    if "unassigned" in attributed and "unassigned" not in out:
        out["unassigned"] = {"bytes_in_use": 0, "peak_bytes_in_use": 0,
                             "attributed": attributed["unassigned"],
                             "unattributed": -attributed["unassigned"]}
        try:
            _ATTRIBUTED.labels("unassigned").set(attributed["unassigned"])
        except Exception:
            pass
    return out


def breakdown(limit: Optional[int] = None,
              device_stats: Optional[Dict[str, Dict[str, int]]] = None
              ) -> Dict:
    """The OOM post-mortem payload: ranked holder table + per-device
    reconciliation + totals, one JSON-able dict."""
    if limit is None:
        limit = int(_cfg("MXNET_MEM_HOLDERS_KEEP", 32))
    rows = holders()
    shown = rows[:max(0, limit)]
    return {
        "ts": time.time(),
        "holders": shown,
        "holders_total": len(rows),
        "holders_omitted_bytes": sum(r["bytes"] for r in rows[limit:]),
        "attributed_bytes": sum(r["bytes"] for r in rows),
        "devices": reconcile(device_stats),
    }


def reset():
    """Drop every holder (tests)."""
    with _LOCK:
        for h in list(_HOLDERS.values()):
            try:
                _HOLDER_BYTES.labels(h.subsystem, h.name).set(0)
            except Exception:
                pass
        _HOLDERS.clear()
