"""Structured span tracing with cross-layer trace-id propagation.

``telemetry.span(name, **attrs)`` opens a nested, timed span:

  - spans nest via a contextvar; a child inherits its parent's ``trace_id``
    so one logical operation (a serving request, a training step) is one
    trace across layers, even when the layers are different subsystems.
  - a span can be *adopted* across threads by passing an explicit
    ``trace_id=...`` — the serving path stamps each admitted request with
    the submitter's trace id, and the worker thread re-opens the trace
    around batch assembly and the compiled device step, so a request's
    trace id survives the queue hop.
  - on exit every span feeds BOTH sinks: the profiler's chrome-trace event
    stream (when a profiler session is running — the span lands in the same
    ``traceEvents`` timeline as per-op events, with the trace id in
    ``args`` so XPlane/Perfetto rows correlate with fleet metrics), and the
    registry's ``mxtpu_span_duration_us{name=...}`` histogram (always on —
    spans are the latency series dashboards scrape).

Span names are dot-scoped ``layer.operation`` (``serving.batch``,
``train.step``, ``dataloader.wait`` — see OBSERVABILITY.md for the
convention); attrs are small JSON-able values, never tensors.
"""
from __future__ import annotations

import contextvars
import random
import sys
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from .metrics import REGISTRY
from .flight import RECORDER as _FLIGHT_RECORDER

__all__ = ["Span", "span", "current_span", "current_trace_id", "new_trace_id"]

# pre-bound deque.append: the flight span ring rides every span exit, so the
# hot path pays one bounded-deque append (GIL-atomic) and nothing else
_record_flight_span = _FLIGHT_RECORDER._spans.append

_CURRENT: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "mxtpu_current_span", default=None)

# per-process random source; seeded from urandom, independent of user PRNGs
_RNG = random.Random()
_SPAN_DURATION = REGISTRY.histogram(
    "mxtpu_span_duration_us",
    "Duration of telemetry spans by span name (microseconds).",
    labelnames=("name",))


def new_trace_id() -> str:
    return f"{_RNG.getrandbits(64):016x}"


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class Span:
    """One timed region. Created by :func:`span`; read-only for users."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "t0_us", "dur_us")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 attrs: Dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = f"{_RNG.getrandbits(64):016x}"
        self.parent_id = parent_id
        self.attrs = attrs
        self.t0_us = _now_us()
        self.dur_us = None

    def __repr__(self):
        return (f"<Span {self.name} trace={self.trace_id} "
                f"dur={self.dur_us}us attrs={self.attrs}>")


@contextmanager
def span(name: str, trace_id: Optional[str] = None, **attrs):
    """Open a nested span. ``trace_id`` adopts an existing trace (cross-thread
    propagation); otherwise the parent's trace is inherited, or a fresh trace
    is started at the root. Yields the Span (``.trace_id`` is the handle to
    stamp onto queue items / requests for later adoption)."""
    parent = _CURRENT.get()
    if trace_id is None:
        trace_id = parent.trace_id if parent is not None else new_trace_id()
    s = Span(name, trace_id, parent.span_id if parent is not None else None,
             attrs)
    token = _CURRENT.set(s)
    try:
        yield s
    finally:
        _CURRENT.reset(token)
        s.dur_us = _now_us() - s.t0_us
        _SPAN_DURATION.labels(name).observe(s.dur_us)
        _record_flight_span(s)
        _emit_profiler(s)


def current_span() -> Optional[Span]:
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    s = _CURRENT.get()
    return s.trace_id if s is not None else None


def _emit_profiler(s: Span):
    """Mirror a finished span into the profiler's chrome trace (only when a
    session is running; module looked up lazily so telemetry never forces the
    profiler onto the import path of lightweight processes)."""
    prof = sys.modules.get("mxnet_tpu.profiler")
    if prof is None or not prof._STATE["running"]:
        return
    args = {"trace_id": s.trace_id, "span_id": s.span_id}
    if s.parent_id:
        args["parent_id"] = s.parent_id
    for k, v in s.attrs.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            args[k] = v
    prof._record(s.name, "span", s.t0_us, s.dur_us, args=args)
