"""Structured span tracing with cross-layer trace-id propagation.

``telemetry.span(name, **attrs)`` opens a nested, timed span:

  - spans nest via a contextvar; a child inherits its parent's ``trace_id``
    so one logical operation (a serving request, a training step) is one
    trace across layers, even when the layers are different subsystems.
  - a span can be *adopted* across threads by passing an explicit
    ``trace_id=...`` — the serving path stamps each admitted request with
    the submitter's trace id, and the worker thread re-opens the trace
    around batch assembly and the compiled device step, so a request's
    trace id survives the queue hop.
  - on exit every span feeds BOTH sinks: the profiler's chrome-trace event
    stream (when a profiler session is running — the span lands in the same
    ``traceEvents`` timeline as per-op events, with the trace id in
    ``args`` so XPlane/Perfetto rows correlate with fleet metrics), and the
    registry's ``mxtpu_span_duration_us{name=...}`` histogram (always on —
    spans are the latency series dashboards scrape).

Span names are dot-scoped ``layer.operation`` (``serving.batch``,
``train.step``, ``dataloader.wait`` — see OBSERVABILITY.md for the
convention); attrs are small JSON-able values, never tensors.

Cross-process journeys (the fleet plane): every finished span also lands in
a bounded in-memory spool buffer; when ``MXNET_SPAN_SPOOL_DIR`` is set the
buffer drains — every ``MXNET_SPAN_SPOOL_FLUSH_N`` spans, and at interpreter
exit — into an append-only per-pid JSONL file (``spool-<pid>.jsonl``, the
compile-ledger file pattern: one ``O_APPEND`` write per batch, size-capped
and rotated). Each line carries the pid and a wall-clock anchor, so
``tools/trace_journey.py`` can assemble one ordered timeline for a trace id
across every process that touched it. A child process inherits its parent's
trace via the ``MXNET_TRACE_ID`` env knob: the first *root* span of the
process adopts it instead of minting a fresh id.
"""
from __future__ import annotations

import atexit
import contextvars
import json
import os
import random
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

from .metrics import REGISTRY
from .flight import RECORDER as _FLIGHT_RECORDER, _clean_attrs

__all__ = ["Span", "span", "current_span", "current_trace_id",
           "new_trace_id", "spool_flush", "spool_path", "read_spool",
           "journey"]

# pre-bound deque.append: the flight span ring rides every span exit, so the
# hot path pays one bounded-deque append (GIL-atomic) and nothing else
_record_flight_span = _FLIGHT_RECORDER._spans.append

_CURRENT: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "mxtpu_current_span", default=None)

# per-process random source; seeded from urandom, independent of user PRNGs
_RNG = random.Random()
_SPAN_DURATION = REGISTRY.histogram(
    "mxtpu_span_duration_us",
    "Duration of telemetry spans by span name (microseconds).",
    labelnames=("name",))


def new_trace_id() -> str:
    return f"{_RNG.getrandbits(64):016x}"


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


def _cfg(name, default):
    """Knob read tolerating the partially initialized package (tracing can be
    imported before ``mxnet_tpu.config`` is bound during package init)."""
    try:
        from .. import config
        return config.get(name, default)
    except Exception:
        return default


# -- cross-process trace inheritance ------------------------------------------
# Resolved once per process: MXNET_TRACE_ID is the parent's trace id handed
# to a child at spawn (env), so the child's first root span joins the
# parent's journey instead of minting a fresh id.
_INHERITED_TRACE: Optional[str] = None
_INHERITED_RESOLVED = False


def _inherited_trace_id() -> Optional[str]:
    global _INHERITED_TRACE, _INHERITED_RESOLVED
    if not _INHERITED_RESOLVED:
        _INHERITED_TRACE = str(_cfg("MXNET_TRACE_ID", "") or "") or None
        _INHERITED_RESOLVED = True
    return _INHERITED_TRACE


class Span:
    """One timed region. Created by :func:`span`; read-only for users."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "t0_us", "dur_us")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 attrs: Dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = f"{_RNG.getrandbits(64):016x}"
        self.parent_id = parent_id
        self.attrs = attrs
        self.t0_us = _now_us()
        self.dur_us = None

    def __repr__(self):
        return (f"<Span {self.name} trace={self.trace_id} "
                f"dur={self.dur_us}us attrs={self.attrs}>")


@contextmanager
def span(name: str, trace_id: Optional[str] = None, **attrs):
    """Open a nested span. ``trace_id`` adopts an existing trace (cross-thread
    propagation); otherwise the parent's trace is inherited, or a fresh trace
    is started at the root. Yields the Span (``.trace_id`` is the handle to
    stamp onto queue items / requests for later adoption)."""
    parent = _CURRENT.get()
    if trace_id is None:
        if parent is not None:
            trace_id = parent.trace_id
        else:
            trace_id = _inherited_trace_id() or new_trace_id()
    s = Span(name, trace_id, parent.span_id if parent is not None else None,
             attrs)
    token = _CURRENT.set(s)
    try:
        yield s
    finally:
        _CURRENT.reset(token)
        s.dur_us = _now_us() - s.t0_us
        _SPAN_DURATION.labels(name).observe(s.dur_us)
        _record_flight_span(s)
        _record_spool_span(s)
        if len(_SPOOL_BUF) >= _SPOOL_FLUSH_N:
            spool_flush()
        _emit_profiler(s)


def current_span() -> Optional[Span]:
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    s = _CURRENT.get()
    return s.trace_id if s is not None else None


# -- per-pid span spool (the fleet plane's raw material) ----------------------
#
# Hot-path discipline mirrors the flight ring: every span exit pays one
# bounded-deque append; file I/O happens only on a flush (every
# MXNET_SPAN_SPOOL_FLUSH_N spans, or at exit), and only when a spool
# directory is configured. With no directory the flush is a buffer clear.

_SPOOL_BUF: deque = deque(maxlen=2048)  # bounded: backlog drops oldest
_record_spool_span = _SPOOL_BUF.append
_SPOOL_LOCK = threading.Lock()
_SPOOL_FLUSH_N = 32          # refreshed from its knob at every flush
# perf_counter -> wall-clock anchor: spans are timed on the monotonic clock
# (in-proc ordering), but cross-process assembly needs wall time
_WALL_ANCHOR_S = time.time() - time.perf_counter()

_SPOOL_SPANS = REGISTRY.counter(
    "mxtpu_span_spool_spans_total",
    "Spans spilled to the per-pid spool file under MXNET_SPAN_SPOOL_DIR.")
_SPOOL_ROTATIONS = REGISTRY.counter(
    "mxtpu_span_spool_rotations_total",
    "Spool-file rotations forced by the MXNET_SPAN_SPOOL_MAX_BYTES size cap.")


def spool_path(d: Optional[str] = None) -> str:
    """This process's spool file ('' when no spool directory is set)."""
    d = d if d is not None else str(_cfg("MXNET_SPAN_SPOOL_DIR", "") or "")
    return os.path.join(d, f"spool-{os.getpid()}.jsonl") if d else ""


def _spool_line(s: Span) -> Dict:
    return {
        "pid": os.getpid(),
        "name": s.name,
        "trace_id": s.trace_id,
        "span_id": s.span_id,
        "parent_id": s.parent_id,
        "t0_wall": round(_WALL_ANCHOR_S + s.t0_us / 1e6, 6),
        "dur_us": s.dur_us,
        "attrs": _clean_attrs(s.attrs) if s.attrs else {},
    }


def spool_flush():
    """Drain the buffered spans into ``spool-<pid>.jsonl`` (one ``O_APPEND``
    write for the whole batch; atomic line appends even with several
    processes sharing the directory). Rotates the file to ``.1`` when it
    would exceed ``MXNET_SPAN_SPOOL_MAX_BYTES``. Never raises — a broken
    disk must not take down the span it is trying to record."""
    global _SPOOL_FLUSH_N
    try:
        _SPOOL_FLUSH_N = max(1, int(_cfg("MXNET_SPAN_SPOOL_FLUSH_N", 32)))
    except Exception:
        pass
    with _SPOOL_LOCK:
        if not _SPOOL_BUF:
            return
        batch = list(_SPOOL_BUF)
        _SPOOL_BUF.clear()
        path = spool_path()
        if not path:
            return
        try:
            lines = [json.dumps(_spool_line(s), sort_keys=True) + "\n"
                     for s in batch if s.dur_us is not None]
            if not lines:
                return
            data = "".join(lines).encode("utf-8")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            cap = int(_cfg("MXNET_SPAN_SPOOL_MAX_BYTES", 8 << 20))
            try:
                if cap > 0 and os.path.getsize(path) + len(data) > cap:
                    os.replace(path, path + ".1")
                    _SPOOL_ROTATIONS.inc()
            except OSError:
                pass
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, data)
            finally:
                os.close(fd)
            _SPOOL_SPANS.inc(len(lines))
        except Exception:
            pass


# short-lived children (loadgen restart phases, chaos subprocesses) must
# spill their tail before exiting, or the journey loses its last hop
atexit.register(spool_flush)


def read_spool(d: Optional[str] = None) -> List[Dict]:
    """Every span line in the spool directory — all processes, rotated
    ``.1`` files included — as dicts (file order within a file)."""
    d = d if d is not None else str(_cfg("MXNET_SPAN_SPOOL_DIR", "") or "")
    out: List[Dict] = []
    if not d or not os.path.isdir(d):
        return out
    for n in sorted(os.listdir(d)):
        if not (n.startswith("spool-") and
                (n.endswith(".jsonl") or n.endswith(".jsonl.1"))):
            continue
        try:
            with open(os.path.join(d, n)) as f:
                for line in f:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
    return out


def journey(trace_id: str, d: Optional[str] = None) -> List[Dict]:
    """One ordered cross-process timeline for ``trace_id``: every spooled
    span carrying that id, across every process's spool file, sorted by
    wall-clock start. The raw material of ``tools/trace_journey.py``."""
    hops = [e for e in read_spool(d) if e.get("trace_id") == trace_id]
    hops.sort(key=lambda e: (e.get("t0_wall", 0.0), e.get("dur_us") or 0))
    return hops


def _reset_spool_for_tests():
    """Forget buffered spans and the cached inherited trace id (tests that
    flip MXNET_TRACE_ID / spool knobs mid-process)."""
    global _INHERITED_RESOLVED, _INHERITED_TRACE
    with _SPOOL_LOCK:
        _SPOOL_BUF.clear()
    _INHERITED_RESOLVED = False
    _INHERITED_TRACE = None


def _emit_profiler(s: Span):
    """Mirror a finished span into the profiler's chrome trace (only when a
    session is running; module looked up lazily so telemetry never forces the
    profiler onto the import path of lightweight processes)."""
    prof = sys.modules.get("mxnet_tpu.profiler")
    if prof is None or not prof._STATE["running"]:
        return
    args = {"trace_id": s.trace_id, "span_id": s.span_id}
    if s.parent_id:
        args["parent_id"] = s.parent_id
    for k, v in s.attrs.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            args[k] = v
    prof._record(s.name, "span", s.t0_us, s.dur_us, args=args)
