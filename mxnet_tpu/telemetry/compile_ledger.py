"""Compile ledger — content-addressed observability for every XLA compile.

Every AOT compile in this stack (serving bucket executables, the
ParallelTrainStep autoformat path, the eager jit cache) emits one
:class:`CompileRecord`: a sha256 fingerprint of the lowered StableHLO text
(the content address ROADMAP item 2's persistent executable cache will key
on), lowering + compile wall time, the backend's ``cost_analysis()`` flops /
bytes and ``memory_analysis()`` argument/output/temp/code bytes where
available, and the trigger key (endpoint/bucket/mesh/dtype/op) that explains
*why* the compile happened.

Records land in three places:

  - a bounded in-memory ring (``recent()``) — the flight recorder snapshots
    it into every bundle, and the ``/compilez`` debug page renders it live;
  - the shared metrics registry (``mxtpu_compile_*`` families);
  - when ``MXNET_COMPILE_LEDGER_DIR`` is set, an append-only JSONL file per
    process (single ``O_APPEND`` write per record: atomic line appends even
    with several processes sharing the directory).

Duplicate detection is the point: a fingerprint seen before — in this
process, or by any process that wrote into the ledger directory — means the
wall time of the new compile was *re-spent* on a program the fleet already
owned. That waste is quantified in
``mxtpu_compile_duplicate_waste_seconds_total`` and is exactly the win a
persistent executable cache would bank.

Fingerprints are canonicalized (MLIR location metadata stripped) so the same
function lowered at the same avals in two different processes hashes
identically — the property the cross-subprocess stability test pins. The
canonicalizer itself lives in :mod:`mxnet_tpu.analysis.ir.parser` now
(shared with hlolint, hardened for nested ``loc(...)`` and string attrs);
this module delegates.

Two growths ride the same seam (hlolint, see STATIC_ANALYSIS.md):

  - when a ledger directory is set, the canonicalized module *text* is
    retained beside the records as ``module-<fingerprint>.mlir`` (deduped
    by content address, byte-bounded by
    MXNET_COMPILE_LEDGER_TEXT_MAX_BYTES, atomic tmp+rename writes) so
    ``mxlint --ir`` and autotune feature extraction run offline against
    the very programs the fleet compiled;
  - an opt-in live guard (MXNET_IR_GUARD=warn|raise) checks each compile
    against the guarded IR rules — donation silently dropped by XLA
    (IR1000), weights baked in as constants (IR1001) — emitting
    ``mxtpu_ir_guard_total`` and an ``ir_guard`` flight event. Fail-open:
    guard *infrastructure* errors never fail the compile; only an actual
    finding under ``raise`` does.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
import warnings
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .metrics import REGISTRY
from ..analysis.ir.guard import IRGuardError, live_findings as _ir_findings
from ..analysis.ir import parser as _irparser

__all__ = ["CompileRecord", "IRGuardError", "fingerprint_text",
           "op_histogram", "lower_and_compile", "record", "recent",
           "summary", "instrument_eager_jit", "eager_active", "ledger_dir",
           "read_ledger", "reset"]

_RECORDS = REGISTRY.counter(
    "mxtpu_compile_records_total",
    "CompileRecords emitted, by compile site (serving_bucket / train_step / "
    "eager_jit).",
    labelnames=("site",))
_WALL = REGISTRY.counter(
    "mxtpu_compile_wall_seconds_total",
    "Wall seconds spent in XLA lowering/compilation, by site and phase "
    "(lower / compile).",
    labelnames=("site", "phase"))
_DUPS = REGISTRY.counter(
    "mxtpu_compile_duplicates_total",
    "Compiles whose StableHLO fingerprint was already in the ledger — a "
    "program the fleet had already paid to compile.",
    labelnames=("site",))
_DUP_WASTE = REGISTRY.counter(
    "mxtpu_compile_duplicate_waste_seconds_total",
    "Wall seconds re-spent compiling already-seen programs (the win a "
    "persistent executable cache keyed by StableHLO hash would bank).")
_IR_GUARD = REGISTRY.counter(
    "mxtpu_ir_guard_total",
    "Live IR-guard verdicts per compile, by rule (IR1000 donation-dropped, "
    "IR1001 baked-in-weights) and outcome (detected = guard off but the "
    "violation was seen / warn / raise).",
    labelnames=("rule", "outcome"))
_TEXT_RETAINED = REGISTRY.counter(
    "mxtpu_compile_text_retained_total",
    "Canonicalized StableHLO texts retained beside the ledger, by outcome "
    "(written / dedup = content address already on disk / over_budget = "
    "MXNET_COMPILE_LEDGER_TEXT_MAX_BYTES reached / error).",
    labelnames=("outcome",))

# ring larger than any MXNET_COMPILE_LEDGER_KEEP a page would ask for
_RING_CAP = 512

_LOCK = threading.Lock()
_RING: deque = deque(maxlen=_RING_CAP)
_SEEN: Dict[str, float] = {}        # fingerprint -> first-seen compile secs
_SCANNED: Dict[str, int] = {}       # ledger file path -> bytes consumed
_SCANNED_DIR: Optional[str] = None  # ledger dir the offsets belong to
_OP_RE = re.compile(r"\b(?:stablehlo|mhlo|chlo)\.([a-z0-9_]+)\b")
_LAST_ERRORS: Dict[str, str] = {}   # where -> last swallowed error


def _note(where: str, exc: BaseException):
    """Instrumentation must never fail the compile it observes — errors are
    swallowed, but the last one per site stays inspectable here (an empty
    ledger with a populated _LAST_ERRORS is a bug report)."""
    _LAST_ERRORS[where] = f"{type(exc).__name__}: {exc}"


def _cfg(name, default):
    try:
        from .. import config
        return config.get(name, default)
    except Exception as e:
        _note("cfg", e)
        return default


def ledger_dir() -> str:
    """The JSONL ledger directory ('' = in-memory only), read live."""
    return str(_cfg("MXNET_COMPILE_LEDGER_DIR", "") or "")


def eager_active() -> bool:
    """Whether the eager jit cache should emit ledger records. 'auto' (the
    default) follows the ledger directory: instrumenting the eager path AOT
    compiles per aval signature, which is only worth doing when someone is
    collecting the records."""
    mode = str(_cfg("MXNET_COMPILE_LEDGER_EAGER", "auto")).lower()
    if mode in ("1", "true", "yes", "on"):
        return True
    if mode in ("0", "false", "no", "off"):
        return False
    return bool(ledger_dir())


class CompileRecord(dict):
    """One compile, as a plain JSON-able dict (subclass only for the name)."""
    __slots__ = ()


def fingerprint_text(text: str) -> str:
    """sha256 of canonicalized StableHLO text. MLIR location metadata
    (``loc(...)`` / ``#loc`` lines) is stripped so the hash depends on the
    program alone, not on where in the host source it was traced from —
    two processes lowering the same function at the same avals agree.
    Delegates to the shared hardened canonicalizer (balanced parens,
    string-attr aware — see :mod:`mxnet_tpu.analysis.ir.parser`); for
    location-free text the result is byte-identical to the original
    regex pass, so existing content addresses stay valid."""
    return _irparser.fingerprint(text)


def op_histogram(text: str, cap: int = 64) -> Dict[str, int]:
    """Opcode histogram of a StableHLO module text: ``{op_name: count}``
    over the ``stablehlo.*`` / ``mhlo.*`` mnemonics. This is the paper's
    program featurization (op counts over the canonicalized program), and
    it is captured at compile time because the ledger stores only the
    sha256 *fingerprint* of the text — the histogram cannot be recovered
    later. Capped to the ``cap`` most frequent ops to bound record size."""
    hist: Dict[str, int] = {}
    for m in _OP_RE.finditer(text):
        op = m.group(1)
        hist[op] = hist.get(op, 0) + 1
    if len(hist) > cap:
        keep = sorted(hist.items(), key=lambda kv: (-kv[1], kv[0]))[:cap]
        hist = dict(keep)
    return hist


def _cost_analysis(compiled) -> Dict[str, float]:
    """flops / bytes accessed from ``compiled.cost_analysis()``; {} when the
    backend doesn't provide it (CPU often reports partial numbers)."""
    out: Dict[str, float] = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if not isinstance(cost, dict):
            return out
        for src, dst in (("flops", "flops"),
                         ("bytes accessed", "bytes_accessed")):
            v = cost.get(src)
            if v is not None:
                out[dst] = float(v)
    except Exception as e:
        _note("cost_analysis", e)
    return out


def _memory_analysis(compiled) -> Dict[str, int]:
    """argument/output/temp/generated-code bytes from
    ``compiled.memory_analysis()`` where the backend provides them."""
    out: Dict[str, int] = {}
    try:
        mem = compiled.memory_analysis()
        if mem is None:
            return out
        for attr, dst in (("argument_size_in_bytes", "argument_bytes"),
                          ("output_size_in_bytes", "output_bytes"),
                          ("temp_size_in_bytes", "temp_bytes"),
                          ("generated_code_size_in_bytes", "code_bytes")):
            v = getattr(mem, attr, None)
            if v is not None:
                out[dst] = int(v)
    except Exception as e:
        _note("memory_analysis", e)
    return out


def _rescan_seen(d: str):  # mxlint: disable=CONC200
    """Fold fingerprints written into ``d`` by ANY process into ``_SEEN``
    (caller holds ``_LOCK``). Incremental: each ledger file is consumed from
    the byte offset the previous scan reached, so calling this on every
    fingerprint miss stays O(new bytes) — sibling processes that wrote
    *after* our first scan are still seen before a compile is (mis)judged
    fresh. Only complete lines are consumed; a line still being appended is
    left for the next scan."""
    global _SCANNED_DIR
    if _SCANNED_DIR != d:
        _SCANNED_DIR = d
        _SCANNED.clear()
    try:
        names = [n for n in os.listdir(d)
                 if n.startswith("ledger-") and n.endswith(".jsonl")]
    except OSError:
        return
    for n in names:
        path = os.path.join(d, n)
        off = _SCANNED.get(path, 0)
        try:
            with open(path, "rb") as f:
                f.seek(off)
                chunk = f.read()
        except OSError:
            continue
        nl = chunk.rfind(b"\n")
        if nl < 0:
            continue
        _SCANNED[path] = off + nl + 1
        for line in chunk[:nl + 1].splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            fp = rec.get("fingerprint")
            if fp and fp not in _SEEN:
                _SEEN[fp] = float(rec.get("compile_s", 0.0) or 0.0)


def _append_jsonl(d: str, rec: Dict):
    """One O_APPEND write of one line: atomic for the short records we write
    even when multiple processes share the ledger directory."""
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"ledger-{os.getpid()}.jsonl")
        data = (json.dumps(rec, sort_keys=True) + "\n").encode("utf-8")
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
    except OSError:
        pass          # a broken disk must not take down the compile it logs


def _retain_text(d: str, fp: str, text: str):
    """Retain the canonicalized module text as ``module-<fp>.mlir`` beside
    the ledger records. Content-addressed, so dedup is a stat; the file
    re-hashes to its own name (``fingerprint_text(contents) == fp``), which
    is the integrity invariant hlolint's IR000 audits. Byte-bounded by
    MXNET_COMPILE_LEDGER_TEXT_MAX_BYTES over the directory's retained
    texts, and written tmp+rename (no O_APPEND: unlike the record stream
    this is a whole file, and a torn module text would fail its own
    content address)."""
    canon = _irparser.canonicalize(text)
    path = os.path.join(d, f"module-{fp}.mlir")
    if os.path.exists(path):
        _TEXT_RETAINED.labels("dedup").inc()
        return
    data = canon.encode("utf-8")
    budget = int(_cfg("MXNET_COMPILE_LEDGER_TEXT_MAX_BYTES", 32 << 20))
    if budget >= 0:
        used = 0
        for n in os.listdir(d):
            if n.startswith("module-") and n.endswith(".mlir"):
                try:
                    used += os.path.getsize(os.path.join(d, n))
                except OSError:
                    continue
        if used + len(data) > budget:
            _TEXT_RETAINED.labels("over_budget").inc()
            return
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _TEXT_RETAINED.labels("written").inc()


def _donation_summary(compiled, text: Optional[str],
                      expect_donation: bool) -> Optional[Dict[str, int]]:
    """``{"requested": n, "aliased": m}`` for a just-compiled executable,
    or None when nothing was donated. ``requested`` comes from the
    executable's own ``donate_argnums`` (present whether or not XLA kept
    the aliases), with the caller's ``expect_donation`` declaration as the
    floor — a site that *intends* donation but compiled a function with no
    donate_argnums is exactly the regression the guard exists to catch.
    ``aliased`` counts entry arguments whose alias survived into the text
    (``tf.aliasing_output`` / ``jax.buffer_donor``); omitted when the text
    was unavailable so IR1000 never fires on missing evidence."""
    requested = 0
    try:
        donated = getattr(compiled, "donate_argnums", None) or ()
        requested = len(tuple(donated))
    except Exception as e:
        _note("donation", e)
    if expect_donation and requested == 0:
        requested = 1
    if requested <= 0:
        return None
    out = {"requested": requested}
    if text is not None:
        out["aliased"] = _irparser.count_aliased_args(text)
    return out


def _guard_mode() -> str:
    mode = str(_cfg("MXNET_IR_GUARD", "") or "").strip().lower()
    return mode if mode in ("warn", "raise") else ""


def _run_ir_guard(site: str, key: Optional[Dict], text: Optional[str],
                  donation: Optional[Dict[str, int]]) -> List:
    """Evaluate the guarded IR rules against one fresh compile and emit
    metrics / flight event / warning. Returns the findings so the caller
    can raise *outside* this function — everything in here is fail-open
    (guard breakage must never fail a compile), but a real finding under
    MXNET_IR_GUARD=raise must."""
    mode = _guard_mode()
    # the donation assertion is metrics-free of cost (the summary already
    # exists for the record) so it runs even with the guard off — a
    # dropped donation always shows up in mxtpu_ir_guard_total
    donation_bad = bool(donation and donation.get("requested", 0) > 0
                        and donation.get("aliased", -1) == 0)
    if not mode and not donation_bad:
        return []
    findings = _ir_findings(text, site=site, donation=donation,
                            check_constants=bool(mode))
    if not findings:
        return []
    outcome = mode or "detected"
    for rule, message in findings:
        try:
            _IR_GUARD.labels(rule, outcome).inc()
        except Exception as e:
            _note("ir_guard_metric", e)
        warnings.warn(f"[{rule}] compile at site={site}: {message}",
                      RuntimeWarning, stacklevel=3)
    try:
        from . import flight as _flight
        _flight.trigger("ir_guard", site=site, outcome=outcome,
                        rules=",".join(sorted({r for r, _ in findings})),
                        key={str(k): v for k, v in (key or {}).items()})
    except Exception as e:
        _note("ir_guard_flight", e)
    return findings if mode == "raise" else []


def record(site: str, fingerprint: Optional[str], lower_s: float,
           compile_s: float, key: Optional[Dict[str, Any]] = None,
           compiled=None, cache_hit: bool = False,
           ops: Optional[Dict[str, int]] = None,
           donation: Optional[Dict[str, int]] = None) -> CompileRecord:
    """Emit one CompileRecord (ring + metrics + JSONL). Never raises.

    ``cache_hit=True`` marks an executable answered by the persistent cache
    (``compile_s`` is then the deserialize time): such records are never
    duplicates and never charge ``mxtpu_compile_duplicate_waste_seconds_total``
    — nothing was re-spent, the fleet's copy was reused. ``ops`` is the
    optional :func:`op_histogram` of the lowered module — the cost model's
    program features. ``donation`` is the optional
    ``{"requested": n, "aliased": m}`` summary: how many arguments the
    caller asked to donate vs how many aliases actually survived lowering —
    the durable evidence hlolint's IR1000 reads (the lowered text itself
    carries *no trace* of a dropped donation)."""
    rec = CompileRecord(
        ts=time.time(), pid=os.getpid(), site=str(site),
        fingerprint=fingerprint,
        lower_s=round(float(lower_s), 6), compile_s=round(float(compile_s), 6),
        key={str(k): v for k, v in (key or {}).items()},
        duplicate=False, cache_hit=bool(cache_hit),
    )
    if ops:
        rec["ops"] = {str(k): int(v) for k, v in ops.items()}
    if donation:
        rec["donation"] = {str(k): int(v) for k, v in donation.items()}
    if compiled is not None:
        rec.update(_cost_analysis(compiled))
        rec.update(_memory_analysis(compiled))
    d = ledger_dir()
    with _LOCK:
        if fingerprint is not None:
            if fingerprint not in _SEEN and d:
                # miss: re-scan sibling processes' ledger files before
                # judging this fingerprint fresh (they may have compiled
                # it after our last scan)
                _rescan_seen(d)
            if fingerprint in _SEEN:
                rec["duplicate"] = not rec["cache_hit"]
            else:
                _SEEN[fingerprint] = rec["lower_s"] + rec["compile_s"]
        _RING.append(rec)
    try:
        _RECORDS.labels(rec["site"]).inc()
        _WALL.labels(rec["site"], "lower").inc(rec["lower_s"])
        _WALL.labels(rec["site"], "compile").inc(rec["compile_s"])
        if rec["duplicate"]:
            _DUPS.labels(rec["site"]).inc()
            _DUP_WASTE.inc(rec["lower_s"] + rec["compile_s"])
    except Exception as e:
        _note("metrics", e)
    if d:
        _append_jsonl(d, rec)
    return rec


def lower_and_compile(jfn, args, *, site: str,
                      key: Optional[Dict[str, Any]] = None,
                      kwargs: Optional[Dict] = None,
                      expect_donation: bool = False):
    """The one-stop instrumentation for an AOT compile site: time
    ``jfn.lower(*args)``, fingerprint the lowered StableHLO, consult the
    persistent executable cache (``MXNET_EXEC_CACHE_DIR``), and only on a
    miss time ``.compile()`` and populate the cache. Emits the record
    (``cache_hit`` says which path ran) and returns the executable. Ledger
    and cache failures never fail the compile.

    ``expect_donation=True`` declares the site requested buffer donation
    (serving endpoints pass their platform decision): the record then
    carries the ``donation`` requested/aliased summary and the IR guard's
    donation assertion is armed. With MXNET_IR_GUARD=raise a guarded-rule
    violation raises :class:`IRGuardError` — the one deliberate exception
    to fail-open, and it fires only after the record, metrics, and flight
    event are already emitted, so the evidence outlives the refusal."""
    t0 = time.perf_counter()
    lowered = jfn.lower(*args, **(kwargs or {}))
    t1 = time.perf_counter()
    fp = None
    ops = None
    text = None
    try:
        text = lowered.as_text()
        fp = fingerprint_text(text)
        ops = op_histogram(text)
    except Exception as e:
        _note("fingerprint", e)
    compiled = None
    ckey = None
    t2 = time.perf_counter()
    if fp is not None:
        try:
            from ..cache import executable_cache as _xcache
            if _xcache.enabled():
                ckey = _xcache.build_key(fp, lowered, extra=key)
                compiled = _xcache.load(ckey)
        except Exception as e:
            _note("exec_cache", e)
            ckey = None
    cache_hit = compiled is not None
    if compiled is None:
        compiled = lowered.compile()
    t3 = time.perf_counter()
    if not cache_hit and ckey is not None:
        try:
            from ..cache import executable_cache as _xcache
            _xcache.store(ckey, compiled)
        except Exception as e:
            _note("exec_cache_store", e)
    donation = None
    try:
        donation = _donation_summary(compiled, text, expect_donation)
    except Exception as e:
        _note("donation", e)
    try:
        record(site, fp, lower_s=t1 - t0, compile_s=t3 - t2, key=key,
               compiled=compiled, cache_hit=cache_hit, ops=ops,
               donation=donation)
    except Exception as e:
        _note("record", e)
    d = ledger_dir()
    if d and fp is not None and text is not None:
        try:
            os.makedirs(d, exist_ok=True)
            _retain_text(d, fp, text)
        except Exception as e:
            _note("retain_text", e)
    raising = []
    try:
        raising = _run_ir_guard(site, key, text, donation)
    except Exception as e:
        _note("ir_guard", e)
    if raising:
        raise IRGuardError(raising, site)
    return compiled


def instrument_eager_jit(jfn, op_name: str):
    """Wrap an eager ``jax.jit`` wrapper so each NEW aval signature compiles
    through the ledger (AOT) instead of lazily inside the jit call. Installed
    by ops/registry only when :func:`eager_active` — the default eager path
    is untouched, so the dispatch-latency gate never pays for bookkeeping it
    isn't using. Tracer inputs (op dispatched inside an outer trace) and
    non-array inputs fall through to the plain jit wrapper."""
    compiled: Dict[tuple, Any] = {}
    lock = threading.Lock()

    def wrapper(*args):
        import jax
        try:
            if any(isinstance(a, jax.core.Tracer) for a in args):
                return jfn(*args)
            sig = tuple((tuple(a.shape), str(a.dtype)) for a in args)
        except Exception:
            return jfn(*args)
        comp = compiled.get(sig)
        if comp is None:
            with lock:
                comp = compiled.get(sig)
                if comp is None:
                    comp = lower_and_compile(jfn, args, site="eager_jit",
                                             key={"op": op_name})
                    compiled[sig] = comp
        return comp(*args)

    wrapper._ledger_instrumented = True
    return wrapper


def recent(k: Optional[int] = None) -> List[Dict]:
    """The last ``k`` CompileRecords (default MXNET_COMPILE_LEDGER_KEEP),
    oldest first."""
    if k is None:
        k = int(_cfg("MXNET_COMPILE_LEDGER_KEEP", 64))
    with _LOCK:
        items = list(_RING)
    return [dict(r) for r in items[-max(0, k):]]


def summary() -> Dict[str, float]:
    """Process-lifetime totals over every record still in scope: compile
    counts, distinct programs, duplicate count and re-spent seconds."""
    with _LOCK:
        items = list(_RING)
    dups = [r for r in items if r.get("duplicate")]
    return {
        "compiles": len(items),
        "distinct_fingerprints": len({r["fingerprint"] for r in items
                                      if r.get("fingerprint")}),
        "duplicates": len(dups),
        "dup_waste_s": round(sum(r["lower_s"] + r["compile_s"]
                                 for r in dups), 6),
        "cache_hits": sum(1 for r in items if r.get("cache_hit")),
        "lower_s": round(sum(r["lower_s"] for r in items), 6),
        "compile_s": round(sum(r["compile_s"] for r in items), 6),
    }


def read_ledger(d: Optional[str] = None) -> List[Dict]:
    """Every record in the JSONL ledger directory (all processes), in file
    order. Used by tools/compile_report.py."""
    d = d or ledger_dir()
    out: List[Dict] = []
    if not d or not os.path.isdir(d):
        return out
    for n in sorted(os.listdir(d)):
        if not (n.startswith("ledger-") and n.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(d, n)) as f:
                for line in f:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
    return out


def reset():
    """Forget ring + seen-set + scan offsets (tests; a changed ledger dir
    re-scans from the top)."""
    global _SCANNED_DIR
    with _LOCK:
        _RING.clear()
        _SEEN.clear()
        _SCANNED.clear()
        _SCANNED_DIR = None
