"""FlightRecorder — the black box that makes a dead process explainable.

Always-on bounded ring buffers (spans, structured events, completed serving
requests) plus a trigger-driven bundle writer: the moment something breaks —
a watchdog stall, a circuit opening, a failover, a numerics anomaly, an SDC
suspect, a preemption, an unhandled exception — the recorder atomically
writes a timestamped JSON bundle to ``MXNET_FLIGHT_DIR`` capturing the last
seconds of activity (ring contents), the full metrics snapshot, the knob/env
fingerprint, and every live thread's stack (``sys._current_frames``).
``tools/flight_inspect.py`` renders a bundle into a human timeline.

Hot-path discipline: ring appends are single ``deque.append`` calls on
bounded deques — atomic under the GIL, no lock, no allocation beyond the
entry itself — so recording rides inside the eager-dispatch overhead gate.
All the expensive work (snapshotting, JSON encoding, fsync-free atomic
rename) happens only on a trigger, rate-limited per trigger kind.

Subsystems emit structured events through ``telemetry.event(kind, **attrs)``
(record-only) or ``flight.trigger(kind, **attrs)`` (record *and* dump when a
flight directory is configured). Triggers never raise: a broken disk must
not take down the serving path it is trying to explain.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

from .metrics import REGISTRY

__all__ = ["FlightRecorder", "RECORDER", "event", "record_request",
           "trigger", "dump", "recent_spans", "recent_events",
           "recent_requests", "install_excepthooks", "uninstall_excepthooks",
           "list_bundles", "load_bundle"]

_EVENTS = REGISTRY.counter(
    "mxtpu_flight_events_total",
    "Structured events recorded into the flight ring, by kind "
    "(circuit_transition, retry, failover, hot_swap, numerics_anomaly, "
    "preemption, ...).",
    labelnames=("kind",))
_DUMPS = REGISTRY.counter(
    "mxtpu_flight_dumps_total",
    "Flight bundles written, by trigger kind.",
    labelnames=("trigger",))
_SUPPRESSED = REGISTRY.counter(
    "mxtpu_flight_dumps_suppressed_total",
    "Trigger dumps suppressed by the per-kind MXNET_FLIGHT_MIN_INTERVAL_S "
    "rate limit (the event is still recorded in the ring).")

_SCHEMA = 2   # 2: + compile_records / memstats sections (perf observability)
_JSONABLE = (str, int, float, bool, type(None))


def _cfg(name, default):
    """Read a knob through mxnet_tpu.config, tolerating the partially
    initialized package (telemetry can be imported by the profiler before
    ``mxnet_tpu.config`` is bound during package init)."""
    try:
        from .. import config
        return config.get(name, default)
    except Exception:
        return default


def _clean_attrs(attrs: Dict) -> Dict:
    """Attrs are small JSON-able values; anything else renders as repr so a
    bundle never fails to serialize."""
    out = {}
    for k, v in attrs.items():
        out[str(k)] = v if isinstance(v, _JSONABLE) else repr(v)
    return out


def _span_entry(s) -> Dict:
    return {
        "name": s.name,
        "trace_id": s.trace_id,
        "span_id": s.span_id,
        "parent_id": s.parent_id,
        "t0_us": s.t0_us,
        "dur_us": s.dur_us,
        "attrs": _clean_attrs(s.attrs) if s.attrs else {},
    }


def _thread_stacks() -> Dict[str, List[str]]:
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, f"thread-{ident}")
        stacks[f"{name} ({ident})"] = traceback.format_stack(frame)
    return stacks


class FlightRecorder:
    """Bounded recorder + trigger-driven bundle writer.

    Ring capacities are fixed at construction (knob-driven for the process
    RECORDER); ``directory`` / ``keep`` / ``min_interval_s`` re-read their
    knobs on every use when not pinned, so ``config.set`` takes effect on
    the live recorder.
    """

    def __init__(self, span_capacity: Optional[int] = None,
                 event_capacity: Optional[int] = None,
                 request_capacity: Optional[int] = None,
                 directory: Optional[str] = None,
                 keep: Optional[int] = None,
                 min_interval_s: Optional[float] = None):
        spans = span_capacity if span_capacity is not None else \
            int(_cfg("MXNET_FLIGHT_SPANS", 512))
        events = event_capacity if event_capacity is not None else \
            int(_cfg("MXNET_FLIGHT_EVENTS", 256))
        requests = request_capacity if request_capacity is not None else \
            int(_cfg("MXNET_FLIGHT_REQUESTS", 128))
        self._spans: deque = deque(maxlen=max(1, spans))
        self._events: deque = deque(maxlen=max(1, events))
        self._requests: deque = deque(maxlen=max(1, requests))
        self._directory = directory
        self._keep = keep
        self._min_interval_s = min_interval_s
        self._dump_lock = threading.Lock()
        self._last_dump: Dict[str, float] = {}
        self._seq = itertools.count()
        self.bundles_written: List[str] = []

    # -- knob-backed settings ----------------------------------------------
    @property
    def directory(self) -> str:
        if self._directory is not None:
            return self._directory
        return str(_cfg("MXNET_FLIGHT_DIR", "") or "")

    @property
    def keep(self) -> int:
        if self._keep is not None:
            return self._keep
        return int(_cfg("MXNET_FLIGHT_KEEP", 8))

    @property
    def min_interval_s(self) -> float:
        if self._min_interval_s is not None:
            return self._min_interval_s
        return float(_cfg("MXNET_FLIGHT_MIN_INTERVAL_S", 1.0))

    # -- hot-path recording (GIL-atomic deque appends, no locks) -----------
    def record_span(self, s):
        self._spans.append(s)

    def record_event(self, kind: str, attrs: Dict) -> Dict:
        entry = {"ts": time.time(), "kind": str(kind),
                 "attrs": _clean_attrs(attrs)}
        self._events.append(entry)
        _EVENTS.labels(kind).inc()
        return entry

    def record_request(self, trace_id: str, endpoint: str, latency_us: float,
                       rows: int = 0, ok: bool = True, **attrs):
        entry = {"ts": time.time(), "trace_id": trace_id,
                 "endpoint": endpoint, "latency_us": float(latency_us),
                 "rows": int(rows), "ok": bool(ok)}
        if attrs:
            entry.update(_clean_attrs(attrs))
        self._requests.append(entry)

    # -- ring introspection -------------------------------------------------
    def recent_spans(self) -> List[Dict]:
        return [_span_entry(s) for s in list(self._spans)]

    def recent_events(self) -> List[Dict]:
        return list(self._events)

    def recent_requests(self) -> List[Dict]:
        return list(self._requests)

    def clear(self):
        self._spans.clear()
        self._events.clear()
        self._requests.clear()

    def reset_rate_limit(self):
        """Forget per-kind dump timestamps (chaos harnesses run scenarios
        back-to-back and each must be able to dump immediately)."""
        with self._dump_lock:
            self._last_dump.clear()

    # -- triggers & bundles -------------------------------------------------
    def trigger(self, kind: str, /, **attrs) -> Optional[str]:
        """Record ``kind`` as an event and, when a flight directory is
        configured, write a bundle (rate-limited per kind). Never raises;
        returns the bundle path or None."""
        try:
            self.record_event(kind, attrs)
            if not self.directory:
                return None
            now = time.monotonic()
            with self._dump_lock:
                last = self._last_dump.get(kind)
                if last is not None and now - last < self.min_interval_s:
                    _SUPPRESSED.inc()
                    return None
                self._last_dump[kind] = now
            return self.dump(trigger=kind, attrs=attrs)
        except Exception:
            return None

    def bundle(self, trigger: str = "manual",
               attrs: Optional[Dict] = None) -> Dict:
        """Everything an on-call human needs, as one JSON-able dict."""
        try:
            from .. import config
            knobs = {name: config.get(name) for name in config.list_flags()}
        except Exception:
            knobs = {}
        env = {k: v for k, v in os.environ.items()
               if k.startswith(("MXNET_", "JAX_", "XLA_", "TPU_"))}
        try:
            from . import compile_ledger as _ledger
            compile_records = _ledger.recent()
            compile_summary = _ledger.summary()
        except Exception:
            compile_records, compile_summary = [], {}
        try:
            from . import memstats as _memstats
            mem = _memstats.breakdown()
        except Exception:
            mem = {}
        return {
            "schema": _SCHEMA,
            "ts": time.time(),
            "trigger": {"kind": str(trigger),
                        "attrs": _clean_attrs(attrs or {})},
            "spans": self.recent_spans(),
            "events": self.recent_events(),
            "requests": self.recent_requests(),
            "metrics": REGISTRY.snapshot(),
            "compile_records": {"summary": compile_summary,
                                "records": compile_records},
            "memstats": mem,
            "config": knobs,
            "fingerprint": {
                "pid": os.getpid(),
                "argv": list(sys.argv),
                "python": sys.version.split()[0],
                "platform": sys.platform,
                "env": env,
            },
            "threads": _thread_stacks(),
        }

    def dump(self, path: Optional[str] = None, trigger: str = "manual",
             attrs: Optional[Dict] = None) -> str:
        """Write a bundle atomically (tmp + rename) and rotate old bundles.
        With no explicit ``path`` the bundle lands in ``directory`` (or the
        cwd when no flight directory is configured)."""
        payload = json.dumps(self.bundle(trigger, attrs), indent=1,
                             sort_keys=True, default=repr)
        with self._dump_lock:
            if path is None:
                d = self.directory or "."
                os.makedirs(d, exist_ok=True)
                slug = "".join(c if c.isalnum() or c in "_-" else "_"
                               for c in str(trigger)) or "manual"
                stamp = time.strftime("%Y%m%d-%H%M%S")
                path = os.path.join(
                    d, f"flight-{stamp}-{next(self._seq):04d}-{slug}.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            # _dump_lock exists solely to serialize bundle IO + rotation
            # (the ring-buffer lock is separate and stays free): blocking
            # here only queues other dumpers, which is its purpose
            with open(tmp, "w") as f:  # mxlint: disable=CONC202
                f.write(payload)
            os.replace(tmp, path)
            self.bundles_written.append(path)
            self._rotate(os.path.dirname(path) or ".")
        _DUMPS.labels(trigger).inc()
        return path

    def _rotate(self, d: str):  # mxlint: disable=CONC200
        """Keep the newest ``keep`` bundles in ``d`` (caller holds
        ``_dump_lock``)."""
        keep = self.keep
        if keep <= 0:
            return
        try:
            bundles = list_bundles(d)
        except OSError:
            return
        for stale in bundles[:-keep]:
            try:
                os.remove(stale)
            except OSError:
                pass


def list_bundles(d: str) -> List[str]:
    """Flight bundle paths in ``d``, oldest first (name-sorted: the
    timestamp+sequence filename makes that write order)."""
    if not d or not os.path.isdir(d):
        return []
    return sorted(
        os.path.join(d, f) for f in os.listdir(d)
        if f.startswith("flight-") and f.endswith(".json"))


def load_bundle(path: str) -> Dict:
    with open(path) as f:
        return json.load(f)


# the process-wide recorder: tracing and the serving/resilience layers feed it
RECORDER = FlightRecorder()


# -- module-level conveniences (the API subsystems call) -----------------------

def event(kind: str, /, **attrs) -> Dict:
    """Record a structured event into the flight ring (and bump
    ``mxtpu_flight_events_total{kind=...}``). Cheap and always on."""
    return RECORDER.record_event(kind, attrs)


def record_request(trace_id: str, endpoint: str, latency_us: float,
                   rows: int = 0, ok: bool = True, **attrs):
    RECORDER.record_request(trace_id, endpoint, latency_us, rows=rows,
                            ok=ok, **attrs)


def trigger(kind: str, /, **attrs) -> Optional[str]:
    return RECORDER.trigger(kind, **attrs)


def dump(path: Optional[str] = None, trigger: str = "manual",
         **attrs) -> str:
    return RECORDER.dump(path=path, trigger=trigger, attrs=attrs)


def recent_spans() -> List[Dict]:
    return RECORDER.recent_spans()


def recent_events() -> List[Dict]:
    return RECORDER.recent_events()


def recent_requests() -> List[Dict]:
    return RECORDER.recent_requests()


# -- crash hooks ---------------------------------------------------------------

_PREV_HOOKS = None


def install_excepthooks():
    """Chain ``sys.excepthook`` and ``threading.excepthook`` so an unhandled
    exception anywhere dumps a flight bundle before the previous hook runs.
    Idempotent; undo with :func:`uninstall_excepthooks`."""
    global _PREV_HOOKS
    if _PREV_HOOKS is not None:
        return
    prev_sys, prev_thread = sys.excepthook, threading.excepthook

    def _sys_hook(tp, val, tb):
        RECORDER.trigger("unhandled_exception", error=tp.__name__,
                         message=str(val)[:500], thread="MainThread")
        prev_sys(tp, val, tb)

    def _thread_hook(args):
        if args.exc_type is not SystemExit:
            name = args.thread.name if args.thread else "?"
            RECORDER.trigger("unhandled_exception",
                             error=args.exc_type.__name__,
                             message=str(args.exc_value)[:500], thread=name)
        prev_thread(args)

    sys.excepthook = _sys_hook
    threading.excepthook = _thread_hook
    _PREV_HOOKS = (prev_sys, prev_thread)


def uninstall_excepthooks():
    global _PREV_HOOKS
    if _PREV_HOOKS is None:
        return
    sys.excepthook, threading.excepthook = _PREV_HOOKS
    _PREV_HOOKS = None


def _autostart():
    """Env-driven crash-hook installation (called once from
    mxnet_tpu/__init__): a configured flight directory means the operator
    wants bundles on every unhandled exception."""
    if RECORDER.directory:
        install_excepthooks()
