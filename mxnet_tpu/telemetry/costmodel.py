"""Learned cost model over the compile ledger — the observatory half of
ROADMAP item 3.

PR 10's compile ledger records, fleet-wide, exactly the corpus "A Learned
Performance Model for Tensor Processing Units" was built from: op
histograms of the canonicalized StableHLO, ``cost_analysis()`` flops and
bytes, the trigger key (endpoint, bucket, dtype, device, mesh slice), and
measured compile wall. This module closes the loop:

* ``kind="step"`` records — measured step wall per (site, key, bucket) —
  are appended into the *same* ``ledger-<pid>.jsonl`` files (rate-limited
  to power-of-two observation counts so steady state costs one line per
  doubling). They carry no ``fingerprint`` so the duplicate-compile
  accounting never sees them.
* :func:`train` fits a small ridge regressor (log-space normal
  equations — numpy only, no new deps) from any ledger directory to two
  targets, ``step_us`` and ``compile_s``, with an honest holdout split,
  and persists a versioned, sha256-sealed JSON artifact via atomic
  write (:meth:`CostModel.save` / :func:`load`).
* :func:`predict_step_us` / :func:`predict_compile_s` serve the active
  model (``MXNET_COSTMODEL_PATH``) as the **prior** for cold
  ``StepCostEWMA`` buckets (serving router EDF pricing, decode admission,
  fabric per-slice admission) and for the autoscaler's predicted warm-up
  lead time. Measured values always win once observed — the EWMA blends
  the prior out over ``MXNET_COSTMODEL_BLEND_N`` observations, never the
  other way around.
* Every prediction is accountable: ``mxtpu_cost_predicted_us`` /
  ``mxtpu_cost_residual_ratio`` per (site, bucket), and a latched
  residual drift detector (the perf_sentinel pattern) fires a single
  ``cost_model_drift`` flight event per episode of sustained
  out-of-band |residual| — the stale-model alarm.

Everything here is telemetry: no function in this module may raise into
a serving or training step.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as onp

from .metrics import REGISTRY

__all__ = [
    "CostModelError", "CostModel", "featurize", "build_corpus",
    "row_ratio_estimator", "train", "train_from_dir", "load",
    "set_active", "active_model", "prior_enabled", "make_prior",
    "predict_step_us", "predict_compile_s", "on_step_observed",
    "read_steps", "export_rows", "snapshot", "reset",
]

SCHEMA = 1
TARGETS = ("step_us", "compile_s")

_PREDICTED_G = REGISTRY.gauge(
    "mxtpu_cost_predicted_us",
    "Cost-model predicted step wall per (site, bucket) — the prior a cold "
    "StepCostEWMA prices with before any measurement exists.",
    labelnames=("site", "bucket"))
_RESIDUAL_G = REGISTRY.gauge(
    "mxtpu_cost_residual_ratio",
    "measured / predicted step wall per (site, bucket); 1.0 is a perfect "
    "prediction, sustained excursions out of the drift band fire "
    "cost_model_drift.",
    labelnames=("site", "bucket"))
_PRIOR_USED = REGISTRY.counter(
    "mxtpu_cost_prior_used_total",
    "Cold (never-measured) buckets priced by the learned prior instead of "
    "the row-ratio fallback, per site.",
    labelnames=("site",))
_DRIFT_C = REGISTRY.counter(
    "mxtpu_cost_model_drift_total",
    "Latched cost_model_drift episodes (sustained out-of-band residual "
    "ratio) per site — a firing means the committed model is stale for "
    "this workload.",
    labelnames=("site",))


def _cfg(name, default):
    try:
        from .. import config
        return config.get(name, default)
    except Exception:
        return default


class CostModelError(Exception):
    """Raised on an unusable corpus or a corrupt/stale model artifact."""


# ---------------------------------------------------------------------------
# featurization
# ---------------------------------------------------------------------------

_COST_FIELDS = (
    ("flops", "log_flops"),
    ("bytes_accessed", "log_bytes_accessed"),
    ("argument_bytes", "log_argument_bytes"),
    ("output_bytes", "log_output_bytes"),
    ("temp_bytes", "log_temp_bytes"),
    ("code_bytes", "log_code_bytes"),
)


def _mesh_size(label: Any) -> float:
    """Total devices in a mesh label like ``dp=4`` or ``dp=2,tp=2``."""
    total = 1.0
    try:
        for part in str(label).split(","):
            if "=" in part:
                total *= max(1.0, float(part.split("=", 1)[1]))
    except (TypeError, ValueError):
        return 1.0
    return total


def featurize(key: Optional[Dict[str, Any]], site: str = "",
              rows: Optional[float] = None,
              comp: Optional[Dict[str, Any]] = None) -> Dict[str, float]:
    """One sparse feature dict (name -> float) shared by training and
    prediction. ``key`` is a ledger trigger key (endpoint/bucket/dtype/
    device/mesh/kind), ``comp`` an optional joined CompileRecord providing
    the program features (op histogram, cost_analysis flops/bytes)."""
    key = key or {}
    f: Dict[str, float] = {"bias": 1.0}
    bucket = key.get("bucket")
    try:
        if bucket is not None and float(bucket) > 0:
            f["log_bucket"] = math.log1p(float(bucket))
    except (TypeError, ValueError):
        bucket = None
    if rows is None:
        rows = bucket
    try:
        if rows is not None and float(rows) > 0:
            f["log_rows"] = math.log1p(float(rows))
    except (TypeError, ValueError):
        pass
    pages = key.get("pages")
    try:
        if pages is not None and float(pages) > 0:
            f["log_pages"] = math.log1p(float(pages))
    except (TypeError, ValueError):
        pass
    if key.get("dtype"):
        f["dtype:%s" % key["dtype"]] = 1.0
    device = str(key.get("device") or "")
    if device:
        f["device:%s" % device.split(":", 1)[0]] = 1.0
    mesh = key.get("mesh")
    if mesh:
        f["mesh:%s" % mesh] = 1.0
        f["log_mesh_size"] = math.log1p(_mesh_size(mesh))
    if key.get("kind"):
        f["kind:%s" % key["kind"]] = 1.0
    if key.get("endpoint"):
        f["endpoint:%s" % key["endpoint"]] = 1.0
    if key.get("op"):
        f["op_name:%s" % key["op"]] = 1.0
    if site:
        f["site:%s" % site] = 1.0
    if comp:
        for src, name in _COST_FIELDS:
            v = comp.get(src)
            try:
                if v and float(v) > 0:
                    f[name] = math.log1p(float(v))
            except (TypeError, ValueError):
                pass
        fl, ba = comp.get("flops"), comp.get("bytes_accessed")
        try:
            if fl and ba and float(ba) > 0:
                f["flops_per_byte"] = min(float(fl) / float(ba), 1e4)
        except (TypeError, ValueError):
            pass
        for op, n in sorted((comp.get("ops") or {}).items()):
            try:
                f["op:%s" % op] = math.log1p(float(n))
            except (TypeError, ValueError):
                pass
    return f


def _key_id(key: Dict[str, Any]) -> str:
    return json.dumps(key or {}, sort_keys=True, default=str)


def _compile_index(records: Sequence[Dict]) -> Dict[Any, Dict]:
    """Index compile records for the step-record join: exact trigger-key
    match first, (endpoint, bucket, kind) fallback. Later records win —
    they carry the freshest cost_analysis."""
    idx: Dict[Any, Dict] = {}
    for r in records:
        if r.get("kind") == "step" or not isinstance(r.get("key"), dict):
            continue
        k = r["key"]
        idx[_key_id(k)] = r
        if k.get("endpoint") is not None and k.get("bucket") is not None:
            idx[(k.get("endpoint"), k.get("bucket"), k.get("kind"))] = r
    return idx


def _join(key: Dict[str, Any], idx: Dict[Any, Dict]) -> Optional[Dict]:
    got = idx.get(_key_id(key))
    if got is None and key.get("endpoint") is not None:
        got = idx.get((key.get("endpoint"), key.get("bucket"),
                       key.get("kind")))
    return got


def build_corpus(records: Sequence[Dict]) -> List[Dict]:
    """Featurized training samples from raw ledger records.

    Each sample: ``{"target", "y", "x", "site", "endpoint", "bucket"}``.
    Step records train the ``step_us`` target (joined to their compile
    record for program features); non-cache-hit compile records train
    ``compile_s`` (target = lower_s + compile_s; cache hits are excluded —
    their wall is deserialize time, a different quantity)."""
    idx = _compile_index(records)
    out: List[Dict] = []
    for r in records:
        try:
            key = r.get("key") if isinstance(r.get("key"), dict) else {}
            if r.get("kind") == "step":
                y = float(r.get("step_us", 0.0) or 0.0)
                if y <= 0:
                    continue
                comp = _join(key, idx)
                x = featurize(key, str(r.get("site", "")),
                              rows=r.get("rows"), comp=comp)
                target = "step_us"
            else:
                if r.get("cache_hit"):
                    continue
                y = float(r.get("lower_s", 0.0) or 0.0) + \
                    float(r.get("compile_s", 0.0) or 0.0)
                if y <= 0:
                    continue
                x = featurize(key, str(r.get("site", "")), comp=r)
                target = "compile_s"
            out.append({
                "target": target, "y": y, "x": x,
                "site": str(r.get("site", "")),
                "endpoint": key.get("endpoint"),
                "bucket": key.get("bucket"),
            })
        except (TypeError, ValueError, KeyError):
            continue
    return out


def row_ratio_estimator(samples: Sequence[Dict]) -> Callable[[Dict], float]:
    """The pre-model fallback as an offline estimator: mean measured cost
    per (endpoint, site) at each bucket, nearest-bucket linear row-ratio
    for unseen buckets — exactly ``StepCostEWMA.estimate``'s shape. The
    baseline the learned model must beat on never-observed buckets."""
    table: Dict[Tuple, Dict[float, List[float]]] = {}
    for s in samples:
        b = s.get("bucket")
        if b is None:
            continue
        g = table.setdefault((s.get("endpoint"), s.get("site")), {})
        g.setdefault(float(b), []).append(float(s["y"]))
    means = {gk: {b: sum(v) / len(v) for b, v in g.items()}
             for gk, g in table.items()}

    def estimate(sample: Dict) -> float:
        b = sample.get("bucket")
        g = means.get((sample.get("endpoint"), sample.get("site")))
        if not g or b is None:
            all_y = [y for gg in means.values() for y in gg.values()]
            return sum(all_y) / len(all_y) if all_y else 0.0
        b = float(b)
        if b in g:
            return g[b]
        nearest = min(g, key=lambda x: abs(x - b))
        return g[nearest] * (b / nearest)

    return estimate


# ---------------------------------------------------------------------------
# model: ridge in log space, JSON artifact
# ---------------------------------------------------------------------------

def _fit_ridge(samples: Sequence[Dict], lam: float) -> Dict[str, float]:
    names = sorted({n for s in samples for n in s["x"]})
    X = onp.zeros((len(samples), len(names)))
    cols = {n: j for j, n in enumerate(names)}
    for i, s in enumerate(samples):
        for n, v in s["x"].items():
            X[i, cols[n]] = v
    y = onp.array([math.log1p(float(s["y"])) for s in samples])
    A = X.T @ X + float(lam) * onp.eye(len(names))
    w = onp.linalg.solve(A, X.T @ y)
    return {n: float(w[cols[n]]) for n in names}


def _predict_raw(weights: Dict[str, float], x: Dict[str, float]) -> float:
    z = 0.0
    for n, v in x.items():
        wn = weights.get(n)
        if wn is not None:
            z += wn * v
    return math.expm1(min(z, 60.0))  # cap: never overflow on a wild input


def _mape(pairs: Sequence[Tuple[float, float]]) -> Optional[float]:
    errs = [abs(p - y) / y for p, y in pairs if y > 0]
    return (sum(errs) / len(errs)) if errs else None


class CostModel:
    """A trained (or loaded) cost model: per-target ridge weights over
    the sparse feature space, plus training metadata."""

    def __init__(self, payload: Dict[str, Any]):
        self.payload = payload

    # -- identity ---------------------------------------------------------
    @property
    def version(self) -> str:
        return str(self.payload.get("sha256", ""))[:12] or "unsealed"

    @property
    def schema(self) -> int:
        return int(self.payload.get("schema", 0))

    def metrics(self, target: str) -> Dict[str, Any]:
        return dict(self.payload.get("targets", {}).get(target, {}
                                                        ).get("metrics", {}))

    # -- inference --------------------------------------------------------
    def predict(self, target: str, x: Dict[str, float]) -> Optional[float]:
        t = self.payload.get("targets", {}).get(target)
        if not t:
            return None
        v = _predict_raw(t.get("weights", {}), x)
        if not math.isfinite(v) or v <= 0:
            return None
        return v

    def importances(self, target: str, top: int = 16) -> List[Tuple[str, float]]:
        """|weight| ranked — in log space every feature is O(log scale),
        so raw magnitude is a fair importance proxy."""
        t = self.payload.get("targets", {}).get(target, {})
        w = t.get("weights", {})
        ranked = sorted(w.items(), key=lambda kv: -abs(kv[1]))
        return [(n, float(v)) for n, v in ranked[:top]]

    # -- artifact ---------------------------------------------------------
    def _sealed(self) -> Dict[str, Any]:
        body = {k: v for k, v in self.payload.items() if k != "sha256"}
        digest = hashlib.sha256(
            json.dumps(body, sort_keys=True).encode("utf-8")).hexdigest()
        body["sha256"] = digest
        return body

    def save(self, path: str) -> str:
        """Atomic write (tmp + rename) of the sha256-sealed artifact."""
        self.payload = self._sealed()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.payload, f, sort_keys=True, indent=1)
            f.write("\n")
        os.replace(tmp, path)
        return self.payload["sha256"]


def load(path: str) -> CostModel:
    """Load + verify an artifact: schema version gate and sha256 seal —
    a corrupt or hand-edited model is worse than no model."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError) as e:
        raise CostModelError("unreadable cost model %s: %s" % (path, e))
    if not isinstance(payload, dict):
        raise CostModelError("cost model %s: not a JSON object" % path)
    if int(payload.get("schema", -1)) != SCHEMA:
        raise CostModelError(
            "cost model %s: schema %r != %d (stale artifact)"
            % (path, payload.get("schema"), SCHEMA))
    want = payload.get("sha256")
    body = {k: v for k, v in payload.items() if k != "sha256"}
    got = hashlib.sha256(
        json.dumps(body, sort_keys=True).encode("utf-8")).hexdigest()
    if not want or want != got:
        raise CostModelError(
            "cost model %s: sha256 mismatch (corrupt artifact)" % path)
    return CostModel(payload)


def train(records: Sequence[Dict], lam: float = 1.0,
          holdout: float = 0.2, seed: int = 13, source: str = "",
          holdout_buckets: Optional[set] = None) -> CostModel:
    """Fit both targets from raw ledger records. Raises
    :class:`CostModelError` on an empty corpus — the predictor refuses to
    exist rather than return garbage, and every EWMA keeps its row-ratio
    fallback. Holdout MAPE (and the row-ratio baseline MAPE for
    ``step_us``) is computed only when a target has >= 10 samples.

    ``holdout_buckets`` — a set of ``(endpoint, bucket)`` pairs — replaces
    the random row split with a *bucket-level* holdout: every sample of a
    held-out bucket leaves the training set, so the reported MAPEs measure
    generalization to never-observed buckets (the cold-start case the
    prior exists for), not interpolation within seen ones."""
    corpus = build_corpus(records)
    if not corpus:
        raise CostModelError(
            "empty ledger: no trainable records (step or compile) — "
            "EWMA fallback stays in effect")
    targets: Dict[str, Any] = {}
    rng = onp.random.RandomState(seed)
    for target in TARGETS:
        samples = [s for s in corpus if s["target"] == target]
        if not samples:
            continue
        if holdout_buckets is not None:
            held = [s for s in samples
                    if (s.get("endpoint"), s.get("bucket"))
                    in holdout_buckets]
            fit = [s for s in samples
                   if (s.get("endpoint"), s.get("bucket"))
                   not in holdout_buckets]
            if not fit:
                continue
        else:
            order = rng.permutation(len(samples)).tolist()
            samples = [samples[i] for i in order]
            n_hold = int(len(samples) * holdout) if len(samples) >= 10 else 0
            held, fit = samples[:n_hold], samples[n_hold:]
        weights = _fit_ridge(fit, lam)
        metrics: Dict[str, Any] = {
            "n_train": len(fit), "n_holdout": len(held),
        }
        if held:
            preds = [(_predict_raw(weights, s["x"]), float(s["y"]))
                     for s in held]
            m = _mape(preds)
            if m is not None:
                metrics["holdout_mape"] = round(m, 4)
                metrics["check_budget_mape"] = round(m * 1.5 + 0.1, 4)
            if target == "step_us":
                base = row_ratio_estimator(fit)
                bm = _mape([(base(s), float(s["y"])) for s in held])
                if bm is not None:
                    metrics["row_ratio_mape"] = round(bm, 4)
        targets[target] = {"weights": weights, "metrics": metrics}
    if not targets:
        raise CostModelError("no target had any trainable samples")
    model = CostModel({
        "schema": SCHEMA,
        "created": round(time.time(), 3),
        "source": str(source),
        "n_records": len(records),
        "n_samples": len(corpus),
        "lambda": float(lam),
        "seed": int(seed),
        "targets": targets,
    })
    model.payload = model._sealed()
    return model


def train_from_dir(d: str, **kw) -> CostModel:
    from . import compile_ledger
    records = compile_ledger.read_ledger(d)
    kw.setdefault("source", d)
    return train(records, **kw)


# ---------------------------------------------------------------------------
# the active model + live predictions
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_ACTIVE: Optional[CostModel] = None
_ACTIVE_PINNED = False              # set_active() wins over the knob
_ACTIVE_SRC: Optional[Tuple[str, float]] = None   # (path, mtime) cache
_ACTIVE_ERR: Optional[str] = None


def set_active(model: Optional[CostModel]):
    """Pin the in-process model (tests / programmatic use). ``None``
    unpins and returns control to ``MXNET_COSTMODEL_PATH``."""
    global _ACTIVE, _ACTIVE_PINNED, _ACTIVE_SRC, _ACTIVE_ERR
    with _LOCK:
        _ACTIVE = model
        _ACTIVE_PINNED = model is not None
        _ACTIVE_SRC = None
        _ACTIVE_ERR = None


def active_model() -> Optional[CostModel]:
    """The serving model: pinned one if set, else a lazy, mtime-cached
    load of ``MXNET_COSTMODEL_PATH``. Load failures are remembered (and
    surfaced on /costz) instead of retried every call."""
    global _ACTIVE, _ACTIVE_SRC, _ACTIVE_ERR
    with _LOCK:
        if _ACTIVE_PINNED:
            return _ACTIVE
        path = str(_cfg("MXNET_COSTMODEL_PATH", "") or "")
        if not path:
            _ACTIVE, _ACTIVE_SRC = None, None
            return None
        try:
            mtime = os.stat(path).st_mtime
        except OSError as e:
            _ACTIVE, _ACTIVE_SRC, _ACTIVE_ERR = None, None, str(e)
            return None
        if _ACTIVE_SRC == (path, mtime):
            return _ACTIVE
        try:
            _ACTIVE = load(path)
            _ACTIVE_ERR = None
        except CostModelError as e:
            _ACTIVE = None
            _ACTIVE_ERR = str(e)
        _ACTIVE_SRC = (path, mtime)
        return _ACTIVE


def prior_enabled() -> bool:
    try:
        return bool(_cfg("MXNET_COSTMODEL_PRIOR", True))
    except Exception:
        return True


def _recent_compile_index() -> Dict[Any, Dict]:
    try:
        from . import compile_ledger
        return _compile_index(compile_ledger.recent(512))
    except Exception:
        return {}


def predict_step_us(key: Optional[Dict[str, Any]], site: str = "",
                    rows: Optional[float] = None) -> Optional[float]:
    """Predicted step wall (us) for a trigger key, or None without a
    usable model. Joins the in-memory compile ring for program features
    (warmup compiles before any step executes, so the join hits)."""
    try:
        m = active_model()
        if m is None:
            return None
        comp = _join(key or {}, _recent_compile_index())
        v = m.predict("step_us", featurize(key, site, rows=rows, comp=comp))
        return v
    except Exception:
        return None


def predict_compile_s(key: Optional[Dict[str, Any]],
                      site: str = "") -> Optional[float]:
    """Predicted cold-compile wall (s) for a trigger key, or None."""
    try:
        m = active_model()
        if m is None:
            return None
        comp = _join(key or {}, _recent_compile_index())
        return m.predict("compile_s", featurize(key, site, comp=comp))
    except Exception:
        return None


def make_prior(site: str, key_fn: Callable[[int], Dict[str, Any]]
               ) -> Callable[[int], Optional[float]]:
    """A ``StepCostEWMA(prior=...)`` hook: prices bucket -> predicted us
    via the active model, counting prior-priced cold buckets and
    exporting the prediction gauge. ``key_fn`` builds the endpoint's
    trigger key for a bucket (so mesh topology rides along for sharded
    endpoints). Never raises; returns None when no model is active."""
    def prior(bucket: int) -> Optional[float]:
        try:
            if not prior_enabled():
                return None
            v = predict_step_us(key_fn(bucket), site)
            if v is None:
                return None
            _PRIOR_USED.labels(site).inc()
            # bounded: buckets come from the fixed padding ladder
            _PREDICTED_G.labels(
                site, str(bucket)).set(v)  # mxlint: disable=MET301
            return v
        except Exception:
            return None
    return prior


# ---------------------------------------------------------------------------
# step records + residual drift
# ---------------------------------------------------------------------------

_STEP_COUNTS: Dict[Tuple[str, str], int] = {}


def _should_log_step(n: int) -> bool:
    # every observation while rare (powers of two), one per 256 steady-state
    return n & (n - 1) == 0 or n % 256 == 0


class _SiteResiduals:
    """Latched residual drift state for one site (perf_sentinel pattern:
    streak of out-of-band ratios -> one flight event per episode)."""

    __slots__ = ("band", "sustain_n", "streak", "latched", "fired",
                 "buckets")

    def __init__(self, band: float, sustain_n: int):
        self.band = max(1.01, float(band))
        self.sustain_n = max(1, int(sustain_n))
        self.streak = 0
        self.latched = False
        self.fired = 0
        self.buckets: Dict[int, Dict[str, float]] = {}


_RESIDUALS: Dict[str, _SiteResiduals] = {}


def on_step_observed(site: str, key: Optional[Dict[str, Any]], bucket: int,
                     measured_us: float, rows: Optional[float] = None,
                     prior_us: Optional[float] = None):
    """The measured side of predicted-vs-measured. Called from endpoint /
    decode execute paths after each observed step: appends a rate-limited
    ``kind="step"`` ledger record (the training corpus), and when a prior
    exists for this bucket, exports the residual ratio and feeds the
    latched drift detector. Never raises."""
    try:
        _maybe_record_step(site, key, bucket, measured_us, rows)
    except Exception:
        pass
    try:
        if prior_us and prior_us > 0 and measured_us > 0:
            _observe_residual(site, int(bucket), float(prior_us),
                              float(measured_us))
    except Exception:
        pass


def _maybe_record_step(site, key, bucket, measured_us, rows):
    from . import compile_ledger
    d = compile_ledger.ledger_dir()
    if not d or not bool(_cfg("MXNET_COSTMODEL_STEP_RECORDS", True)):
        return
    key = {str(k): v for k, v in (key or {}).items()}
    ck = (str(site), _key_id(key))
    with _LOCK:
        n = _STEP_COUNTS.get(ck, 0) + 1
        _STEP_COUNTS[ck] = n
    if not _should_log_step(n):
        return
    rec = {
        "kind": "step", "ts": round(time.time(), 3), "pid": os.getpid(),
        "site": str(site), "key": key,
        "step_us": round(float(measured_us), 3), "n": n,
    }
    if rows:
        rec["rows"] = float(rows)
    compile_ledger._append_jsonl(d, rec)


def _observe_residual(site: str, bucket: int, prior_us: float,
                      measured_us: float):
    ratio = measured_us / prior_us
    # bounded: buckets come from the fixed padding ladder
    _RESIDUAL_G.labels(
        site, str(bucket)).set(ratio)  # mxlint: disable=MET301
    fire = None
    with _LOCK:
        st = _RESIDUALS.get(site)
        if st is None:
            st = _RESIDUALS[site] = _SiteResiduals(
                band=float(_cfg("MXNET_COSTMODEL_DRIFT_BAND", 4.0)),
                sustain_n=int(_cfg("MXNET_COSTMODEL_DRIFT_SUSTAIN_N", 8)))
        b = st.buckets.setdefault(bucket, {"n": 0.0, "measured_us": 0.0})
        b["n"] += 1
        b["predicted_us"] = prior_us
        prev = b["measured_us"]
        b["measured_us"] = measured_us if b["n"] <= 1 else \
            prev + 0.25 * (measured_us - prev)
        b["ratio"] = ratio
        out = ratio > st.band or ratio < 1.0 / st.band
        if out:
            st.streak += 1
            if not st.latched and st.streak >= st.sustain_n:
                # one event per episode: latch until a sample returns
                # in-band
                st.latched = True
                st.fired += 1
                fire = dict(site=site, bucket=bucket,
                            predicted_us=round(prior_us, 3),
                            measured_us=round(measured_us, 3),
                            ratio=round(ratio, 4), band=st.band,
                            sustain_n=st.sustain_n, episode=st.fired)
        else:
            st.streak = 0
            st.latched = False
    if fire is not None:
        try:
            _DRIFT_C.labels(site).inc()
            m = active_model()
            fire["model_version"] = m.version if m else None
            from . import flight as _flight
            _flight.trigger("cost_model_drift", **fire)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# corpus export / introspection
# ---------------------------------------------------------------------------

def read_steps(d: Optional[str] = None) -> List[Dict]:
    """All ``kind="step"`` records from a ledger directory."""
    from . import compile_ledger
    return [r for r in compile_ledger.read_ledger(d)
            if r.get("kind") == "step"]


def export_rows(records: Sequence[Dict]
                ) -> Tuple[List[str], List[Dict[str, Any]]]:
    """The featurized corpus as flat rows for --features export:
    (ordered column names, row dicts). Meta columns first, then the
    sorted union of feature names."""
    corpus = build_corpus(records)
    names = sorted({n for s in corpus for n in s["x"]})
    meta = ["target", "y", "site", "endpoint", "bucket"]
    rows = []
    for s in corpus:
        row = {m: s.get(m) for m in meta}
        row.update({n: s["x"].get(n, 0.0) for n in names})
        rows.append(row)
    return meta + names, rows


def snapshot() -> Dict[str, Any]:
    """Everything /costz renders: active model identity + per-target
    metrics, load error if any, and per-site residual state."""
    with _LOCK:
        err = _ACTIVE_ERR
        res = {
            site: {
                "band": st.band, "sustain_n": st.sustain_n,
                "streak": st.streak, "latched": st.latched,
                "fired": st.fired,
                "buckets": {
                    str(b): {k: (round(v, 3) if isinstance(v, float) else v)
                             for k, v in info.items()}
                    for b, info in sorted(st.buckets.items())},
            }
            for site, st in sorted(_RESIDUALS.items())
        }
    m = active_model()
    info = None
    if m is not None:
        info = {
            "version": m.version,
            "schema": m.schema,
            "created": m.payload.get("created"),
            "source": m.payload.get("source"),
            "n_samples": m.payload.get("n_samples"),
            "targets": {t: m.metrics(t)
                        for t in m.payload.get("targets", {})},
        }
    return {
        "model": info,
        "error": err,
        "path": str(_cfg("MXNET_COSTMODEL_PATH", "") or "") or None,
        "prior_enabled": prior_enabled(),
        "residuals": res,
    }


def reset():
    """Test hook: drop the active model, residual state and step-record
    rate limiter."""
    global _ACTIVE, _ACTIVE_PINNED, _ACTIVE_SRC, _ACTIVE_ERR
    with _LOCK:
        _ACTIVE = None
        _ACTIVE_PINNED = False
        _ACTIVE_SRC = None
        _ACTIVE_ERR = None
        _RESIDUALS.clear()
        _STEP_COUNTS.clear()
