"""Goodput ledger — where did this process's wall clock actually go?

Attributes elapsed wall time into EXCLUSIVE buckets, entirely from series
the registry already carries (no new instrumentation on any hot path):

  compile           mxtpu_compile_wall_seconds_total (compile-ledger wall
                    seconds; falls back to mxtpu_serving_compile_seconds_total
                    when the ledger saw nothing) + executable-cache
                    deserialize seconds
  data_wait         mxtpu_dataloader_wait_us histogram sum
  step              mxtpu_train_step_latency_us + mxtpu_serving_step_latency_us
                    + mxtpu_decode_step_us + mxtpu_decode_prefill_us sums —
                    the bucket that IS goodput
  checkpoint_flush  mxtpu_checkpoint_save_duration_us +
                    mxtpu_preempt_flush_duration_us sums
  retry_recovery    mxtpu_span_duration_us sums for the recovery span names
                    (checkpoint.restore, resilience.retry, serving.failover)
  drain             mxtpu_span_duration_us{name="serving.drain"} (the span
                    InferenceServer.stop opens around its drain wait)
  idle              the residual: elapsed wall minus every active bucket,
                    clamped at zero

Invariants (pinned by tier-1 tests): buckets are exclusive — each comes
from disjoint source series; if the active sum exceeds elapsed wall
(overlapped threads, clock skew) every active bucket is scaled down
proportionally so the total reconciles; idle is the residual and never
negative — so the buckets always sum to elapsed wall exactly.

:func:`account` publishes the attribution as
``mxtpu_goodput_seconds_total{bucket=...}`` (monotone: each call emits the
delta since the previous accounting) plus the ``mxtpu_goodput_wall_seconds``
gauge, so snapshot dumps carry their own goodput table and
``tools/fleet_report.py`` can verify buckets-vs-wall offline.

:func:`utilization` is the roofline half: per-executable achieved FLOP/s
and bytes/s — compile-ledger ``cost_analysis`` flops/bytes over the
observed mean step time for that executable's site — optionally as a
fraction of ``MXNET_GOODPUT_PEAK_FLOPS`` / ``MXNET_GOODPUT_PEAK_GBS``.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from .metrics import REGISTRY

__all__ = ["BUCKETS", "attribute", "account", "utilization", "reset",
           "wall_seconds"]

BUCKETS = ("compile", "data_wait", "step", "checkpoint_flush",
           "retry_recovery", "drain", "idle")

# span names whose durations count as recovery / drain time
RECOVERY_SPANS = ("checkpoint.restore", "resilience.retry",
                  "serving.failover")
DRAIN_SPANS = ("serving.drain",)

_GOODPUT = REGISTRY.counter(
    "mxtpu_goodput_seconds_total",
    "Process wall time attributed to exclusive buckets (compile / "
    "data_wait / step / checkpoint_flush / retry_recovery / drain / idle). "
    "Buckets sum to mxtpu_goodput_wall_seconds; step is the goodput share.",
    labelnames=("bucket",))
_WALL = REGISTRY.gauge(
    "mxtpu_goodput_wall_seconds",
    "Elapsed wall seconds the goodput buckets attribute (since process "
    "start / the last goodput.reset()).")

_LOCK = threading.Lock()
_T0 = time.perf_counter()
_LAST: Dict[str, float] = {}       # bucket -> absolute seconds last emitted
_LAST_WALL = 0.0


def _cfg(name, default):
    try:
        from .. import config
        return config.get(name, default)
    except Exception:
        return default


def _fam_sum(snap: Dict, name: str, value_key: str = "value",
             label_filter: Optional[Dict[str, str]] = None) -> float:
    fam = (snap.get("metrics") or {}).get(name)
    if not fam:
        return 0.0
    total = 0.0
    for s in fam.get("series", []):
        if label_filter:
            labels = s.get("labels") or {}
            if any(labels.get(k) != v for k, v in label_filter.items()):
                continue
        total += float(s.get(value_key, 0.0) or 0.0)
    return total


def _span_sum_s(snap: Dict, names) -> float:
    """Summed duration (seconds) of mxtpu_span_duration_us series whose
    ``name`` label is in ``names``."""
    fam = (snap.get("metrics") or {}).get("mxtpu_span_duration_us")
    if not fam:
        return 0.0
    total = 0.0
    for s in fam.get("series", []):
        if (s.get("labels") or {}).get("name") in names:
            total += float(s.get("sum", 0.0) or 0.0)
    return total / 1e6


def attribute(snap: Dict, elapsed_s: Optional[float]) -> Dict[str, float]:
    """Pure attribution: registry snapshot + elapsed wall -> bucket dict.

    With ``elapsed_s`` None only the active buckets are reported (idle 0,
    no reconciliation) — the best an offline reader without a wall anchor
    can do.
    """
    compile_s = _fam_sum(snap, "mxtpu_compile_wall_seconds_total")
    if not compile_s:
        compile_s = _fam_sum(snap, "mxtpu_serving_compile_seconds_total")
    compile_s += _fam_sum(snap, "mxtpu_exec_cache_deserialize_seconds_total")
    buckets = {
        "compile": compile_s,
        "data_wait": _fam_sum(snap, "mxtpu_dataloader_wait_us", "sum") / 1e6,
        "step": (_fam_sum(snap, "mxtpu_train_step_latency_us", "sum")
                 + _fam_sum(snap, "mxtpu_serving_step_latency_us", "sum")
                 + _fam_sum(snap, "mxtpu_decode_step_us", "sum")
                 + _fam_sum(snap, "mxtpu_decode_prefill_us", "sum")) / 1e6,
        "checkpoint_flush":
            (_fam_sum(snap, "mxtpu_checkpoint_save_duration_us", "sum")
             + _fam_sum(snap, "mxtpu_preempt_flush_duration_us", "sum")) / 1e6,
        "retry_recovery": _span_sum_s(snap, RECOVERY_SPANS),
        "drain": _span_sum_s(snap, DRAIN_SPANS),
    }
    active = sum(buckets.values())
    if elapsed_s is None:
        buckets["idle"] = 0.0
        return buckets
    elapsed_s = max(0.0, float(elapsed_s))
    if active > elapsed_s and active > 0.0:
        # overlapped work (pipelined prep/step threads, N replicas in one
        # process) can book more active seconds than one wall clock holds;
        # scale proportionally so the attribution still reconciles
        scale = elapsed_s / active
        for k in buckets:
            buckets[k] *= scale
        active = elapsed_s
    buckets["idle"] = max(0.0, elapsed_s - active)
    return buckets


def wall_seconds() -> float:
    """Elapsed wall this process's goodput attributes over."""
    return time.perf_counter() - _T0


def account(snap: Optional[Dict] = None) -> Dict[str, float]:
    """Attribute wall time now and publish the result as metrics.

    Emits the per-bucket DELTA since the previous accounting into
    ``mxtpu_goodput_seconds_total{bucket=...}`` (so the counter stays
    monotone and its absolute value equals the current attribution) and
    refreshes ``mxtpu_goodput_wall_seconds``. Returns the absolute bucket
    attribution. A bucket whose absolute value shrank (proportional
    rescaling between calls) emits no negative delta — the counter keeps
    its high-water value and re-converges on the next call.
    """
    global _LAST_WALL
    if snap is None:
        snap = REGISTRY.snapshot()
    elapsed = wall_seconds()
    buckets = attribute(snap, elapsed)
    with _LOCK:
        for bucket, absolute in buckets.items():
            delta = absolute - _LAST.get(bucket, 0.0)
            if delta > 0:
                _GOODPUT.labels(bucket).inc(delta)
                _LAST[bucket] = absolute
        _WALL.set(elapsed)
        _LAST_WALL = elapsed
    return buckets


def utilization(snap: Optional[Dict] = None,
                records: Optional[List[Dict]] = None) -> List[Dict]:
    """Per-executable achieved-vs-peak utilization estimates.

    For every distinct compile-ledger fingerprint with ``cost_analysis``
    flops/bytes, the achieved rate is flops (bytes) divided by the observed
    mean step time of that executable's site — serving sites read their
    endpoint's ``mxtpu_serving_step_latency_us`` mean, train sites the
    ``mxtpu_train_step_latency_us`` mean. With MXNET_GOODPUT_PEAK_FLOPS /
    MXNET_GOODPUT_PEAK_GBS set, each row also carries the roofline
    fraction. Rows without an observed step (never executed under load)
    are skipped.
    """
    if snap is None:
        snap = REGISTRY.snapshot()
    if records is None:
        from . import compile_ledger
        records = compile_ledger.recent()
    peak_flops = float(_cfg("MXNET_GOODPUT_PEAK_FLOPS", 0.0) or 0.0)
    peak_gbs = float(_cfg("MXNET_GOODPUT_PEAK_GBS", 0.0) or 0.0)

    def _mean_us(name, label_filter=None):
        fam = (snap.get("metrics") or {}).get(name)
        if not fam:
            return 0.0
        n = total = 0.0
        for s in fam.get("series", []):
            if label_filter:
                labels = s.get("labels") or {}
                if any(labels.get(k) != v for k, v in label_filter.items()):
                    continue
            n += float(s.get("count", 0))
            total += float(s.get("sum", 0.0))
        return (total / n) if n else 0.0

    rows: List[Dict] = []
    seen = set()
    for rec in records:
        fp = rec.get("fingerprint")
        flops = rec.get("flops")
        nbytes = rec.get("bytes_accessed")
        if not fp or fp in seen or (not flops and not nbytes):
            continue
        seen.add(fp)
        site = rec.get("site", "?")
        key = rec.get("key") or {}
        if site == "serving_bucket" and key.get("endpoint"):
            step_us = _mean_us("mxtpu_serving_step_latency_us",
                               {"endpoint": str(key["endpoint"])})
        elif site.startswith("train"):
            step_us = _mean_us("mxtpu_train_step_latency_us")
        else:
            step_us = 0.0
        if not step_us:
            continue
        step_s = step_us / 1e6
        row = {"fingerprint": fp[:12], "site": site, "key": key,
               "step_mean_s": round(step_s, 6)}
        if flops:
            row["flops"] = float(flops)
            row["achieved_flops_s"] = float(flops) / step_s
            if peak_flops > 0:
                row["flops_frac_of_peak"] = round(
                    row["achieved_flops_s"] / peak_flops, 4)
        if nbytes:
            row["bytes_accessed"] = float(nbytes)
            row["achieved_bytes_s"] = float(nbytes) / step_s
            if peak_gbs > 0:
                row["bytes_frac_of_peak"] = round(
                    row["achieved_bytes_s"] / peak_gbs, 4)
        rows.append(row)
    return rows


def reset(t0: Optional[float] = None):
    """Restart the attribution clock (tests; a scripted run sets its own t0
    on the perf_counter timebase). Also zeroes the emitted counter series —
    the ledger's invariant is "counter == the current attribution since the
    last reset", so a fresh clock must mean a fresh ledger (otherwise the
    next :func:`account` would re-add absolutes on top of the old ones and
    a dump would no longer reconcile against the wall gauge)."""
    global _T0, _LAST_WALL
    with _LOCK:
        _T0 = time.perf_counter() if t0 is None else float(t0)
        _LAST.clear()
        _LAST_WALL = 0.0
        for _labels, child in _GOODPUT._series():
            child._value = 0.0
        _WALL.set(0.0)
