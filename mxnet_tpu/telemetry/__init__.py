"""mxnet_tpu.telemetry — unified metrics registry + cross-layer tracing.

The process-wide observability layer (ISSUE r7; the operability counterpart
to the serving layer): every hot subsystem — eager jit cache, serving
endpoint/server, ParallelTrainStep, kvstore, DataLoader, and the resilience
layer (retry/checkpoint/watchdog/circuit-breaker, ISSUE r8:
``mxtpu_retries_total``, ``mxtpu_checkpoint_*``, ``mxtpu_circuit_state``,
``checkpoint.save``/``checkpoint.restore`` spans) — reports into ONE
thread-safe registry, exported two ways:

    from mxnet_tpu import telemetry

    telemetry.snapshot()          # whole registry as one JSON-able dict
    telemetry.prometheus_text()   # Prometheus text exposition (scrapable)
    telemetry.periodic_logger(10) # background heartbeat + snapshot file

    with telemetry.span("app.request", user="u1") as s:
        ...                       # nested spans share s.trace_id

Metric families (full catalog: OBSERVABILITY.md) are created by subsystems
at import time via get-or-create, bump pre-bound label children on the hot
path, and are linted at registration (``^mxtpu_[a-z0-9_]+$``, unique) so a
rename can never silently break a dashboard. Spans nest, carry a trace id
across threads (a serving request's id survives queue → batch assembly →
compiled device step), and feed BOTH the profiler's chrome trace (when a
session runs) and the registry's duration histograms (always).

Relationship to ``profiler``: the profiler answers "where did this
microsecond go" (per-op events, XPlane device traces) for a bounded capture
window; telemetry answers "is the fleet healthy" (counters/gauges/quantiles,
negligible overhead, always on). Spans bridge the two — the same trace id
appears in chrome-trace ``args`` and in metric label space.
"""
from __future__ import annotations

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry, REGISTRY,
                      DEFAULT_BUCKETS, METRIC_NAME_RE)
from .flight import FlightRecorder, event
from .tracing import (Span, span, current_span, current_trace_id,
                      new_trace_id, spool_flush, read_spool, journey)
from .reporter import (PeriodicReporter, periodic_logger, dump,
                       sample_device_memory, summary_line)
from .debug_server import DebugServer
from .slo import SLOMonitor
from . import flight, debug_server, slo
from . import compile_ledger, costmodel, memstats, perf_sentinel
from . import fleet, goodput

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "DEFAULT_BUCKETS", "METRIC_NAME_RE",
    "Span", "span", "current_span", "current_trace_id", "new_trace_id",
    "spool_flush", "read_spool", "journey",
    "PeriodicReporter", "periodic_logger", "dump", "sample_device_memory",
    "summary_line",
    "FlightRecorder", "event", "flight",
    "DebugServer", "debug_server",
    "SLOMonitor", "slo",
    "compile_ledger", "costmodel", "memstats", "perf_sentinel", "fleet",
    "goodput",
    "counter", "gauge", "histogram", "snapshot", "snapshot_json",
    "prometheus_text", "lint_names",
]


# -- registry conveniences (the surface subsystems and users actually call) --

def counter(name, help="", labelnames=()) -> Counter:
    """Get-or-create a Counter in the default registry."""
    return REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()) -> Gauge:
    """Get-or-create a Gauge in the default registry."""
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None) -> Histogram:
    """Get-or-create a Histogram (fixed log-spaced default buckets)."""
    return REGISTRY.histogram(name, help, labelnames, buckets)


def _refresh_memory_gauges():
    """On-demand gauge refresh for the operator's single-pane exports:
    device memory_stats plus the memstats holder/attribution gauges (the
    scrape IS the sampling tick — no background thread required)."""
    sample_device_memory()
    try:
        memstats.reconcile()
    except Exception:
        pass


def snapshot() -> dict:
    """Whole-registry snapshot as one JSON-able dict (refreshes device
    memory + attribution gauges first — the snapshot is the operator's
    single pane)."""
    _refresh_memory_gauges()
    return REGISTRY.snapshot()


def snapshot_json(**dumps_kw) -> str:
    import json as _json
    return _json.dumps(snapshot(), **dumps_kw)


def prometheus_text() -> str:
    """Prometheus text exposition of the default registry."""
    _refresh_memory_gauges()
    return REGISTRY.prometheus_text()


def lint_names() -> list:
    """Metric-name lint violations in the default registry (empty = clean)."""
    return REGISTRY.lint_names()
