"""Alias module: mx.init (the reference exposes initializer as mx.init too)."""
from .initializer import *  # noqa: F401,F403
from .initializer import Initializer, Xavier, Uniform, Normal, Constant, Zero, One  # noqa: F401
