"""Data iterators (parity: python/mxnet/io/ + src/io/ C++ iterators — DataIter,
DataBatch, DataDesc, NDArrayIter, MNISTIter, CSVIter, ImageRecordIter,
PrefetchingIter, ResizeIter).

TPU-native: the reference's threaded decode→augment→batch→prefetch pipeline
(iter_prefetcher.h) maps to a background-thread prefetcher that overlaps host
batching with async device transfer (PJRT DMA).
"""
from __future__ import annotations

import collections
import os
from collections import namedtuple

import numpy as onp

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "MNISTIter",
           "CSVIter", "LibSVMIter", "ImageRecordIter", "PrefetchingIter",
           "ResizeIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype="float32", layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad=0, index=None, bucket_key=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Base iterator (io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    if data is None:
        return []
    if isinstance(data, (onp.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        data = {f"{default_name}{'_' + str(i) if i else ''}": d
                for i, d in enumerate(data)} if len(data) > 1 \
            else ({default_name: data[0]} if data else {})
    out = []
    for k, v in data.items():
        if not isinstance(v, NDArray):
            v = NDArray(onp.asarray(v))
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (io.py NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        self._shuffle = shuffle
        self._last_batch_handle = last_batch_handle
        self._order = onp.arange(self.num_data)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], str(v.dtype))
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], str(v.dtype))
                for k, v in self.label]

    def reset(self):
        if self._shuffle:
            onp.random.shuffle(self._order)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self._last_batch_handle == "roll_over":
            return self.cursor + self.batch_size <= self.num_data
        if self._last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _slice(self, arrays):
        out = []
        idx = self._order[self.cursor:self.cursor + self.batch_size]
        pad = self.getpad()
        if pad:
            idx = onp.concatenate([idx, self._order[:pad]])
        for _, v in arrays:
            out.append(NDArray(v.data[idx]))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self):
        if self._last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class MNISTIter(NDArrayIter):
    """MNIST iterator (src/io/iter_mnist.cc parity): reads idx files or synthesizes
    deterministic data in zero-egress environments."""

    def __init__(self, image=None, label=None, batch_size=128, shuffle=True,
                 flat=False, silent=False, seed=None, input_shape=None, **kwargs):
        from .gluon.data.vision.datasets import MNIST
        train = image is None or "train" in str(image)
        root = os.path.dirname(os.path.expanduser(image)) if image \
            else os.path.join("~", ".mxnet", "datasets", "mnist")
        ds = MNIST(root=root, train=train)
        data = ds._data.asnumpy().astype(onp.float32) / 255.0
        labels = ds._label
        if flat:
            data = data.reshape(len(data), -1)
        else:
            data = data.transpose(0, 3, 1, 2)
        super().__init__(data, labels.astype(onp.float32), batch_size, shuffle)


class CSVIter(DataIter):
    """CSV iterator (src/io/iter_csv.cc parity)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = onp.loadtxt(data_csv, delimiter=",", dtype=onp.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = onp.loadtxt(label_csv, delimiter=",", dtype=onp.float32) \
            if label_csv else onp.zeros(len(data), onp.float32)
        self._inner = NDArrayIter(data, label, batch_size)

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


class LibSVMIter(DataIter):
    """LibSVM-format iterator producing CSR batches (src/io/iter_libsvm.cc
    parity): each line is ``label idx:val idx:val ...``; batches carry a
    CSRNDArray for data (sparse stays sparse through the pipeline, the
    FInferStorageType discipline of the reference's sparse iterators)."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=(1,), batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        self._feature_dim = int(data_shape[0]) if hasattr(data_shape, "__len__") \
            else int(data_shape)
        vals, idxs, ptr, labels = [], [], [0], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    idxs.append(int(i))
                    vals.append(float(v))
                ptr.append(len(idxs))
        self._vals = onp.asarray(vals, onp.float32)
        self._idxs = onp.asarray(idxs, onp.int32)
        self._ptr = onp.asarray(ptr, onp.int64)
        self._labels = onp.asarray(labels, onp.float32)
        if label_libsvm:
            # label file is ALSO libsvm-format (first token per line), like
            # iter_libsvm.cc's label_libsvm param
            lab = []
            with open(label_libsvm) as f:
                for line in f:
                    parts = line.split()
                    if parts:
                        lab.append(float(parts[0]))
            self._labels = onp.asarray(lab, onp.float32)
        if len(self._labels) != len(self._ptr) - 1:
            raise ValueError(
                f"LibSVMIter: {len(self._ptr) - 1} data rows but "
                f"{len(self._labels)} labels")
        self._round_batch = round_batch
        self._n = len(self._labels)
        self._cursor = 0
        self.provide_data = [DataDesc("data", (batch_size, self._feature_dim))]
        self.provide_label = [DataDesc("softmax_label", (batch_size,))]

    def reset(self):
        self._cursor = 0

    def iter_next(self):
        return self._cursor < self._n

    def next(self):
        from .sparse import CSRNDArray
        if not self.iter_next():
            raise StopIteration
        b = self.batch_size
        lo = self._cursor
        hi = min(lo + b, self._n)
        pad = b - (hi - lo)
        if pad and not self._round_batch:
            b = hi - lo  # round_batch=False: emit the short final batch
            pad = 0
        rows = list(range(lo, hi)) + list(range(pad))  # wrap from the start
        ptr = [0]
        vals, idxs = [], []
        for r in rows:
            s, e = self._ptr[r], self._ptr[r + 1]
            vals.append(self._vals[s:e])
            idxs.append(self._idxs[s:e])
            ptr.append(ptr[-1] + (e - s))
        csr = CSRNDArray(onp.concatenate(vals) if vals else onp.zeros(0),
                         onp.concatenate(idxs) if idxs else onp.zeros(0),
                         onp.asarray(ptr, onp.int64),
                         (b, self._feature_dim))
        label = NDArray(self._labels[[min(r, self._n - 1) for r in rows]])
        self._cursor = hi
        return DataBatch(data=[csr], label=[label], pad=pad)


class NativeImageRecordIter(DataIter):
    """C++ decode→augment→batch→prefetch pipeline over RecordIO
    (mxnet_tpu/native/image_pipeline.cc; iter_image_recordio_2.cc analog)."""

    def __init__(self, path_imgrec, data_shape, batch_size, shuffle=False,
                 rand_crop=False, rand_mirror=False, mean=None, std=None,
                 preprocess_threads=4, label_width=1, seed=0, prefetch_buffer=4,
                 data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        import ctypes
        from . import native
        lib = native.get_lib()
        if lib is None or not hasattr(lib, "mxtpu_impipe_create"):
            raise MXNetError("native image pipeline unavailable: "
                             f"{native.build_error()}")
        self._lib = lib
        c, h, w = data_shape
        mean_arr = (ctypes.c_float * 3)(*(mean if mean is not None
                                          else (0.0, 0.0, 0.0)))
        std_arr = (ctypes.c_float * 3)(*(std if std is not None
                                         else (1.0, 1.0, 1.0)))
        self._h = lib.mxtpu_impipe_create(
            str(path_imgrec).encode(), batch_size, c, h, w, int(shuffle),
            preprocess_threads, int(rand_mirror), int(rand_crop), mean_arr,
            std_arr, label_width, seed, prefetch_buffer)
        if not self._h:
            raise MXNetError(f"could not open {path_imgrec}")
        self._shape = (batch_size,) + tuple(data_shape)
        self._label_width = label_width
        self._data_name, self._label_name = data_name, label_name
        self.provide_data = [DataDesc(data_name, self._shape)]
        lshape = (batch_size,) if label_width == 1 else (batch_size, label_width)
        self.provide_label = [DataDesc(label_name, lshape)]

    def reset(self):
        self._lib.mxtpu_impipe_reset(self._h)

    def next(self):
        import ctypes
        data = onp.zeros(self._shape, "float32")
        label = onp.zeros((self._shape[0], self._label_width), "float32")
        n = self._lib.mxtpu_impipe_next(
            self._h, data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            label.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if n == 0:
            raise StopIteration
        from .ndarray import array
        lab = label[:, 0] if self._label_width == 1 else label
        return DataBatch(data=[array(data)], label=[array(lab)],
                         pad=self._shape[0] - n)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.mxtpu_impipe_destroy(self._h)
                self._h = None
        except Exception:
            pass


def ImageRecordIter(path_imgrec=None, data_shape=(3, 224, 224), batch_size=128,
                    shuffle=False, rand_crop=False, rand_mirror=False, mean_r=0,
                    mean_g=0, mean_b=0, std_r=1, std_g=1, std_b=1,
                    preprocess_threads=None, prefetch_buffer=4, seed=0,
                    **kwargs):
    """ImageRecordIter (src/io/iter_image_recordio_2.cc:887 parity): RecordIO
    decode→augment→batch with thread prefetch. Uses the native C++ pipeline
    when built; otherwise the Python ImageIter + PrefetchingIter stack.
    Default thread count honors MXNET_CPU_PRIORITY_NTHREADS."""
    from . import config
    if preprocess_threads is None:
        preprocess_threads = config.get("MXNET_CPU_PRIORITY_NTHREADS")
    mean = onp.array([mean_r, mean_g, mean_b]) if (mean_r or mean_g or mean_b) \
        else None
    std = onp.array([std_r, std_g, std_b]) if (std_r != 1 or std_g != 1
                                               or std_b != 1) else None
    from . import native
    if native.available() and hasattr(native.get_lib(), "mxtpu_impipe_create"):
        return NativeImageRecordIter(
            path_imgrec, data_shape, batch_size, shuffle=shuffle,
            rand_crop=rand_crop, rand_mirror=rand_mirror, mean=mean, std=std,
            preprocess_threads=preprocess_threads, seed=seed,
            prefetch_buffer=prefetch_buffer,
            label_width=kwargs.get("label_width", 1))
    from .image import ImageIter, CreateAugmenter
    aug = CreateAugmenter(data_shape, rand_crop=rand_crop, rand_mirror=rand_mirror,
                          mean=mean, std=std)
    inner = ImageIter(batch_size, data_shape, path_imgrec=path_imgrec,
                      shuffle=shuffle, aug_list=aug,
                      seed=seed if shuffle else None, **kwargs)
    return PrefetchingIter(inner, prefetch=prefetch_buffer)


class PrefetchingIter(DataIter):
    """Prefetcher scheduled on the dependency engine (io.py PrefetchingIter /
    iter_prefetcher.h over the threaded engine).

    Each batch fetch is a host task pushed to the engine (native/engine.cc
    worker pool when built, Python fallback otherwise) with two write vars:
    a per-slot var that ``next()`` waits on, and a shared iterator var whose
    per-var FIFO write discipline serializes the underlying iterator across
    the pool — the same ordering mechanism the reference engine uses for
    mutable NDArray writes."""

    def __init__(self, iters, rename_data=None, rename_label=None, prefetch=2):
        super().__init__()
        from . import engine as engine_mod
        self._iter = iters if not isinstance(iters, list) else iters[0]
        self._prefetch = max(1, prefetch)
        self._engine = engine_mod.get_engine()
        self._slots = None
        self._iter_var = self._engine.new_var()
        # fixed ring of slot vars, reused round-robin: engine vars live for
        # the engine's lifetime, so per-batch allocation would leak over long
        # runs; a slot var is only rescheduled after next() waited on it
        self._slot_vars = [self._engine.new_var()
                           for _ in range(self._prefetch)]
        self._next_slot = 0
        self._done = False
        self.reset()

    def _schedule(self):
        if self._done:
            return
        var = self._slot_vars[self._next_slot]
        self._next_slot = (self._next_slot + 1) % len(self._slot_vars)
        cell = {}

        def task(cell=cell):
            try:
                cell["batch"] = self._iter.next()
            except StopIteration:
                cell["end"] = True
            except Exception as e:  # noqa: BLE001 — delivered at next()
                cell["error"] = e

        self._engine.push(task, write_vars=(var, self._iter_var))
        self._slots.append((var, cell))

    def reset(self):
        if self._slots:
            # drain in-flight tasks before touching the inner iterator
            self._engine.wait_for_var(self._iter_var)
        self._iter.reset()
        self._done = False
        self._slots = collections.deque()
        for _ in range(self._prefetch):
            self._schedule()

    def next(self):
        if not self._slots:
            raise StopIteration
        var, cell = self._slots.popleft()
        self._engine.wait_for_var(var)
        if "error" in cell:
            self._done = True
            raise cell["error"]
        if "end" in cell:
            self._done = True
            raise StopIteration
        self._schedule()
        return cell["batch"]


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration
