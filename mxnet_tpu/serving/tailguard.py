"""Tail-tolerance defense layer: deadlines, hedges, retry budgets, brownout.

Four mechanisms that turn the fleet's isolated per-tier defenses into one
coordinated overload-and-tail policy (the bounded-speculation / budgeted-
retry discipline of large-scale serving systems — cf. the distributed
fault-handling design in TensorFlow, arXiv:1605.08695):

**Deadline propagation** — a :class:`Deadline` is minted once at ingress
(FrontDoor.submit, or any tier a client enters at) and the SAME object rides
every hop: pool submit, endpoint queue, batch assembly, per-batch retry,
decode per-token. Every tier decrements the one budget instead of re-deriving
its own, and fails fast with :class:`~.errors.DeadlineExceeded` (bumping
``mxtpu_deadline_exceeded_total{site}``) the moment the budget is gone — a
request that cannot finish in time stops consuming capacity at the earliest
tier that can know.

**Hedged requests** — :class:`HedgePolicy` decides when a pending request is
"late enough" to duplicate onto the second-least-loaded replica: after an
adaptive delay that is the max of the observed p95 pool latency and the cost
model's predicted step cost × ``MXNET_HEDGE_DELAY_FACTOR`` (floored at
``MXNET_HEDGE_DELAY_MIN_MS``). Hedges draw from a token bucket refilled at
``MXNET_HEDGE_BUDGET_RATIO`` tokens per primary submit (default ≤5% of
traffic), so speculation can never amplify an overload: when the bucket is
dry the hedge is skipped and ``mxtpu_hedge_budget_exhausted_total`` latches
the ``hedge_budget_exhausted`` flight trigger. First response wins; the
loser is cancelled and dropped at batch assembly (never mid-step), and both
replicas run identical executables so hedged results are byte-identical to
unhedged ones.

**Retry budgets** — per-tier token buckets (``frontdoor`` resubmit,
``execute`` device-step retry, ``decode`` requeue) gate every retry through
:func:`retry_allowed`. Each unit of real work deposits
``MXNET_RETRY_BUDGET_RATIO`` tokens (min ``MXNET_RETRY_BUDGET_MIN`` so cold
tiers can still retry, cap ``MXNET_RETRY_BUDGET_CAP``); a retry takes one
whole token. Under a retry storm the bucket drains and further retries are
refused — the storm converts into bounded, classified shed instead of
cascading amplification — with ``mxtpu_retry_budget_exhausted_total{tier}``
latching the ``retry_budget_exhausted`` flight trigger once per episode.

**Brownout ladder** — :class:`BrownoutController` watches the SLO monitor's
burn state and degrades the fleet in criticality order, with hysteresis
(``MXNET_BROWNOUT_UP_N`` hot ticks to worsen, ``MXNET_BROWNOUT_DOWN_N``
calm ticks to recover) and one ``brownout_shift`` flight event per
transition:

  level 0  normal service
  level 1  soften: batch timeouts widen ×MXNET_BROWNOUT_TIMEOUT_BOOST
           (bigger batches, better goodput per step) and decode
           ``max_new_tokens`` clamps to MXNET_BROWNOUT_MAX_NEW_TOKENS
  level 2  shed bulk: tenants registered ``tier="bulk"`` are refused at
           admission (ServerOverloadError — retryable, the honest signal)
  level 3  shed bulk+silver: only gold serves — gold is never refused by
           the brownout ladder at any level

The controller is a pure decision core (``tick(now)``): the Autoscaler's
poll loop drives it for free, and chaos drills drive it deterministically
with a stubbed monitor.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .. import config as _config
from .. import telemetry as _telemetry
from ..telemetry import flight as _flight
from .errors import DeadlineExceeded

__all__ = ["Deadline", "DeadlineExceeded", "TokenBucket", "RetryBudgets",
           "RETRY_BUDGETS", "retry_allowed", "retry_deposit", "HedgePolicy",
           "HEDGER", "BrownoutController", "BROWNOUT", "TIER_RANKS"]


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------
_config.register("MXNET_HEDGE_ENABLE", True, bool,
                 "Tail hedging: allow ServingPool.submit to duplicate a "
                 "still-pending request onto the second-least-loaded replica "
                 "after the adaptive hedge delay. First response wins; the "
                 "loser is cancelled at batch assembly. 0 disables hedging "
                 "entirely (pure primary-only routing).")
_config.register("MXNET_HEDGE_BUDGET_RATIO", 0.05, float,
                 "Tail hedging: token-bucket refill per primary submit — the "
                 "steady-state ceiling on hedged traffic as a fraction of "
                 "total (default 5%). A dry bucket skips the hedge and "
                 "latches the hedge_budget_exhausted flight trigger. <= 0 "
                 "disables hedging.")
_config.register("MXNET_HEDGE_DELAY_FACTOR", 2.0, float,
                 "Tail hedging: multiplier on the cost model / EWMA "
                 "predicted step cost when computing the adaptive hedge "
                 "delay (hedge fires only after max(observed p95 latency, "
                 "predicted_step * factor)).")
_config.register("MXNET_HEDGE_DELAY_MIN_MS", 10.0, float,
                 "Tail hedging: floor on the adaptive hedge delay, "
                 "milliseconds — never hedge faster than this however "
                 "cheap the predicted step.")
_config.register("MXNET_RETRY_BUDGET_RATIO", 0.1, float,
                 "Retry budgets: tokens deposited per unit of successful "
                 "work per tier (frontdoor submit, device batch, decode "
                 "step); one retry costs one token, so retries are bounded "
                 "to ~this fraction of real work in steady state. <= 0 "
                 "disables retry budgeting (every retry allowed).")
_config.register("MXNET_RETRY_BUDGET_MIN", 50.0, float,
                 "Retry budgets: floor on each tier's bucket — a cold or "
                 "low-traffic tier can always afford this many retries "
                 "before the ratio takes over.")
_config.register("MXNET_RETRY_BUDGET_CAP", 500.0, float,
                 "Retry budgets: ceiling on each tier's bucket, so a long "
                 "quiet period cannot bank an unbounded retry burst.")
_config.register("MXNET_BROWNOUT_ENABLE", True, bool,
                 "Brownout ladder: let the BrownoutController move off "
                 "level 0 under sustained SLO burn. 0 pins level 0 "
                 "(no degradation ever).")
_config.register("MXNET_BROWNOUT_UP_N", 2, int,
                 "Brownout hysteresis: consecutive burning ticks required "
                 "before the ladder degrades one level (one hot tick never "
                 "sheds).")
_config.register("MXNET_BROWNOUT_DOWN_N", 3, int,
                 "Brownout hysteresis: consecutive calm ticks required "
                 "before the ladder recovers one level (recovery is the "
                 "cautious direction).")
_config.register("MXNET_BROWNOUT_MAX_NEW_TOKENS", 32, int,
                 "Brownout level >= 1: clamp on decode max_new_tokens — "
                 "long generations are the first work shortened under "
                 "brownout, before any request is refused.")
_config.register("MXNET_BROWNOUT_TIMEOUT_BOOST", 4.0, float,
                 "Brownout level >= 1: multiplier on batch timeouts — wider "
                 "assembly windows build fuller batches (better goodput per "
                 "device step) at the cost of per-request latency, spending "
                 "latency headroom before refusing anyone.")


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------
_DEADLINE_C = _telemetry.counter(
    "mxtpu_deadline_exceeded_total",
    "Requests failed fast because their propagated Deadline budget ran out, "
    "by the site that detected it (ingress/pool_submit/queue/assembly/"
    "retry_backoff/decode_token) — the earliest tier that could know, so "
    "expired work stops consuming capacity immediately.",
    labelnames=("site",))
_HEDGES_C = _telemetry.counter(
    "mxtpu_hedge_requests_total",
    "Hedge duplicates launched onto a second replica after the adaptive "
    "delay (the speculation volume; bounded by the hedge token bucket).")
_HEDGE_WINS_C = _telemetry.counter(
    "mxtpu_hedge_wins_total",
    "Hedged requests where the duplicate finished first — tail latency the "
    "hedge actually saved.")
_HEDGE_CANCELLED_C = _telemetry.counter(
    "mxtpu_hedge_cancelled_total",
    "Hedge losers cancelled before occupying device rows (dropped at batch "
    "assembly) — speculation that cost zero device work.")
_HEDGE_WASTED_C = _telemetry.counter(
    "mxtpu_hedge_wasted_total",
    "Hedge losers that had already entered a device batch when the winner "
    "resolved — the duplicate work hedging truly wasted.")
_HEDGE_EXHAUSTED_C = _telemetry.counter(
    "mxtpu_hedge_budget_exhausted_total",
    "Hedges skipped because the hedge token bucket was dry — speculation "
    "refusing to amplify an overload.")
_RETRY_TOKENS_G = _telemetry.gauge(
    "mxtpu_retry_budget_tokens",
    "Live token balance of each tier's retry budget bucket (frontdoor / "
    "execute / decode); zero means further retries are refused until real "
    "work deposits more.",
    labelnames=("tier",))
_RETRY_EXHAUSTED_C = _telemetry.counter(
    "mxtpu_retry_budget_exhausted_total",
    "Retries refused because the tier's budget bucket was dry — a retry "
    "storm converting into bounded shed instead of amplification.",
    labelnames=("tier",))
_BROWNOUT_LEVEL_G = _telemetry.gauge(
    "mxtpu_brownout_level",
    "Current brownout ladder level: 0 normal, 1 soften (timeout boost + "
    "decode clamp), 2 shed bulk, 3 shed bulk+silver (gold always serves).")
_BROWNOUT_TRANSITIONS_C = _telemetry.counter(
    "mxtpu_brownout_transitions_total",
    "Brownout ladder level changes, by direction (degrade / recover); one "
    "brownout_shift flight event accompanies each.",
    labelnames=("direction",))
_BROWNOUT_SHED_C = _telemetry.counter(
    "mxtpu_brownout_shed_total",
    "Requests refused at admission by the brownout ladder, by tenant tier "
    "(gold is never in this count by construction).",
    labelnames=("tier",))


# ---------------------------------------------------------------------------
# deadline propagation
# ---------------------------------------------------------------------------
class Deadline:
    """One end-to-end latency budget, minted at ingress and passed by
    reference through every tier. Absolute expiry on the shared
    ``perf_counter_ns()//1000`` microsecond clock (the clock every serving
    tier already timestamps with), so decrementing is implicit: each tier
    reads ``remaining_us()`` against the same wall.

    ``check(site)`` is the fail-fast hop: raises
    :class:`~.errors.DeadlineExceeded` (and bumps
    ``mxtpu_deadline_exceeded_total{site}``) once the budget is spent.
    """

    __slots__ = ("deadline_us", "born_us")

    def __init__(self, budget_ms: float, now_us: Optional[int] = None):
        self.born_us = _now_us() if now_us is None else int(now_us)
        self.deadline_us = self.born_us + int(float(budget_ms) * 1000.0)

    @classmethod
    def at(cls, deadline_us: int) -> "Deadline":
        """Adopt an absolute expiry already on the shared clock."""
        d = cls.__new__(cls)
        d.born_us = _now_us()
        d.deadline_us = int(deadline_us)
        return d

    def remaining_us(self, now_us: Optional[int] = None) -> int:
        now = _now_us() if now_us is None else now_us
        return self.deadline_us - now

    def remaining_ms(self, now_us: Optional[int] = None) -> float:
        return self.remaining_us(now_us) / 1e3

    def expired(self, now_us: Optional[int] = None) -> bool:
        return self.remaining_us(now_us) <= 0

    def check(self, site: str):
        """Fail fast: raise DeadlineExceeded when the budget is gone."""
        rem = self.remaining_us()
        if rem <= 0:
            _DEADLINE_C.labels(site).inc()
            raise DeadlineExceeded(
                f"deadline exceeded at {site}: budget of "
                f"{(self.deadline_us - self.born_us) / 1e3:.1f} ms overran "
                f"by {-rem / 1e3:.1f} ms")

    def __repr__(self):
        return (f"Deadline(remaining_ms={self.remaining_ms():.1f}, "
                f"deadline_us={self.deadline_us})")


def deadline_expired(site: str, n: int = 1):
    """Account deadline expiries detected without a Deadline object in hand
    (e.g. the batcher dropping expired heads at assembly)."""
    _DEADLINE_C.labels(site).inc(n)


# ---------------------------------------------------------------------------
# token buckets (hedge budget + per-tier retry budgets)
# ---------------------------------------------------------------------------
class TokenBucket:
    """A capped token bucket: ``deposit()`` is driven by units of real work,
    ``take()`` spends one token per speculative/retried unit. No time-based
    refill — the budget is a *fraction of actual traffic*, so an idle system
    banks nothing and a storm cannot outrun its own income."""

    __slots__ = ("_lock", "tokens", "cap")

    def __init__(self, initial: float, cap: float):
        self._lock = threading.Lock()
        self.cap = float(cap)
        self.tokens = min(float(initial), self.cap)

    def deposit(self, amount: float):
        with self._lock:
            self.tokens = min(self.tokens + float(amount), self.cap)

    def take(self, amount: float = 1.0) -> bool:
        with self._lock:
            if self.tokens >= amount:
                self.tokens -= amount
                return True
            return False

    def balance(self) -> float:
        with self._lock:
            return self.tokens


class RetryBudgets:
    """Per-tier retry token buckets with latched exhaustion triggers.

    Tiers are created lazily (``frontdoor`` / ``execute`` / ``decode`` are
    the wired ones). Each bucket starts at — and is floored by re-deposit
    at — ``MXNET_RETRY_BUDGET_MIN`` and capped at ``MXNET_RETRY_BUDGET_CAP``;
    ``on_work`` deposits ``MXNET_RETRY_BUDGET_RATIO`` per unit of real work.
    A ratio <= 0 disables budgeting: every ``allow`` succeeds (the
    pre-budget behavior, so existing retry semantics are opt-in unchanged).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self._latched: Dict[str, bool] = {}

    @staticmethod
    def _ratio() -> float:
        return float(_config.get("MXNET_RETRY_BUDGET_RATIO"))

    def _bucket(self, tier: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(tier)
            if b is None:
                b = TokenBucket(float(_config.get("MXNET_RETRY_BUDGET_MIN")),
                                float(_config.get("MXNET_RETRY_BUDGET_CAP")))
                self._buckets[tier] = b
                self._latched[tier] = False
            return b

    def on_work(self, tier: str, units: float = 1.0):
        """Deposit for real work done at ``tier`` (a submit routed, a batch
        stepped, a decode step advanced)."""
        if self._ratio() <= 0:
            return
        b = self._bucket(tier)
        b.deposit(self._ratio() * units)
        _RETRY_TOKENS_G.labels(tier).set(b.balance())

    def allow(self, tier: str) -> bool:
        """Spend one token for a retry at ``tier``. False means the budget
        is exhausted: the caller must NOT retry (propagate the last error —
        bounded shed). Exhaustion latches one flight trigger per episode;
        a later successful allow re-arms it."""
        if self._ratio() <= 0:
            return True
        b = self._bucket(tier)
        ok = b.take(1.0)
        _RETRY_TOKENS_G.labels(tier).set(b.balance())
        if ok:
            with self._lock:
                self._latched[tier] = False
            return True
        _RETRY_EXHAUSTED_C.labels(tier).inc()
        with self._lock:
            first = not self._latched[tier]
            self._latched[tier] = True
        if first:
            _flight.trigger("retry_budget_exhausted", tier=tier,
                            tokens=round(b.balance(), 3), cap=b.cap)
        return False

    def balance(self, tier: str) -> float:
        return self._bucket(tier).balance()

    def reset(self):
        """Forget every bucket (tests / chaos scenario isolation)."""
        with self._lock:
            self._buckets.clear()
            self._latched.clear()


#: the process-wide registry every wired tier consumes
RETRY_BUDGETS = RetryBudgets()


def retry_deposit(tier: str, units: float = 1.0):
    """Module-level convenience over ``RETRY_BUDGETS.on_work``."""
    RETRY_BUDGETS.on_work(tier, units)


def retry_allowed(tier: str) -> bool:
    """Module-level convenience over ``RETRY_BUDGETS.allow``."""
    return RETRY_BUDGETS.allow(tier)


# ---------------------------------------------------------------------------
# hedging policy
# ---------------------------------------------------------------------------
class HedgePolicy:
    """When (and whether) to duplicate a pending request.

    The delay is adaptive: ``max(observed p95 of recent end-to-end pool
    latencies, predicted_step_us * MXNET_HEDGE_DELAY_FACTOR)``, floored at
    ``MXNET_HEDGE_DELAY_MIN_MS`` — a hedge should fire only when the primary
    is *already late* relative to what this workload usually costs, which is
    exactly the signal the learned cost model prices for cold buckets and
    the latency ring measures for warm ones.
    """

    _RING = 256

    def __init__(self):
        self._lock = threading.Lock()
        self._lat_us: list = []       # ring of recent pool latencies
        self._idx = 0

    @staticmethod
    def enabled() -> bool:
        return bool(_config.get("MXNET_HEDGE_ENABLE")) and \
            float(_config.get("MXNET_HEDGE_BUDGET_RATIO")) > 0.0

    def observe_latency(self, us: float):
        """Feed one completed pool submit's end-to-end latency."""
        with self._lock:
            if len(self._lat_us) < self._RING:
                self._lat_us.append(float(us))
            else:
                self._lat_us[self._idx] = float(us)
                self._idx = (self._idx + 1) % self._RING

    def p95_us(self) -> float:
        with self._lock:
            if not self._lat_us:
                return 0.0
            vals = sorted(self._lat_us)
        return vals[min(len(vals) - 1, int(0.95 * len(vals)))]

    def delay_s(self, predicted_step_us: float = 0.0) -> float:
        """Adaptive hedge delay in seconds for one request."""
        factor = float(_config.get("MXNET_HEDGE_DELAY_FACTOR"))
        floor_us = float(_config.get("MXNET_HEDGE_DELAY_MIN_MS")) * 1000.0
        delay_us = max(self.p95_us(), predicted_step_us * factor, floor_us)
        return delay_us / 1e6

    def reset(self):
        with self._lock:
            self._lat_us.clear()
            self._idx = 0


#: process-wide hedging policy + its budget bucket (lazily floored by knobs)
HEDGER = HedgePolicy()
_HEDGE_BUCKET = TokenBucket(1.0, 64.0)
_HEDGE_LATCH = threading.Event()


def hedge_deposit():
    """One primary submit's worth of hedge budget income."""
    _HEDGE_BUCKET.deposit(float(_config.get("MXNET_HEDGE_BUDGET_RATIO")))


def hedge_allowed() -> bool:
    """Spend one hedge token; False (latching one flight trigger per dry
    episode) refuses the hedge so speculation cannot amplify overload."""
    if _HEDGE_BUCKET.take(1.0):
        _HEDGE_LATCH.clear()
        return True
    _HEDGE_EXHAUSTED_C.inc()
    if not _HEDGE_LATCH.is_set():
        _HEDGE_LATCH.set()
        _flight.trigger("hedge_budget_exhausted",
                        tokens=round(_HEDGE_BUCKET.balance(), 3))
    return False


def hedge_launched():
    _HEDGES_C.inc()


def hedge_won():
    _HEDGE_WINS_C.inc()


def hedge_cancelled():
    _HEDGE_CANCELLED_C.inc()


def hedge_wasted():
    _HEDGE_WASTED_C.inc()


def hedge_reset():
    """Drain + re-seed the hedge bucket and latency ring (tests/chaos)."""
    global _HEDGE_BUCKET
    _HEDGE_BUCKET = TokenBucket(1.0, 64.0)
    _HEDGE_LATCH.clear()
    HEDGER.reset()


# ---------------------------------------------------------------------------
# brownout ladder
# ---------------------------------------------------------------------------
#: tenant criticality ranks — lower sheds LAST. register(tier=...) values.
TIER_RANKS = {"gold": 0, "silver": 1, "bulk": 2}

#: brownout level -> minimum tier rank refused at admission (None = nobody)
_SHED_RANK_AT_LEVEL = {0: None, 1: None, 2: 2, 3: 1}

_MAX_LEVEL = 3


class BrownoutController:
    """Fleet-level degradation ladder over the SLO monitor's burn state.

    ``tick(now)`` reads the monitor (injectable for drills; default the
    process-wide ``slo.MONITOR``): *burning* means any objective's latched
    alert is active or its fast burn exceeds the monitor's threshold.
    ``MXNET_BROWNOUT_UP_N`` consecutive burning ticks degrade one level;
    ``MXNET_BROWNOUT_DOWN_N`` consecutive calm ticks recover one. Each
    transition bumps ``mxtpu_brownout_transitions_total{direction}``, moves
    the ``mxtpu_brownout_level`` gauge and fires exactly one
    ``brownout_shift`` flight event.

    The ladder's effects are consumed by the tiers:

    - ``shed_tier(tier)`` — InferenceServer.submit refuses matching tenants
      with ServerOverloadError (bulk at level 2, bulk+silver at level 3;
      gold never).
    - ``timeout_boost()`` — the Router widens batch timeouts (>= level 1).
    - ``clamp_max_new_tokens(n)`` — DecodeScheduler.submit clamps the
      generation budget (>= level 1).
    """

    def __init__(self, monitor=None):
        self._monitor = monitor     # None -> slo.MONITOR, resolved lazily
        self._lock = threading.Lock()
        self.level = 0
        self._hot = 0
        self._calm = 0
        _BROWNOUT_LEVEL_G.set(0)

    def _resolve_monitor(self):
        if self._monitor is not None:
            return self._monitor
        from ..telemetry.slo import MONITOR
        return MONITOR

    def set_monitor(self, monitor):
        """Swap the burn-signal source (chaos drills use a stub); None
        restores the process-wide SLO monitor."""
        self._monitor = monitor

    @staticmethod
    def enabled() -> bool:
        return bool(_config.get("MXNET_BROWNOUT_ENABLE"))

    # -- burn signal -----------------------------------------------------
    def _burning(self) -> bool:
        mon = self._resolve_monitor()
        try:
            thr = float(mon.burn_threshold)
            for st in mon.check_all():
                if st.get("alert_active"):
                    return True
                if float(st.get("fast_burn", 0.0)) >= thr:
                    return True
        except Exception:
            return False
        return False

    # -- the decision ----------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """One control turn: read the burn signal, apply hysteresis, move
        at most one level. Returns the transition report or None."""
        if not self.enabled():
            with self._lock:
                if self.level == 0:
                    return None
            return self._shift(-1, "disabled")
        burning = self._burning()
        up_n = max(1, int(_config.get("MXNET_BROWNOUT_UP_N")))
        down_n = max(1, int(_config.get("MXNET_BROWNOUT_DOWN_N")))
        with self._lock:
            if burning:
                self._hot += 1
                self._calm = 0
                if self._hot >= up_n and self.level < _MAX_LEVEL:
                    self._hot = 0
                    return self._shift_locked(+1, "slo_burn")
            else:
                self._calm += 1
                self._hot = 0
                if self._calm >= down_n and self.level > 0:
                    self._calm = 0
                    return self._shift_locked(-1, "burn_cleared")
        return None

    def _shift(self, direction: int, reason: str) -> dict:
        with self._lock:
            return self._shift_locked(direction, reason)

    def _shift_locked(self, direction: int, reason: str) -> dict:  # mxlint: disable=CONC200
        old = self.level
        self.level = min(max(self.level + direction, 0), _MAX_LEVEL)
        _BROWNOUT_LEVEL_G.set(self.level)
        word = "degrade" if direction > 0 else "recover"
        _BROWNOUT_TRANSITIONS_C.labels(word).inc()
        report = {"from_level": old, "to_level": self.level,
                  "direction": word, "reason": reason,
                  "shedding": self.shedding_tiers()}
        _flight.trigger("brownout_shift", **report)
        _telemetry.event("brownout_shift", **report)
        return report

    # -- effects consumed by the tiers ----------------------------------
    def shed_tier(self, tier: str) -> bool:
        """Should a request for a ``tier`` tenant be refused right now?
        Gold (rank 0) is never refused by the ladder."""
        rank = TIER_RANKS.get(tier, 0)
        shed_from = _SHED_RANK_AT_LEVEL.get(self.level)
        if shed_from is None or rank == 0:
            return False
        if rank >= shed_from:
            _BROWNOUT_SHED_C.labels(tier).inc()
            return True
        return False

    def shedding_tiers(self) -> list:
        shed_from = _SHED_RANK_AT_LEVEL.get(self.level)
        if shed_from is None:
            return []
        return sorted(t for t, r in TIER_RANKS.items()
                      if r >= shed_from and r > 0)

    def timeout_boost(self) -> float:
        """Batch-timeout multiplier the Router applies (1.0 at level 0)."""
        if self.level >= 1:
            return max(1.0, float(_config.get("MXNET_BROWNOUT_TIMEOUT_BOOST")))
        return 1.0

    def clamp_max_new_tokens(self, requested: int) -> int:
        """Decode generation budget under brownout (identity at level 0)."""
        if self.level >= 1:
            clamp = max(1, int(_config.get("MXNET_BROWNOUT_MAX_NEW_TOKENS")))
            return min(int(requested), clamp)
        return int(requested)

    def snapshot(self) -> dict:
        with self._lock:
            return {"level": self.level, "hot_ticks": self._hot,
                    "calm_ticks": self._calm, "enabled": self.enabled(),
                    "shedding": self.shedding_tiers(),
                    "timeout_boost": self.timeout_boost()}

    def reset(self):
        """Back to level 0 with counters cleared (tests/chaos isolation);
        no transition event — this is bookkeeping, not a recovery."""
        with self._lock:
            self.level = 0
            self._hot = 0
            self._calm = 0
            _BROWNOUT_LEVEL_G.set(0)


#: the process-wide ladder — Autoscaler.tick drives it; servers consult it
BROWNOUT = BrownoutController()
