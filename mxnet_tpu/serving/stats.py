"""Per-endpoint serving observability.

Same sink discipline as profiler/monitor (the instrumentation never blocks the
dispatch path): counters and histogram bumps are O(1) under a short lock, and
nothing synchronises a device value. Latency lands in log-spaced histograms
(~9% bin resolution, 1 us .. ~17 min) so p50/p95/p99 are readable without
retaining per-request samples; ``snapshot()`` renders the whole endpoint state
as one plain dict — the ``serving.stats()`` surface.

r7: re-based onto the process-wide ``mxnet_tpu.telemetry`` registry. Every
bump lands in the shared ``mxtpu_serving_*`` families (labeled by endpoint)
— the Prometheus/JSON export surface — while the fine-resolution local
histograms keep serving ``serving.stats()`` its exact legacy shape. The
executable-cache counters double as the recompile-storm detector:
``mxtpu_serving_compile_seconds_total`` climbing after warmup means traffic
is recompiling.
"""
from __future__ import annotations

import math
import sys
import threading
from typing import Dict

from .. import telemetry as _telemetry
from ..telemetry.metrics import _quantile_from_buckets

__all__ = ["LatencyHistogram", "EndpointStats"]

# shared-registry families (one per metric, children per endpoint label)
_REQUESTS = _telemetry.counter(
    "mxtpu_serving_requests_total",
    "Serving request lifecycle events by endpoint and event "
    "(submitted/completed/rejected/deadline_dropped/cancelled).",
    labelnames=("endpoint", "event"))
_BATCHES = _telemetry.counter(
    "mxtpu_serving_batches_total", "Device batch steps executed.",
    labelnames=("endpoint",))
_ROWS = _telemetry.counter(
    "mxtpu_serving_batch_rows_total",
    "Batch rows by kind: real (request rows) vs padded (bucket fill); "
    "occupancy = real / (real + padded).",
    labelnames=("endpoint", "kind"))
_QUEUE_DEPTH = _telemetry.gauge(
    "mxtpu_serving_queue_depth",
    "Rows currently admitted and waiting, per endpoint.",
    labelnames=("endpoint",))
_QUEUE_PEAK = _telemetry.gauge(
    "mxtpu_serving_queue_peak", "High-water mark of the admitted-row queue.",
    labelnames=("endpoint",))
_OCCUPANCY = _telemetry.gauge(
    "mxtpu_serving_batch_occupancy",
    "Cumulative real/(real+padded) row ratio per endpoint (0..1).",
    labelnames=("endpoint",))
_LATENCY = _telemetry.histogram(
    "mxtpu_serving_request_latency_us",
    "End-to-end request latency: submit -> result ready (microseconds).",
    labelnames=("endpoint",))
_STEP = _telemetry.histogram(
    "mxtpu_serving_step_latency_us",
    "Device step latency: pad + run + slice (microseconds).",
    labelnames=("endpoint",))
_CACHE_HITS = _telemetry.counter(
    "mxtpu_serving_cache_hits_total",
    "Shape-bucket executable cache hits.", labelnames=("endpoint",))
_CACHE_MISSES = _telemetry.counter(
    "mxtpu_serving_cache_misses_total",
    "Shape-bucket executable cache misses (each one is a compile).",
    labelnames=("endpoint",))
_COMPILE_SECONDS = _telemetry.counter(
    "mxtpu_serving_compile_seconds_total",
    "Cumulative wall seconds spent compiling bucket executables; growth "
    "after warmup is a recompile storm.", labelnames=("endpoint",))
_QUEUE_WAIT = _telemetry.histogram(
    "mxtpu_serving_queue_wait_us",
    "Time a request waits admitted-but-unscheduled: submit -> picked for a "
    "batch assembly (microseconds). The scheduling share of latency — at "
    "saturation this, not step time, is where p99 lives.",
    labelnames=("endpoint",))
_PREP_LATENCY = _telemetry.histogram(
    "mxtpu_serving_prep_latency_us",
    "Host prep time per batch: concat + pad + device transfer "
    "(microseconds). Pipelined serving overlaps this with the device step.",
    labelnames=("endpoint",))
_SHED = _telemetry.counter(
    "mxtpu_serving_shed_total",
    "Requests shed at admission by endpoint and reason: queue_full, "
    "degraded (tightened admission), circuit_open, circuit_half_open.",
    labelnames=("endpoint", "reason"))
_PREP_OVERLAP = _telemetry.gauge(
    "mxtpu_serving_prep_overlap_ratio",
    "Cumulative fraction of host batch-prep time hidden under a concurrent "
    "device step (0..1); ~0 means prep rides the critical path.")


def set_prep_overlap_ratio(ratio: float):
    """Pipeline hook for the process-wide prep/step overlap gauge."""
    _PREP_OVERLAP.set(ratio)

# EndpointStats counter key -> (family, extra label values before/after)
_EVENT_NAMES = {"submitted": "submitted", "completed": "completed",
                "rejected": "rejected", "deadline_drops": "deadline_dropped",
                "cancelled": "cancelled"}

# 24 bins per decade-of-e... concretely: geometric bins with ratio 2**(1/8)
# (~9% wide), starting at 1 us. 240 bins tops out around 1e9 us (~17 min).
_RATIO = 2.0 ** 0.125
_NBINS = 240
# upper bound of each bin (bin i covers [_RATIO**i, _RATIO**(i+1))): the
# shape telemetry.metrics._quantile_from_buckets expects, so this histogram
# keeps its finer resolution while sharing the one quantile estimator
_BOUNDS = tuple(_RATIO ** (i + 1) for i in range(_NBINS))


class LatencyHistogram:
    """Log-spaced duration histogram with quantile estimation."""

    __slots__ = ("counts", "n", "total_us", "min_us", "max_us")

    def __init__(self):
        self.counts = [0] * _NBINS
        self.n = 0
        self.total_us = 0.0
        self.min_us = float("inf")
        self.max_us = 0.0

    def record(self, dur_us: float):
        d = max(float(dur_us), 0.0)
        self.n += 1
        self.total_us += d
        self.min_us = min(self.min_us, d)
        self.max_us = max(self.max_us, d)
        idx = 0 if d < 1.0 else min(int(math.log(d) / math.log(_RATIO)),
                                    _NBINS - 1)
        self.counts[idx] += 1

    def percentile(self, p: float) -> float:
        """p in [0, 100] -> approximate duration in us (geometric bin midpoint),
        0.0 when empty."""
        return _quantile_from_buckets(_BOUNDS, self.counts, self.n, p,
                                      self.max_us)

    def snapshot(self) -> Dict[str, float]:
        if self.n == 0:
            return {"count": 0, "mean_us": 0.0, "p50_us": 0.0, "p95_us": 0.0,
                    "p99_us": 0.0, "min_us": 0.0, "max_us": 0.0}
        return {
            "count": self.n,
            "mean_us": self.total_us / self.n,
            "p50_us": self.percentile(50),
            "p95_us": self.percentile(95),
            "p99_us": self.percentile(99),
            "min_us": self.min_us,
            "max_us": self.max_us,
        }


class EndpointStats:
    """All counters/gauges/histograms for one ModelEndpoint.

    Counters
    --------
    submitted / completed / rejected / deadline_drops / cancelled — request
    lifecycle; ``rejected`` counts admission-control overload rejections,
    ``deadline_drops`` requests dropped at batch assembly because their
    deadline had already expired (no device step spent on them).
    batches / real_rows / padded_rows — device-step accounting; occupancy is
    real/(real+padded).
    compiles / cache_hits — shape-bucket executable cache behaviour: compiles
    should equal the number of warmed buckets and stay flat under traffic.
    """

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "submitted": 0, "completed": 0, "rejected": 0,
            "deadline_drops": 0, "cancelled": 0, "batches": 0,
            "real_rows": 0, "padded_rows": 0, "compiles": 0, "cache_hits": 0,
            "hot_swaps": 0,
        }
        self.queue_depth = 0          # rows currently admitted and waiting
        self.queue_peak = 0
        self.latency = LatencyHistogram()     # submit -> result ready
        self.step = LatencyHistogram()        # device step (run+slice)
        self.queue_wait = LatencyHistogram()  # submit -> batch assembly
        self.prep = LatencyHistogram()        # concat+pad+device transfer
        self.shed_reasons: Dict[str, int] = {}
        self.compile_us = 0.0                 # total time in bucket compiles
        self._qd_counter = None               # lazy profiler.Counter
        # pre-bound shared-registry children (one bump, no lookup, hot path)
        self._m_events = {k: _REQUESTS.labels(name, v)
                          for k, v in _EVENT_NAMES.items()}
        self._m_batches = _BATCHES.labels(name)
        self._m_rows = {"real_rows": _ROWS.labels(name, "real"),
                        "padded_rows": _ROWS.labels(name, "padded")}
        self._m_qdepth = _QUEUE_DEPTH.labels(name)
        self._m_qpeak = _QUEUE_PEAK.labels(name)
        self._m_occupancy = _OCCUPANCY.labels(name)
        self._m_latency = _LATENCY.labels(name)
        self._m_step = _STEP.labels(name)
        self._m_queue_wait = _QUEUE_WAIT.labels(name)
        self._m_prep = _PREP_LATENCY.labels(name)
        self._m_hits = _CACHE_HITS.labels(name)
        self._m_misses = _CACHE_MISSES.labels(name)
        self._m_compile_s = _COMPILE_SECONDS.labels(name)

    # -- O(1) bumps on the dispatch path ------------------------------------
    def bump(self, counter: str, delta: int = 1):
        with self._lock:
            self.counters[counter] += delta
            if counter in ("real_rows", "padded_rows"):
                den = self.counters["real_rows"] + self.counters["padded_rows"]
                occ = self.counters["real_rows"] / den if den else 0.0
        ev = self._m_events.get(counter)
        if ev is not None:
            ev.inc(delta)
        elif counter == "batches":
            self._m_batches.inc(delta)
        elif counter in ("real_rows", "padded_rows"):
            if delta:
                self._m_rows[counter].inc(delta)
            self._m_occupancy.set(occ)
        elif counter == "cache_hits":
            self._m_hits.inc(delta)

    def set_queue_depth(self, rows: int):
        with self._lock:
            self.queue_depth = rows
            self.queue_peak = max(self.queue_peak, rows)
            peak = self.queue_peak
        self._m_qdepth.set(rows)
        self._m_qpeak.set(peak)
        # mirror the gauge into the profiler's chrome trace as a counter
        # track (only when a session is running; lazy so the profiler module
        # never loads on the serving path otherwise)
        prof = sys.modules.get("mxnet_tpu.profiler")
        if prof is not None and prof._STATE["running"]:
            if self._qd_counter is None:
                self._qd_counter = prof.Counter(
                    f"serving[{self.name}].queue_depth")
            self._qd_counter.set_value(rows)

    def record_latency(self, dur_us: float):
        with self._lock:
            self.latency.record(dur_us)
        self._m_latency.observe(dur_us)

    def record_step(self, dur_us: float):
        with self._lock:
            self.step.record(dur_us)
        self._m_step.observe(dur_us)
        from ..telemetry import perf_sentinel as _perf_sentinel
        _perf_sentinel.observe(f"serving_step.{self.name}", dur_us)

    def record_queue_wait(self, dur_us: float):
        with self._lock:
            self.queue_wait.record(dur_us)
        self._m_queue_wait.observe(dur_us)

    def record_prep(self, dur_us: float):
        with self._lock:
            self.prep.record(dur_us)
        self._m_prep.observe(dur_us)

    def record_shed(self, reason: str):
        """One admission-control shed, by reason (the caller also bumps the
        legacy ``rejected`` lifecycle counter where applicable)."""
        with self._lock:
            self.shed_reasons[reason] = self.shed_reasons.get(reason, 0) + 1
        _SHED.labels(self.name, reason).inc()

    def record_compile(self, dur_us: float):
        with self._lock:
            self.counters["compiles"] += 1
            self.compile_us += dur_us
        self._m_misses.inc()
        self._m_compile_s.inc(dur_us / 1e6)

    # -----------------------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            c = dict(self.counters)
            occ_den = c["real_rows"] + c["padded_rows"]
            return {
                "counters": c,
                "queue_depth": self.queue_depth,
                "queue_peak": self.queue_peak,
                "batch_occupancy": (c["real_rows"] / occ_den) if occ_den else 0.0,
                "latency": self.latency.snapshot(),
                "step": self.step.snapshot(),
                "queue_wait": self.queue_wait.snapshot(),
                "prep": self.prep.snapshot(),
                "shed": dict(self.shed_reasons),
                "compile_ms_total": self.compile_us / 1e3,
            }
