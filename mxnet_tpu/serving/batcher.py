"""Dynamic batcher: per-endpoint bounded request queue + batch assembly.

Concurrent requests accumulate into device-sized batches under a deadline:
a queue becomes *ready* when it holds a full ``max_batch_size`` worth of rows
or its oldest request has waited ``batch_timeout_ms`` (or the server is
draining, which flushes immediately). Assembly is where per-request deadlines
are enforced — expired requests are failed and dropped BEFORE they occupy
device rows, so a timed-out client never wastes a step.

Admission control is row-based: ``offer`` rejects (without enqueueing) once
``max_queue_rows`` rows are waiting. The caller-facing contract is explicit
backpressure — callers see ServerOverloadError and back off — instead of an
unbounded queue whose latency grows until everything times out.

All mutation happens under the server's shared condition lock; the batcher
itself never blocks and never touches the device.
"""
from __future__ import annotations

import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import List, Optional, Sequence, Tuple

import numpy as onp

from .errors import RequestTimeoutError

__all__ = ["Request", "EndpointQueue", "resolve", "fail"]


def _deadline_expired(site: str):
    """Bump mxtpu_deadline_exceeded_total{site} lazily (tailguard registers
    knobs at import; the batcher must stay import-light)."""
    try:
        from .tailguard import deadline_expired
        deadline_expired(site)
    except Exception:
        pass


def brownout_timeout_boost() -> float:
    """The brownout ladder's batch-timeout multiplier (1.0 at level 0):
    under degradation the assembly window widens — fuller batches, better
    goodput per device step — before any request is refused. Lazy import
    for the same reason as :func:`_deadline_expired`."""
    try:
        from .tailguard import BROWNOUT
        return BROWNOUT.timeout_boost()
    except Exception:
        return 1.0


def resolve(fut: Future, value):
    """set_result that tolerates the future already being settled (client
    cancelled it, or a racing stop() failed it first). ONLY the Future's own
    ``InvalidStateError`` is swallowed — anything else (a broken result
    object, a poisoned Future subclass) is a real bug and must surface."""
    try:
        fut.set_result(value)
    except InvalidStateError:
        pass


def fail(fut: Future, exc: Exception):
    """set_exception with the same narrow tolerance as :func:`resolve`."""
    try:
        fut.set_exception(exc)
    except InvalidStateError:
        pass


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class Request:
    """One admitted inference request: host-side input rows plus a Future the
    dispatch loop resolves with sliced outputs (or an error). ``trace_id``
    is stamped at submit (inheriting the submitter's telemetry span, if any)
    and adopted by the worker thread around batch assembly and the device
    step — one trace id follows the request across the queue hop."""

    __slots__ = ("inputs", "rows", "squeeze", "enqueue_us", "deadline_us",
                 "deadline", "future", "trace_id")

    def __init__(self, inputs: Tuple[onp.ndarray, ...], rows: int,
                 squeeze: bool, deadline_ms: Optional[float] = None,
                 deadline=None):
        from .. import telemetry
        self.inputs = inputs
        self.rows = rows
        self.squeeze = squeeze            # single example: drop the batch axis
        self.enqueue_us = _now_us()
        # a propagated tailguard.Deadline wins over a tier-local deadline_ms:
        # the budget was minted once at ingress and is never re-derived here
        self.deadline = deadline
        if deadline is not None:
            self.deadline_us: Optional[int] = int(deadline.deadline_us)
        else:
            self.deadline_us = (self.enqueue_us + int(deadline_ms * 1000)
                                if deadline_ms is not None else None)
        self.future: Future = Future()
        self.trace_id = (telemetry.current_trace_id()
                         or telemetry.new_trace_id())

    def expired(self, now_us: int) -> bool:
        return self.deadline_us is not None and now_us > self.deadline_us


class EndpointQueue:
    """FIFO of admitted requests for one endpoint, with row accounting."""

    def __init__(self, endpoint, max_queue_rows: int, batch_timeout_us: int):
        self.endpoint = endpoint
        self.max_queue_rows = max_queue_rows
        self.batch_timeout_us = batch_timeout_us
        self._pending: "deque[Request]" = deque()
        self.pending_rows = 0

    def __len__(self):
        return len(self._pending)

    # -- admission (caller holds the server lock) ---------------------------
    def offer(self, req: Request) -> bool:
        """Admit ``req`` unless the bounded queue is full. Returns False on
        overload (request NOT enqueued; caller raises)."""
        if self.pending_rows + req.rows > self.max_queue_rows:
            self.endpoint.stats.bump("rejected")
            return False
        self._pending.append(req)
        self.pending_rows += req.rows
        self.endpoint.stats.bump("submitted")
        self.endpoint.stats.set_queue_depth(self.pending_rows)
        return True

    def effective_timeout_us(self) -> int:
        """The batch window this queue assembles under right now: the
        configured timeout, widened by the brownout ladder's boost."""
        return int(self.batch_timeout_us * brownout_timeout_boost())

    # -- readiness (caller holds the server lock) ---------------------------
    def ready(self, now_us: int, flush: bool = False) -> bool:
        if not self._pending:
            return False
        if flush or self.pending_rows >= self.endpoint.max_batch_size:
            return True
        return now_us - self._pending[0].enqueue_us >= \
            self.effective_timeout_us()

    def next_wakeup_us(self) -> Optional[int]:
        """Absolute time at which the head request hits the batch deadline."""
        if not self._pending:
            return None
        return self._pending[0].enqueue_us + self.effective_timeout_us()

    def head_enqueue_us(self) -> int:
        """Enqueue time of the head request (queue must be non-empty)."""
        return self._pending[0].enqueue_us

    def head_deadline_us(self) -> Optional[int]:
        """Explicit deadline of the head request, when the client set one."""
        return self._pending[0].deadline_us

    # -- assembly (caller holds the server lock) ----------------------------
    def take_batch(self, now_us: int) -> List[Request]:
        """Pop a FIFO prefix of requests that fits max_batch_size rows,
        failing-and-dropping any whose deadline already passed. May return []
        when every pending request had expired."""
        ep = self.endpoint
        batch: List[Request] = []
        rows = 0
        while self._pending:
            head = self._pending[0]
            if head.future.cancelled():
                # a settled future nobody is waiting on (hedge loser, or a
                # client that cancelled): drop before it occupies device rows
                self._pending.popleft()
                self.pending_rows -= head.rows
                ep.stats.bump("cancelled")
                continue
            if head.expired(now_us):
                self._pending.popleft()
                self.pending_rows -= head.rows
                ep.stats.bump("deadline_drops")
                _deadline_expired("queue")
                fail(head.future, RequestTimeoutError(
                    f"deadline expired after "
                    f"{(now_us - head.enqueue_us) / 1e3:.1f} ms in queue"))
                continue
            if rows + head.rows > ep.max_batch_size:
                break
            self._pending.popleft()
            self.pending_rows -= head.rows
            # queue wait ends at assembly: submit -> picked for a batch. The
            # remaining latency is prep + device step, charged separately.
            ep.stats.record_queue_wait(max(now_us - head.enqueue_us, 0))
            batch.append(head)
            rows += head.rows
        ep.stats.set_queue_depth(self.pending_rows)
        return batch

    def requeue_front(self, requests: Sequence[Request]):
        """Push already-admitted requests back at the HEAD of the queue in
        their original order (worker failover: batches a dead/wedged worker
        never finished re-enter scheduling). Deliberately ignores the row
        bound — these rows were admitted once and still hold their original
        ``enqueue_us``/deadline, so expiry at re-assembly still applies."""
        for r in reversed(list(requests)):
            self._pending.appendleft(r)
            self.pending_rows += r.rows
        self.endpoint.stats.set_queue_depth(self.pending_rows)

    def fail_all(self, exc: Exception, counter: str = "cancelled"):
        """Drain the queue, failing every pending future (non-drain stop)."""
        while self._pending:
            req = self._pending.popleft()
            self.pending_rows -= req.rows
            self.endpoint.stats.bump(counter)
            fail(req.future, exc)
        self.endpoint.stats.set_queue_depth(0)


def concat_inputs(reqs: Sequence[Request], num_inputs: int
                  ) -> Tuple[onp.ndarray, ...]:
    """Concatenate per-request host inputs into one batch per model input."""
    return tuple(
        onp.concatenate([r.inputs[i] for r in reqs], axis=0)
        if len(reqs) > 1 else reqs[0].inputs[i]
        for i in range(num_inputs))
