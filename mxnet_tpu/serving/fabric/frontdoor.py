"""Multi-host serving front door: process groups, consistent-hash tenant
routing, and cross-host failover with zero client-visible errors.

A :class:`FrontDoor` fronts N *hosts*. Each host is one serving plane — an
``InferenceServer`` (built by the caller's ``host_factory``, endpoints
registered and started) plus a **host agent subprocess**: the CPU stand-in
for a ``jax.distributed`` process-group member. The agent runs a tiny real
workload at startup (so its goodput ledger is non-trivial), then heartbeats:
every tick it touches its heartbeat file, re-attributes goodput
(``goodput.account()`` — buckets always reconcile to wall exactly) and
rewrites its telemetry dump. A SIGKILLed host therefore leaves behind a
recent dump for the post-mortem pane, and a silent one is detected by
heartbeat age (:meth:`check_hosts`) rather than by an RPC that would hang.

Routing is a consistent-hash ring (``MXNET_FABRIC_VNODES`` virtual nodes
per host, md5 positions): a tenant maps to the first **alive** host at or
after its hash. Rebalancing is bounded by construction — when a host dies,
exactly the tenants whose walk landed on it move (to the next survivor
clockwise); every other tenant keeps its host. ``mxtpu_fabric_tenant_moves_total``
counts the moves so a test can pin the bound.

Failover rides the same fencing discipline as the intra-host supervisor
(each host also gets a :class:`~..supervisor.PoolSupervisor`): killing a
host bumps the front door's epoch, fails the host's queued work with
``ServerClosedError`` via ``stop(drain=False)``, and the front door's
wrapper future catches exactly that and resubmits on the rerouted survivor
— the client's future resolves normally. Zero dropped requests is the
acceptance bar, and :mod:`tools.chaos_check` ``--scenario host_down``
drills it.
"""
from __future__ import annotations

import hashlib
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence

from ... import config as _config
from ... import telemetry as _telemetry
from ...base import MXNetError
from ...resilience import faults as _faults
from ...resilience.faults import FaultInjected
from ...telemetry import flight as _flight
from ...telemetry.fleet import FleetCollector
from .. import tailguard as _tailguard
from ..errors import ServerClosedError, ServerOverloadError
from ..supervisor import PoolSupervisor

__all__ = ["FrontDoor"]

_HOSTS_G = _telemetry.gauge(
    "mxtpu_fabric_hosts",
    "Front-door hosts by liveness ('alive'/'down').",
    labelnames=("state",))
_MOVES_C = _telemetry.counter(
    "mxtpu_fabric_tenant_moves_total",
    "Tenants rehashed to a different host after a membership change — "
    "bounded rebalancing means only a dead host's tenants ever move.")
_FAILOVERS_C = _telemetry.counter(
    "mxtpu_fabric_host_failovers_total",
    "Host-down failovers the front door executed, by host.",
    labelnames=("host",))
_RESUBMITS_C = _telemetry.counter(
    "mxtpu_fabric_resubmits_total",
    "In-flight requests resubmitted on a survivor after their host died.")
_REQS_C = _telemetry.counter(
    "mxtpu_fabric_requests_total",
    "Requests routed through the front door, by host.",
    labelnames=("host",))


# The process-group member: a real subprocess per host. Startup serves a
# tiny real workload (non-trivial goodput), then each tick touches the
# heartbeat file, re-attributes goodput and rewrites this host's telemetry
# dump. Spans join the parent's journey via the inherited MXNET_TRACE_ID.
_HOST_AGENT_SRC = """\
import os, time
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import nd, serving, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.telemetry import goodput

host = os.environ["FABRIC_HOST"]
hb = os.environ["FABRIC_HB_PATH"]
dump = os.environ["FABRIC_DUMP_PATH"]
tick_s = float(os.environ.get("FABRIC_TICK_S", "0.2"))

mx.random.seed(0); onp.random.seed(0)
net = nn.HybridSequential()
with net.name_scope():
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
net.initialize(mx.init.Xavier())
net(nd.array(onp.zeros((2, 6), "float32")))
with telemetry.span("fabric.host_agent", host=host):
    srv = serving.InferenceServer(batch_timeout_ms=1.0)
    srv.register(serving.ModelEndpoint("fabric_probe_" + host, net,
                                       input_shapes=(6,), max_batch_size=4))
    srv.start()
    for _ in range(3):
        srv.submit("fabric_probe_" + host,
                   onp.zeros((2, 6), "float32")).result(timeout=30)
    srv.stop()
    serving.unregister("fabric_probe_" + host)
telemetry.spool_flush()
while True:
    with open(hb, "w") as f:
        f.write(str(time.time()))
    goodput.account()
    telemetry.dump(dump)
    time.sleep(tick_s)
"""


def _hash(s: str) -> int:
    return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "big")


class _Host:
    __slots__ = ("name", "server", "supervisor", "agent", "hb_path",
                 "dump_path", "alive")

    def __init__(self, name, server):
        self.name = name
        self.server = server
        self.supervisor = None
        self.agent = None
        self.hb_path = ""
        self.dump_path = ""
        self.alive = True


class FrontDoor:
    """Route tenants across host serving planes; survive a host dying.

    Parameters
    ----------
    hosts : sequence of str
        Host names (process-group members).
    host_factory : callable(name) -> InferenceServer
        Builds one host's serving plane: a STARTED server with this
        fabric's endpoints registered. Every host must register the same
        tenant set — the ring may land any tenant on any host.
    spawn_agents : bool
        Launch the per-host agent subprocess (heartbeat + dumps). On by
        default; tests that only exercise routing may turn it off.
    supervise : bool
        Attach a PoolSupervisor to each host's server for intra-host
        worker/prep failover. On by default.
    workdir : str, optional
        Where heartbeat and dump files live (default: a fresh tempdir).
    """

    def __init__(self, hosts: Sequence[str],
                 host_factory: Callable[[str], object],
                 spawn_agents: bool = True, supervise: bool = True,
                 workdir: Optional[str] = None):
        names = list(hosts)
        if len(set(names)) != len(names) or not names:
            raise MXNetError(f"need unique, non-empty host names: {names}")
        self.epoch = 0
        self._lock = threading.RLock()
        self._workdir = workdir or tempfile.mkdtemp(prefix="mxtpu-fabric-")
        self._vnodes = int(_config.get("MXNET_FABRIC_VNODES"))
        self._hosts: Dict[str, _Host] = {}
        self._owner: Dict[str, str] = {}      # tenant -> host, for move count
        for n in names:
            h = _Host(n, host_factory(n))
            h.hb_path = os.path.join(self._workdir, f"hb-{n}")
            h.dump_path = os.path.join(self._workdir, f"dump-host-{n}.json")
            if supervise:
                h.supervisor = PoolSupervisor(h.server).start()
            self._hosts[n] = h
        tenant_sets = {n: frozenset(h.server._router.names())
                       for n, h in self._hosts.items()}
        if len(set(tenant_sets.values())) != 1:
            raise MXNetError(
                f"hosts must register identical tenant sets, got "
                f"{ {n: sorted(s) for n, s in tenant_sets.items()} }")
        self._ring = self._build_ring()
        if spawn_agents:
            for h in self._hosts.values():
                self._spawn_agent(h)
        self._set_hosts_gauge()

    # -- membership -----------------------------------------------------
    def _build_ring(self) -> List:
        ring = []
        for n in self._hosts:
            for v in range(self._vnodes):
                ring.append((_hash(f"{n}#{v}"), n))
        ring.sort()
        return ring

    def _set_hosts_gauge(self):
        up = sum(1 for h in self._hosts.values() if h.alive)
        _HOSTS_G.labels("alive").set(up)
        _HOSTS_G.labels("down").set(len(self._hosts) - up)

    def _spawn_agent(self, h: _Host):
        env = dict(os.environ)
        env["FABRIC_HOST"] = h.name
        env["FABRIC_HB_PATH"] = h.hb_path
        env["FABRIC_DUMP_PATH"] = h.dump_path
        env["FABRIC_TICK_S"] = str(_config.get("MXNET_FABRIC_HEARTBEAT_S"))
        env.setdefault("JAX_PLATFORMS", "cpu")
        h.agent = subprocess.Popen(
            [sys.executable, "-c", _HOST_AGENT_SRC], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def hosts(self) -> List[str]:
        return list(self._hosts)

    def alive_hosts(self) -> List[str]:
        return [n for n, h in self._hosts.items() if h.alive]

    def tenants(self) -> List[str]:
        h = next(iter(self._hosts.values()))
        return list(h.server._router.names())

    # -- routing --------------------------------------------------------
    def route(self, tenant: str) -> str:
        """The first alive host at/after the tenant's ring position.
        Bounded rebalancing falls out of the walk: a dead host only
        reroutes the tenants that previously landed on it."""
        with self._lock:
            if not any(h.alive for h in self._hosts.values()):
                raise ServerClosedError("fabric: every host is down")
            pos = _hash(tenant)
            idx = 0
            for i, (p, _) in enumerate(self._ring):
                if p >= pos:
                    idx = i
                    break
            for step in range(len(self._ring)):
                _, name = self._ring[(idx + step) % len(self._ring)]
                if self._hosts[name].alive:
                    prev = self._owner.get(tenant)
                    if prev is not None and prev != name:
                        _MOVES_C.inc()
                    self._owner[tenant] = name
                    return name
            raise ServerClosedError("fabric: every host is down")

    def submit(self, tenant: str, inputs, deadline_ms: Optional[float] = None
               ) -> Future:
        """Route and enqueue; the returned future hides host death. When
        the serving host dies before this request resolves, the request is
        resubmitted on the rerouted survivor behind the same future —
        callers never see the dead host's ServerClosedError.

        ``deadline_ms`` mints the request's end-to-end
        :class:`~..tailguard.Deadline` HERE, at ingress — the one budget
        every downstream tier (routing, pool, queue, batch, retry backoff)
        decrements; no tier re-derives its own."""
        deadline = _tailguard.Deadline(deadline_ms) \
            if deadline_ms is not None else None
        out: Future = Future()
        self._submit_once(tenant, inputs, deadline_ms, out,
                          tries=len(self._hosts), deadline=deadline)
        return out

    def _submit_once(self, tenant, inputs, deadline_ms, out: Future,
                     tries: int, deadline=None):
        if deadline is not None:
            deadline.check("ingress")
        # the network hop between client and serving plane: net_delay
        # sleeps in place; net_drop (a partition) raises and is absorbed by
        # re-sending under the frontdoor retry budget — a drop storm
        # converts into bounded shed the moment the bucket runs dry
        while True:
            try:
                _faults.check("frontdoor")
                break
            except FaultInjected as e:
                if not e.retryable or not _tailguard.retry_allowed(
                        "frontdoor"):
                    raise
                if deadline is not None:
                    deadline.check("ingress")
        host = self.route(tenant)
        h = self._hosts[host]
        _REQS_C.labels(host).inc()
        # one routed request = one unit of real work funding the frontdoor
        # tier's retry budget
        _tailguard.retry_deposit("frontdoor")
        try:
            inner = h.server.submit(tenant, inputs, deadline_ms=deadline_ms,
                                    deadline=deadline)
        except (ServerClosedError, ServerOverloadError):
            # overload on a LIVE host is the caller's backpressure signal;
            # only a dead host's rejection reroutes (race with kill_host),
            # and the replay spends a frontdoor retry-budget token
            if h.alive or tries <= 1 or not self.alive_hosts() \
                    or not _tailguard.retry_allowed("frontdoor"):
                raise
            _RESUBMITS_C.inc()
            return self._submit_once(tenant, inputs, deadline_ms, out,
                                     tries - 1, deadline=deadline)

        def _done(f: Future):
            exc = f.exception()
            if exc is None:
                out.set_result(f.result())
                return
            # ServerClosedError from a host marked down == the host died
            # with this request in flight: replay it on a survivor (same
            # propagated deadline — the budget keeps burning), under the
            # frontdoor retry budget
            if isinstance(exc, ServerClosedError) and not h.alive \
                    and tries > 1 and self.alive_hosts() \
                    and _tailguard.retry_allowed("frontdoor"):
                _RESUBMITS_C.inc()
                try:
                    self._submit_once(tenant, inputs, deadline_ms, out,
                                      tries - 1, deadline=deadline)
                except Exception as e:          # survivors full/closed
                    out.set_exception(e)
                return
            out.set_exception(exc)

        inner.add_done_callback(_done)

    # -- failure handling -----------------------------------------------
    def kill_host(self, name: str, reason: str = "host_down") -> Dict:
        """Take one host out: SIGKILL its agent, fail its serving plane
        (queued work raises ServerClosedError → the wrapper futures replay
        on survivors), bump the epoch fence and rehash. Returns a report
        naming the host, the epoch and how many tenants moved."""
        with self._lock:
            h = self._hosts.get(name)
            if h is None:
                raise MXNetError(f"unknown host {name!r}: {self.hosts()}")
            if not h.alive:
                return {"host": name, "epoch": self.epoch, "moved": 0,
                        "already_down": True}
            before = dict(self._owner)
            h.alive = False              # routing excludes it from here on
            self.epoch += 1
            epoch = self.epoch
        if h.agent is not None and h.agent.poll() is None:
            try:
                h.agent.send_signal(signal.SIGKILL)
                h.agent.wait(timeout=10)
            except Exception:
                pass
        if h.supervisor is not None:
            h.supervisor.stop()
        h.server.stop(drain=False)       # fails inflight -> resubmission
        moved = 0
        for t in self.tenants():
            new = self.route(t)
            if before.get(t) == name and new != name:
                moved += 1
        _FAILOVERS_C.labels(name).inc()
        self._set_hosts_gauge()
        report = {"host": name, "reason": reason, "epoch": epoch,
                  "moved": moved, "survivors": self.alive_hosts()}
        _flight.trigger("host_down", **report)
        _telemetry.event("fabric_host_down", **report)
        return report

    def check_hosts(self) -> List[Dict]:
        """Heartbeat-age failure detector: a host whose agent has not
        ticked within MXNET_FABRIC_HOST_TIMEOUT_S is declared dead and
        failed over exactly like :meth:`kill_host`."""
        timeout_s = float(_config.get("MXNET_FABRIC_HOST_TIMEOUT_S"))
        reports = []
        for n, h in list(self._hosts.items()):
            if not h.alive or h.agent is None:
                continue
            age = None
            try:
                with open(h.hb_path) as f:
                    age = time.time() - float(f.read().strip())
            except (OSError, ValueError):
                pass                      # no beat yet: judge by spawn age
            dead_proc = h.agent.poll() is not None
            if dead_proc or (age is not None and age > timeout_s):
                reports.append(self.kill_host(
                    n, reason="agent_exit" if dead_proc else "heartbeat"))
        return reports

    # -- one pane of glass ----------------------------------------------
    def fleet_collect(self, include_local: bool = True) -> Dict:
        """The PR 15 fleet collector over every host agent's dump (plus
        this front-door process when ``include_local``)."""
        coll = FleetCollector(include_local=include_local,
                              local_label=f"frontdoor-{os.getpid()}",
                              glob="")
        for n, h in self._hosts.items():
            if os.path.exists(h.dump_path):
                coll.add_file(h.dump_path, label=f"host-{n}")
        return coll.collect()

    def goodput_reconcile(self, tol: float = 0.01) -> Dict[str, Dict]:
        """Per-host goodput ledger check from each host's own dump: the
        bucket seconds must sum to that host's wall clock within ``tol``."""
        import json
        out = {}
        for n, h in self._hosts.items():
            if not os.path.exists(h.dump_path):
                continue
            with open(h.dump_path) as f:
                snap = json.load(f)
            mets = snap.get("metrics", {})
            wall = max((float(s.get("value", 0.0)) for s in
                        mets.get("mxtpu_goodput_wall_seconds",
                                 {}).get("series", [])), default=0.0)
            total = sum(float(s.get("value", 0.0)) for s in
                        mets.get("mxtpu_goodput_seconds_total",
                                 {}).get("series", []))
            out[n] = {"wall_s": wall, "buckets_sum_s": total,
                      "ok": abs(total - wall) <= tol * max(wall, 1e-9)}
        return out

    # -- lifecycle ------------------------------------------------------
    def stop(self, drain: bool = True):
        """Stop every surviving host plane and reap the agents."""
        for h in self._hosts.values():
            if h.supervisor is not None:
                h.supervisor.stop()
            if h.agent is not None and h.agent.poll() is None:
                try:
                    h.agent.send_signal(signal.SIGKILL)
                    h.agent.wait(timeout=10)
                except Exception:
                    pass
            if h.alive:
                h.alive = False
                try:
                    h.server.stop(drain=drain)
                except Exception:
                    pass
        self._set_hosts_gauge()

    def __repr__(self):
        return (f"FrontDoor(hosts={self.hosts()}, "
                f"alive={self.alive_hosts()}, epoch={self.epoch})")
