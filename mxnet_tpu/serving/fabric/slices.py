"""Slice planner: partition the visible device set into gang-scheduled
slices, each backing one logical serving replica.

A :class:`SliceSpec` is the scheduling unit the fabric gangs devices by: a
contiguous run of devices (``parallel.mesh.carve_slices`` keeps contiguous
ids together — the tightest ICI neighborhoods on a real pod slice), a named
mesh layout over them, and a ``capacity`` equal to its device count — the
weight ``ServingPool.submit`` divides queue load by, so heterogeneous
replicas (one 4-chip slice next to two singles) each attract their fair
share of traffic.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ... import telemetry as _telemetry
from ...base import MXNetError
from ...parallel import mesh as _mesh

__all__ = ["SliceSpec", "plan_slices"]

_SLICES_G = _telemetry.gauge(
    "mxtpu_fabric_slices",
    "Gang-scheduled device slices in the last plan, by slice size "
    "(devices per slice).",
    labelnames=("size",))


class SliceSpec:
    """One gang-scheduled device slice: the devices, the mesh axis layout
    over them, and the replica capacity they add up to.

    ``axes`` defaults to ``{"dp": n}`` — the batch-axis layout whose
    sharded executables are bitwise-equal to a single chip's (row sharding
    never reorders a reduction). Pass a different layout for tp/fsdp-style
    experiments; the bitwise contract is only pinned for the default.
    """

    __slots__ = ("index", "devices", "axes", "_mesh")

    def __init__(self, index: int, devices: Sequence,
                 axes: Optional[Dict[str, int]] = None):
        self.index = int(index)
        self.devices = list(devices)
        if not self.devices:
            raise MXNetError("a slice needs at least one device")
        n = len(self.devices)
        if axes is None:
            axes = {"dp": n}
        sizes = 1
        for s in axes.values():
            sizes *= int(s)
        if sizes != n:
            raise MXNetError(f"slice axes {axes} need {sizes} devices, "
                             f"slice has {n}")
        self.axes = dict(axes)
        self._mesh: Optional[_mesh.DeviceMesh] = None

    @property
    def capacity(self) -> int:
        """Devices this slice gangs — the replica's load weight."""
        return len(self.devices)

    @property
    def name(self) -> str:
        """Topology-stable label: axis layout, not concrete device ids —
        the same string on any restart that lands an equal-shaped slice."""
        return "slice[" + ",".join(f"{a}={s}" for a, s in
                                   sorted(self.axes.items())) + "]"

    def make_mesh(self) -> _mesh.DeviceMesh:
        """The slice's DeviceMesh (built once, cached)."""
        if self._mesh is None:
            self._mesh = _mesh.make_mesh(self.axes, devices=self.devices)
        return self._mesh

    def __repr__(self):
        ids = [getattr(d, "id", d) for d in self.devices]
        return f"SliceSpec(#{self.index} {self.name} devices={ids})"


def plan_slices(sizes: Sequence[int], devices=None,
                axes: Optional[Sequence[Dict[str, int]]] = None
                ) -> List[SliceSpec]:
    """Carve ``devices`` (default: all visible) into gang-scheduled slices.

    ``sizes`` follows ``carve_slices``: asymmetric sizes are fine, leftover
    devices stay uncarved for single-chip replicas, oversubscription raises.
    ``axes`` optionally gives each slice its own mesh layout (one dict per
    size; default ``{"dp": size}``). Publishes ``mxtpu_fabric_slices``.
    """
    if axes is not None and len(axes) != len(sizes):
        raise MXNetError(f"axes ({len(axes)}) must match sizes "
                         f"({len(sizes)}) one-to-one")
    carved = _mesh.carve_slices(sizes, devices=devices)
    specs = [SliceSpec(i, devs, axes[i] if axes is not None else None)
             for i, devs in enumerate(carved)]
    by_size: Dict[int, int] = {}
    for sp in specs:
        by_size[sp.capacity] = by_size.get(sp.capacity, 0) + 1
    for size, count in by_size.items():
        # bounded: slice capacities are divisors of the device count
        _SLICES_G.labels(str(size)).set(count)  # mxlint: disable=MET301
    return specs
