"""mxnet_tpu.serving.fabric — mesh-sharded replicas and a multi-host front
door.

PRs 12–15 made serving a *fleet* (replica pools, SLO autoscaling, one-pane
observability) while every replica stayed one chip in one process. This
package is the missing layer between "a replica" and "a chip":

- **slices** (:mod:`.slices`): a slice planner that partitions the visible
  device set into gang-scheduled slices (``parallel.mesh.carve_slices``) —
  each slice backs one logical replica with ``capacity == len(devices)``,
  so a 4-chip sharded replica and a single-chip one coexist in one
  ``ServingPool`` with capacity-weighted placement.
- **sharded** (:mod:`.sharded`): :class:`ShardedEndpoint` /
  :class:`ShardedDecodeEndpoint` — drop-in endpoint twins whose bucket
  executables compile through the same ``compile_ledger.lower_and_compile``
  hook with NamedSharding in/out shardings over a slice's mesh. One logical
  replica spans N chips; the executable cache, compile ledger, warmup and
  StepCostEWMA contracts are unchanged. Outputs are BITWISE equal to the
  single-chip reference endpoint: only the batch (row) axis is ever
  sharded, and parameters shard along their leading axis where divisible —
  placements and all-gathers move exact bytes, no cross-device reduction
  ever reorders a floating-point sum.
- **frontdoor** (:mod:`.frontdoor`): a multi-host serving front door —
  per-host serving planes with subprocess-simulated process-group
  membership (heartbeats + telemetry dumps per host agent, the CPU stand-in
  for ``jax.distributed``), consistent-hash tenant->host routing with
  bounded rebalancing (a dead host's tenants move, nobody else's), and
  cross-host failover that resubmits a dead host's in-flight work on the
  survivors behind the client future — zero client-visible errors. The
  PR 15 fleet collector is the one pane of glass over every host's dump.
"""
from __future__ import annotations

from .slices import SliceSpec, plan_slices
from .sharded import ShardedDecodeEndpoint, ShardedEndpoint
from .frontdoor import FrontDoor

__all__ = ["SliceSpec", "plan_slices", "ShardedEndpoint",
           "ShardedDecodeEndpoint", "FrontDoor"]
