"""Mesh-sharded endpoint twins: one logical replica spanning N chips.

:class:`ShardedEndpoint` and :class:`ShardedDecodeEndpoint` are drop-in
subclasses of ``serving.ModelEndpoint`` / ``serving.generate.DecodeEndpoint``
whose bucket executables compile with ``NamedSharding`` in/out shardings
over a gang-scheduled slice's mesh (:mod:`.slices`). Everything else —
the AOT compile path through ``compile_ledger.lower_and_compile``, the
per-bucket executable dict, warmup seeding StepCostEWMA, the persistent
executable cache, hot-swap probe validation — is inherited unchanged: the
sharding enters only through four small hooks (jit wrapping, input/param
placement, and the cache trigger key).

Bitwise contract (the tier-1 oracle): a sharded replica's outputs equal the
single-chip reference endpoint's bit for bit. Two rules make that true by
construction rather than by luck:

- only the **batch (row) axis** of inputs and outputs is ever sharded.
  Every per-row computation then happens whole on one device — no
  contraction dimension is ever split, so no floating-point reduction is
  reordered;
- parameters shard along their **leading axis** where divisible (fsdp-style
  memory spreading) and replicate otherwise. Consuming a leading-axis
  shard is an all-gather — a byte move, not arithmetic.

Uneven sharding is a compile error in XLA (a global batch axis must divide
by the mesh axis), so a sharded endpoint's bucket ladder may only contain
multiples of its slice's batch-axis size; the default ladder is the pow2
ladder filtered down to those.

Cache-key topology rule: the trigger key must carry the slice *shape*
(axis sizes), never concrete device ids — the canonical StableHLO of a
sharded lowering is identical for any equal-shaped slice, so a restarted
replica that lands on different chips of the same shape deserializes the
fleet's stored executables (``fresh_compiles == 0``).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as onp

from ...base import MXNetError
from ...parallel.mesh import DeviceMesh
from .. import bucketing
from ..endpoint import ModelEndpoint
from ..generate.engine import DecodeEndpoint
from .slices import SliceSpec

__all__ = ["ShardedEndpoint", "ShardedDecodeEndpoint"]


def _compiled_mesh(comp):
    """The jax Mesh an executable's inputs are bound to, or None.

    A cache-deserialized executable is bound to the device assignment
    recorded at serialize time — the same slice *shape*, but possibly
    different chips than this replica nominally carved. The endpoint
    adopts that mesh so its placements match (fingerprint and trigger key
    are topology-stable, so every bucket of one endpoint deserializes onto
    the same assignment)."""
    import jax
    try:
        shardings = comp.input_shardings
    except Exception:
        return None
    for sh in jax.tree_util.tree_leaves(shardings):
        m = getattr(sh, "mesh", None)
        if m is not None and getattr(m, "devices", None) is not None:
            return m
    return None


def _resolve_mesh(slice_spec: Optional[SliceSpec],
                  mesh: Optional[DeviceMesh]) -> DeviceMesh:
    if slice_spec is not None:
        if mesh is not None:
            raise MXNetError("pass slice_spec OR mesh, not both")
        return slice_spec.make_mesh()
    if mesh is None:
        raise MXNetError("a sharded endpoint needs a slice_spec or mesh")
    return mesh


def _mesh_label(mesh: DeviceMesh) -> str:
    """Topology-stable slice label: axis layout, not device ids."""
    return ",".join(f"{a}={s}" for a, s in sorted(mesh.shape.items()))


def _sharded_buckets(buckets: Optional[Sequence[int]], max_batch_size: int,
                     shard: int) -> Sequence[int]:
    """Bucket ladder constrained to multiples of the batch-shard size:
    XLA rejects a global batch axis the mesh axis does not divide."""
    if max_batch_size % shard:
        raise MXNetError(
            f"max_batch_size={max_batch_size} must be a multiple of the "
            f"slice's batch-shard size {shard} (uneven batch sharding "
            "does not compile)")
    if buckets is None:
        return [b for b in bucketing.pow2_buckets(max_batch_size)
                if b % shard == 0]
    bad = [b for b in buckets if int(b) % shard]
    if bad:
        raise MXNetError(
            f"buckets {bad} are not multiples of the batch-shard size "
            f"{shard}; every sharded bucket's batch axis must divide by it")
    return buckets


class ShardedEndpoint(ModelEndpoint):
    """A ModelEndpoint whose replica spans every chip of one mesh slice.

    Parameters beyond ModelEndpoint's:

    slice_spec : SliceSpec, optional
        The gang-scheduled slice (from :func:`.slices.plan_slices`) this
        replica owns. ``capacity`` becomes its device count.
    mesh : DeviceMesh, optional
        Explicit mesh alternative to ``slice_spec``.
    shard_params : bool
        Shard each parameter along its leading axis over the batch axis
        where the size divides (fsdp-style: per-chip weight memory drops by
        ~the slice size); non-divisible parameters replicate. All-gather
        only — bitwise-invisible. Default True.
    """

    def __init__(self, name: str, block, input_shapes, dtype="float32",
                 max_batch_size: int = 32,
                 buckets: Optional[Sequence[int]] = None,
                 slice_spec: Optional[SliceSpec] = None,
                 mesh: Optional[DeviceMesh] = None,
                 shard_params: bool = True, ctx=None):
        dmesh = _resolve_mesh(slice_spec, mesh)
        self.slice_spec = slice_spec
        self._dmesh = dmesh
        self._batch_axis = dmesh.axis_names[0]
        self._shard = dmesh.axis_size(self._batch_axis)
        self._shard_params = bool(shard_params)
        self.capacity = dmesh.size
        self._placed_params = None
        self._placed_key = None
        buckets = _sharded_buckets(buckets, int(max_batch_size), self._shard)
        super().__init__(name, block, input_shapes, dtype=dtype,
                         max_batch_size=max_batch_size, buckets=buckets,
                         ctx=ctx)

    # -- sharding layout ------------------------------------------------
    def _batch_sharding(self):
        return self._dmesh.sharding(self._batch_axis)

    def _param_shardings(self):
        repl = self._dmesh.replicated()
        if not self._shard_params:
            return tuple(repl for _ in self._params)
        rowsh = self._batch_sharding()
        return tuple(
            rowsh if (len(p.shape) >= 1 and p.shape[0] % self._shard == 0)
            else repl
            for p in self._params)

    def _device_label(self) -> str:
        try:
            platform = self.ctx.jax_device().platform
        except Exception:
            platform = "?"
        return f"{platform}:{_mesh_label(self._dmesh)}"

    def _compile_key(self, bucket: int) -> Dict[str, object]:
        # the mesh label rides into the compile ledger AND the cost-model
        # prior: a cold bucket on a 4-chip slice is priced by predictions
        # trained on that topology, so fabric admission (step_cost.estimate
        # behind ServingPool's capacity-weighted routing) is per-slice
        key = super()._compile_key(bucket)
        key["mesh"] = _mesh_label(self._dmesh)
        return key

    def _adopt_compiled(self, comp):
        m = _compiled_mesh(comp)
        if m is None:
            return
        if set(m.devices.flat) != set(self._dmesh.mesh.devices.flat):
            self._dmesh = DeviceMesh(m)
            self._placed_params = None     # re-place onto the adopted mesh
            self._placed_key = None

    def prepare(self, host_inputs, rows: int, parity: int = 0):
        # adoption must precede placement: materialize the bucket's
        # executable first (idempotent, lock-protected) so an unwarmed
        # endpoint's first batch still places onto the bound mesh
        self._get_executable(bucketing.bucket_for(rows, self.buckets))
        return super().prepare(host_inputs, rows, parity=parity)

    # -- the four sharding hooks ----------------------------------------
    def _jit_infer(self, infer, donate):
        import jax
        bsh = self._batch_sharding()
        in_sh = (self._param_shardings(),) + \
            (bsh,) * len(self.input_shapes)
        # out_shardings as a prefix: every (batch-major) output row-shards
        return jax.jit(infer, donate_argnums=donate,
                       in_shardings=in_sh, out_shardings=bsh)

    def _place_inputs(self, arrays):
        import jax
        bsh = self._batch_sharding()
        return tuple(jax.device_put(onp.asarray(a), bsh) for a in arrays)

    def _place_params(self, arrays):
        import jax
        return tuple(jax.device_put(a, sh)
                     for a, sh in zip(arrays, self._param_shardings()))

    def _param_datas(self):
        if self._active_params is not None:     # hot-swap committed set,
            return self._active_params          # already mesh-placed
        base = tuple(p.data(self.ctx).data for p in self._params)
        key = tuple(id(a) for a in base)
        if key != self._placed_key:
            self._placed_params = self._place_params(base)
            self._placed_key = key
        return self._placed_params

    def _warmup_inputs(self, bucket: int):
        # plain numpy: an uncommitted host array auto-places per the
        # compiled sharding (a committed single-device array would not)
        return tuple(onp.zeros((bucket,) + s, dt)
                     for s, dt in zip(self.input_shapes, self.np_dtypes))

    def __repr__(self):
        return (f"ShardedEndpoint({self.name!r}, "
                f"mesh={_mesh_label(self._dmesh)}, "
                f"inputs={self.input_shapes}, buckets={self.buckets})")


class ShardedDecodeEndpoint(DecodeEndpoint):
    """A DecodeEndpoint twin over a mesh slice.

    Layout: the decode-step batch row-shards over the slice's batch axis
    (its bucket ladder is constrained to multiples of the shard size, like
    the dense twin); prefill (batch 1) and the paged KV pools replicate —
    replication across N chips is trivially bitwise, and the pool write
    scatter then moves bytes only. Parameters replicate (a generative
    model's embedding/vocab tables are the likeliest leading-axis
    mismatches, so the dense twin's fsdp-style spreading is not defaulted
    here).
    """

    def __init__(self, name: str, block, *,
                 slice_spec: Optional[SliceSpec] = None,
                 mesh: Optional[DeviceMesh] = None,
                 max_batch_size: Optional[int] = None,
                 decode_buckets: Optional[Sequence[int]] = None, **kw):
        dmesh = _resolve_mesh(slice_spec, mesh)
        self.slice_spec = slice_spec
        self._dmesh = dmesh
        self._batch_axis = dmesh.axis_names[0]
        self._shard = dmesh.axis_size(self._batch_axis)
        self.capacity = dmesh.size
        self._placed_params = None
        self._placed_key = None
        if max_batch_size is None:
            from ... import config as _config
            max_batch_size = int(_config.get("MXNET_DECODE_MAX_BATCH"))
        decode_buckets = _sharded_buckets(decode_buckets,
                                          int(max_batch_size), self._shard)
        super().__init__(name, block, max_batch_size=max_batch_size,
                         decode_buckets=decode_buckets, **kw)
        import jax
        repl = self._dmesh.replicated()
        # the pools ride as executable arguments: committed single-device
        # arrays are rejected by a sharded AOT call, so place them
        # replicated once; every later update keeps the mesh placement
        self.pool.update_arrays(jax.device_put(self.pool.k_pool, repl),
                                jax.device_put(self.pool.v_pool, repl))

    def _device_label(self) -> str:
        try:
            platform = self.ctx.jax_device().platform
        except Exception:
            platform = "?"
        return f"{platform}:{_mesh_label(self._dmesh)}"

    def _cost_key(self, kind: str, bucket: int) -> Dict[str, object]:
        # mirror the dense twin: slice topology reaches the ledger and the
        # cost-model prior, so decode admission prices per-slice
        key = super()._cost_key(kind, bucket)
        key["mesh"] = _mesh_label(self._dmesh)
        return key

    def _adopt_compiled(self, comp):
        m = _compiled_mesh(comp)
        if m is None:
            return
        if set(m.devices.flat) != set(self._dmesh.mesh.devices.flat):
            import jax
            self._dmesh = DeviceMesh(m)
            self._placed_params = None
            self._placed_key = None
            repl = self._dmesh.replicated()
            self.pool.update_arrays(
                jax.device_put(onp.asarray(self.pool.k_pool), repl),
                jax.device_put(onp.asarray(self.pool.v_pool), repl))

    def _param_datas(self):
        import jax
        base = super()._param_datas()
        key = tuple(id(a) for a in base)
        if key != self._placed_key:
            repl = self._dmesh.replicated()
            self._placed_params = tuple(jax.device_put(a, repl)
                                        for a in base)
            self._placed_key = key
        return self._placed_params

    def _jit_prefill(self, fn, donate):
        import jax
        repl = self._dmesh.replicated()
        # batch 1 cannot shard: the whole prefill replicates (bitwise by
        # construction); 6 args — params tree takes repl as a prefix
        return jax.jit(fn, donate_argnums=donate,
                       in_shardings=(repl,) * 6, out_shardings=repl)

    def _jit_decode(self, fn, donate):
        import jax
        repl = self._dmesh.replicated()
        bsh = self._dmesh.sharding(self._batch_axis)
        # (params, ids, positions, tables, valid, k_pool, v_pool)
        in_sh = (repl, bsh, bsh, bsh, bsh, repl, repl)
        # (next_ids, k_pool, v_pool)
        return jax.jit(fn, donate_argnums=donate,
                       in_shardings=in_sh, out_shardings=(bsh, repl, repl))

    def __repr__(self):
        return (f"ShardedDecodeEndpoint({self.name!r}, "
                f"mesh={_mesh_label(self._dmesh)}, "
                f"decode_buckets={self.decode_buckets})")
