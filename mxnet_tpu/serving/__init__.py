"""mxnet_tpu.serving — pipelined, multi-tenant dynamic-batching inference.

The production request->response path over this framework (the serving-system
component TensorFlow treats as first-class, PAPERS.md): concurrent client
requests are accumulated by a dynamic batcher into device-sized batches under
a configurable deadline, padded to shape buckets so every bucket hits one
cached compiled executable (never recompiling in steady state), executed as
one device step, and sliced back into per-request responses.

r6 rebuilt the dispatch path into a multi-tenant scheduler with a
double-buffered host pipeline:

- **Router** (router.py): N endpoints multiplex over the single
  device-owning dispatch path; the next batch is picked
  earliest-deadline-first across tenants, priced by each bucket's measured
  step-time EWMA (seeded at warmup), with shortest-job-first among
  already-late tenants so a long batch cannot convoy short requests.
  Batches assemble at the last moment — rows arriving during device step k
  join batch k+1 (continuous batching).
- **Host pipeline** (pipeline.py): a prep thread concat/pads and
  ``device_put``s batch k+1 into the next parity's input-buffer set while
  the worker executes batch k; host time leaves the critical path. Only the
  worker invokes compiled executables. ``InferenceServer(pipeline=False)``
  keeps the serial path (bitwise-identical outputs, same executables).
- **Per-tenant shedding**: each endpoint gets its own CircuitBreaker, so one
  tenant's overload tightens that tenant's admission, not the whole server.

r7 adds the elastic layer: **zero-downtime weight hot-swap**
(``server.hot_swap(name, ckpt)`` verifies + stages off the serving path,
probe-validates bitwise against recorded outputs, cuts over on the worker
at a batch boundary, rolls back on failure) and **worker failover**
(``PoolSupervisor`` declares a dead or watchdog-wedged worker, requeues its
batches front-of-queue with deadlines intact, trips only the affected
tenant's breaker, restarts the worker generation). See RESILIENCE.md's
"Preemption & hot-swap runbook".

    from mxnet_tpu import serving

    ep = serving.ModelEndpoint("resnet50", net, input_shapes=(3, 224, 224),
                               dtype="bfloat16", max_batch_size=32)
    server = serving.InferenceServer(batch_timeout_ms=2.0, max_queue=256)
    server.register(ep, slo_ms=50.0)   # warms buckets + seeds the cost model
    server.start()

    out = server.predict("resnet50", img)           # blocking
    fut = server.submit("resnet50", img, deadline_ms=50.0)  # async w/ deadline

    serving.stats()["resnet50"]  # p50/p95/p99, queue_wait, prep, shed, ...
    server.stop(drain=True)      # graceful: flushes admitted work first

Numerics contract: a served output is BITWISE equal to the hybridized direct
forward of the same rows — the endpoint executable is the same
single-XLA-computation trace CachedOp builds, padding rows never mix into
real rows, and bucket size does not change per-row results; the pipelined
path reuses the serial path's executables, padding and concat, so it is
bitwise-identical to serial serving too. (Eager op-by-op dispatch of the
same net may differ by float rounding, because XLA fuses the whole traced
graph differently than per-op programs.)

Robustness contract: the queue is bounded per tenant (ServerOverloadError at
admission — explicit backpressure instead of unbounded latency), per-request
deadlines drop expired work before it occupies device rows
(RequestTimeoutError), and shutdown drains by default with a bounded timeout
(abandoned requests are failed, never waited on forever). Each device batch
step runs under a resilience.RetryPolicy (transient failures retried within
the batch's earliest deadline), a Watchdog flags hung steps (degrading the
stalled tenant's breaker), and per-tenant CircuitBreakers shed load
(HEALTHY→DEGRADED→OPEN→HALF_OPEN) — see ``InferenceServer.health()`` and
RESILIENCE.md. Observability rides the telemetry registry: queue-wait and
prep histograms, the prep/step overlap gauge, per-tenant shed counters, and
``stats()`` snapshots per-endpoint latency histograms, queue depth, batch
occupancy (real vs padded rows) and executable-cache hit/compile counters.

r11 adds the generative path (``serving.generate``): autoregressive decode
with a paged KV cache and token-granularity continuous batching — a
``DecodeEndpoint`` compiles two AOT executables per bucket (prefill by
sequence length, decode-step by batch size), a ``DecodeScheduler`` re-forms
the decode batch every token (EDF admission against per-tenant *inter-token*
SLOs, lossless stream backpressure, failover that requeues partial
sequences), and ``server.register_generator(engine)`` /
``server.generate(name, prompt)`` expose it behind the InferenceServer
facade with streaming ``TokenStream`` responses. Batched continuous decode
is bitwise-equal to serial greedy decode (tier-1 oracle).

r16 adds the serving fabric (``serving.fabric``): mesh-sharded replicas and
a multi-host front door. ``plan_slices`` carves the visible device set into
gang-scheduled slices; a ``ShardedEndpoint`` / ``ShardedDecodeEndpoint``
spans one slice's mesh with NamedSharding-compiled bucket executables
(bitwise-equal to the single-chip twins; same executable cache, compile
ledger and warmup contracts), ``ServingPool.submit`` weights placement by
replica capacity, and ``FrontDoor`` adds consistent-hash tenant→host
routing with bounded rebalancing plus cross-host failover that replays a
dead host's in-flight work on survivors — zero client-visible errors.

r18 adds the tail-tolerance defense layer (``serving.tailguard``): one
end-to-end ``Deadline`` minted at ingress rides every hop and fails fast
(``DeadlineExceeded``, which ``RequestTimeoutError`` now derives from);
``ServingPool.submit`` hedges a late request onto the second-least-loaded
replica under a token-bucket hedge budget (first response wins, loser
cancelled at batch assembly, results bitwise-equal to unhedged); per-tier
retry budgets (frontdoor / execute / decode) convert retry storms into
bounded shed; and a ``BrownoutController`` ladder degrades under sustained
SLO burn in tenant-criticality order (``register(..., tier="bulk")`` sheds
before silver before gold; gold is never refused).
"""
from __future__ import annotations

from .autoscaler import Autoscaler, ServingPool
from .endpoint import ModelEndpoint, get_endpoint, list_endpoints, unregister
from .errors import (DeadlineExceeded, HotSwapError, KVPoolExhausted,
                     RequestTimeoutError, ServerClosedError,
                     ServerOverloadError, ServingError)
from .router import Router, StepCostEWMA, Tenant
from .server import InferenceServer
from .supervisor import PoolSupervisor
from . import bucketing
from . import generate
from .generate import (DecodeEndpoint, DecodeScheduler, PagedKVPool,
                       TokenStream)
from . import fabric
from .fabric import (FrontDoor, ShardedDecodeEndpoint, ShardedEndpoint,
                     SliceSpec, plan_slices)
from . import tailguard
from .tailguard import (BROWNOUT, BrownoutController, Deadline, HEDGER,
                        HedgePolicy, RETRY_BUDGETS, RetryBudgets, TIER_RANKS,
                        TokenBucket)

__all__ = ["ModelEndpoint", "InferenceServer", "PoolSupervisor", "stats",
           "get_endpoint", "list_endpoints", "unregister", "ServingError",
           "ServerOverloadError", "RequestTimeoutError", "ServerClosedError",
           "HotSwapError", "KVPoolExhausted", "DeadlineExceeded", "Router",
           "StepCostEWMA", "Tenant", "bucketing", "generate",
           "DecodeEndpoint", "DecodeScheduler", "PagedKVPool", "TokenStream",
           "ServingPool", "Autoscaler", "fabric", "FrontDoor",
           "ShardedEndpoint", "ShardedDecodeEndpoint", "SliceSpec",
           "plan_slices", "tailguard", "Deadline", "TokenBucket",
           "RetryBudgets", "RETRY_BUDGETS", "HedgePolicy", "HEDGER",
           "BrownoutController", "BROWNOUT", "TIER_RANKS"]


def stats():
    """Snapshot of every registered endpoint's serving metrics:
    ``{endpoint: {counters, queue_depth, batch_occupancy, latency, step,
    queue_wait, prep, shed}}``. Latency blocks carry
    count/mean/p50/p95/p99/min/max in microseconds."""
    from .endpoint import _ENDPOINTS, _REG_LOCK
    with _REG_LOCK:
        eps = list(_ENDPOINTS.values())
    return {ep.name: ep.stats.snapshot() for ep in eps}
