"""mxnet_tpu.serving — dynamic-batching inference serving.

The production request->response path over this framework (the serving-system
component TensorFlow treats as first-class, PAPERS.md): concurrent client
requests are accumulated by a dynamic batcher into device-sized batches under
a configurable deadline, padded to shape buckets so every bucket hits one
cached compiled executable (never recompiling in steady state), executed as
one device step, and sliced back into per-request responses.

    from mxnet_tpu import serving

    ep = serving.ModelEndpoint("resnet50", net, input_shapes=(3, 224, 224),
                               dtype="bfloat16", max_batch_size=32)
    server = serving.InferenceServer(batch_timeout_ms=2.0, max_queue=256)
    server.register(ep)          # warms every shape bucket (compile-free serving)
    server.start()

    out = server.predict("resnet50", img)           # blocking
    fut = server.submit("resnet50", img, deadline_ms=50.0)  # async w/ deadline

    serving.stats()["resnet50"]  # p50/p95/p99, occupancy, compile counters
    server.stop(drain=True)      # graceful: flushes admitted work first

Numerics contract: a served output is BITWISE equal to the hybridized direct
forward of the same rows — the endpoint executable is the same
single-XLA-computation trace CachedOp builds, padding rows never mix into
real rows, and bucket size does not change per-row results. (Eager op-by-op
dispatch of the same net may differ by float rounding, because XLA fuses the
whole traced graph differently than per-op programs.)

Robustness contract: the queue is bounded (ServerOverloadError at admission —
explicit backpressure instead of unbounded latency), per-request deadlines
drop expired work before it occupies device rows (RequestTimeoutError), and
shutdown drains by default with a bounded timeout (abandoned requests are
failed, never waited on forever). Each device batch step runs under a
resilience.RetryPolicy (transient failures retried within the batch's
earliest deadline), a Watchdog flags hung steps, and a CircuitBreaker sheds
load (HEALTHY→DEGRADED→OPEN→HALF_OPEN) — see ``InferenceServer.health()``
and RESILIENCE.md. Observability rides the profiler layer: when the
profiler runs, every serving step is a recorded dispatch event, and
``stats()`` snapshots per-endpoint latency histograms, queue depth, batch
occupancy (real vs padded rows) and executable-cache hit/compile counters.
"""
from __future__ import annotations

from .endpoint import ModelEndpoint, get_endpoint, list_endpoints, unregister
from .errors import (RequestTimeoutError, ServerClosedError,
                     ServerOverloadError, ServingError)
from .server import InferenceServer
from . import bucketing

__all__ = ["ModelEndpoint", "InferenceServer", "stats", "get_endpoint",
           "list_endpoints", "unregister", "ServingError",
           "ServerOverloadError", "RequestTimeoutError", "ServerClosedError",
           "bucketing"]


def stats():
    """Snapshot of every registered endpoint's serving metrics:
    ``{endpoint: {counters, queue_depth, batch_occupancy, latency, step}}``.
    Latency blocks carry count/mean/p50/p95/p99/min/max in microseconds."""
    from .endpoint import _ENDPOINTS, _REG_LOCK
    with _REG_LOCK:
        eps = list(_ENDPOINTS.values())
    return {ep.name: ep.stats.snapshot() for ep in eps}
