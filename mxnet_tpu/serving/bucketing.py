"""Shape-bucket policy for the dynamic batcher.

On XLA-compiled hardware every distinct input shape is a distinct executable,
and served batch sizes are whatever concurrency happens to produce — so an
unbucketed server compiles continuously and a fully-padded server wastes MXU
rows (the padding/bucketing trade-off the learned-TPU-cost-model line of work
measures, PAPERS.md). The policy here is the standard compromise: batch sizes
round UP to a small fixed ladder (powers of two by default), so the executable
cache is bounded by ``len(buckets)`` while padding waste per step is < 2x in
the worst case and ~0 at the full-batch steady state.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import numpy as onp

from ..base import MXNetError

__all__ = ["pow2_buckets", "seq_buckets", "bucket_for", "pad_rows",
           "validate_buckets"]


def validate_buckets(buckets: Sequence[int], max_batch_size: int
                     ) -> Tuple[int, ...]:
    """Validate a user-supplied bucket ladder at endpoint construction.

    The executable cache is keyed by bucket, so a malformed ladder is a
    config error worth failing loudly on: buckets must be integers >= 1,
    strictly ascending (which also rules out duplicates — a duplicate is a
    second compile of the same shape), and the largest must equal
    ``max_batch_size`` (otherwise some admissible request fits no bucket, or
    rows beyond the largest bucket can never be served). Returns the ladder
    as a tuple; raises MXNetError with the offending ladder otherwise."""
    ladder = tuple(buckets)
    if not ladder:
        raise MXNetError("bucket list must be non-empty")
    prev = 0
    for b in ladder:
        ib = int(b)
        if ib != b or ib < 1:
            raise MXNetError(
                f"buckets must be integers >= 1, got {b!r} in {ladder}")
        if ib <= prev:
            raise MXNetError(
                "buckets must be strictly ascending with no duplicates "
                f"(got {ladder}: {ib} after {prev})")
        prev = ib
    ladder = tuple(int(b) for b in ladder)
    if ladder[-1] != max_batch_size:
        raise MXNetError("largest bucket must equal max_batch_size "
                         f"(got buckets={ladder}, "
                         f"max_batch_size={max_batch_size})")
    return ladder


def pow2_buckets(max_batch_size: int) -> Tuple[int, ...]:
    """Power-of-two ladder 1, 2, 4, ... capped at and including max_batch_size."""
    if max_batch_size < 1:
        raise MXNetError(f"max_batch_size must be >= 1, got {max_batch_size}")
    out = []
    b = 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(max_batch_size)
    return tuple(out)


def seq_buckets(max_seq_len: int, min_bucket: int = 16,
                ladder: Sequence[int] = None) -> Tuple[int, ...]:
    """Sequence-length ladder for prefill bucketing.

    Same contract as the batch ladder — each distinct prompt-length bucket
    is one prefill executable, so the ladder bounds the AOT cache while
    padding waste per prompt stays < 2x — but anchored at ``min_bucket``
    instead of 1: a one-token prefill executable is useless (the decode-step
    executable already covers single tokens) and sub-tile sequence lengths
    pessimize the attention kernels. Doubles from ``min_bucket`` and is
    capped at (and always includes) ``max_seq_len``. An explicit ``ladder``
    skips generation and gets the same :func:`validate_buckets` dup /
    ascending / largest-equals-max checks."""
    if max_seq_len < 1:
        raise MXNetError(f"max_seq_len must be >= 1, got {max_seq_len}")
    if ladder is not None:
        return validate_buckets(ladder, max_seq_len)
    if min_bucket < 1:
        raise MXNetError(f"min_bucket must be >= 1, got {min_bucket}")
    out = []
    b = min(min_bucket, max_seq_len)
    while b < max_seq_len:
        out.append(b)
        b *= 2
    out.append(max_seq_len)
    return validate_buckets(out, max_seq_len)


def bucket_for(rows: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits ``rows`` real rows."""
    for b in buckets:
        if b >= rows:
            return b
    raise MXNetError(f"{rows} rows exceed the largest bucket {buckets[-1]}")


def pad_rows(batch: onp.ndarray, bucket: int) -> onp.ndarray:
    """Zero-pad ``batch`` along axis 0 up to ``bucket`` rows (no copy when
    already exact)."""
    rows = batch.shape[0]
    if rows == bucket:
        return batch
    if rows > bucket:
        raise MXNetError(f"batch of {rows} rows does not fit bucket {bucket}")
    pad = onp.zeros((bucket - rows,) + batch.shape[1:], dtype=batch.dtype)
    return onp.concatenate([batch, pad], axis=0)
