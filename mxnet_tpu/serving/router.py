"""Router: SLO-aware batch selection across many endpoints on one device.

The InferenceServer multiplexes N ``ModelEndpoint``s (tenants) over a single
device-owning dispatch path. The Router decides *whose* batch runs next. The
policy is earliest-deadline-first corrected by each bucket's measured step
cost (a per-(endpoint, bucket) EWMA fed by every device step, seeded by
warmup) — the "pick by deadline, price by observed step time" discipline the
learned-TPU-cost-model line of work argues for (PAPERS.md):

1. A tenant's head request has an *effective deadline*: its explicit
   ``deadline_ms`` when set, else ``enqueue + slo_ms`` (per-tenant SLO), else
   ``enqueue + batch_timeout`` (the batching deadline).
2. Its *slack* is ``deadline - now - est_step``: how long scheduling can be
   deferred and the head still finish in time. ``est_step`` comes from the
   EWMA for the bucket this batch would actually run in, so a tenant whose
   next batch is expensive becomes urgent *earlier* — EDF that knows a big
   batch needs a head start.
3. Among tenants whose head is still meetable (slack >= 0), pick the
   smallest slack. When only already-late tenants remain, pick the
   *cheapest* estimated step (shortest-job-first): a long batch that is
   late regardless must not convoy short requests that are late too —
   running the short ones first strictly reduces total lateness.
4. Anti-starvation backstop: a late tenant whose head has waited more than
   ``starvation_factor x (batch_timeout + est_step)`` is escalated and
   served oldest-first, so SJF can never starve the expensive tenant.

Continuous batching falls out of *when* selection happens: the prep stage
(or the serial worker) assembles a batch at the last moment, after the
previous batch is already executing — rows that arrived during device step k
join the assembly for step k+1 instead of waiting out the in-flight
generation.

The Router owns no lock: every mutation and every ``select()`` happens under
the server's shared condition, exactly like the EndpointQueues it reads.
Only :class:`StepCostEWMA` is internally locked — it is fed from the worker
thread (outside the server lock) and read during selection (under it).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from . import bucketing
from .batcher import EndpointQueue
from ..telemetry.metrics import REGISTRY

__all__ = ["StepCostEWMA", "Tenant", "Router"]

_EST_G = REGISTRY.gauge(
    "mxtpu_step_cost_est_us",
    "Live per-(endpoint, bucket) step-cost estimate: the cost-model prior "
    "while a bucket is cold, the measured EWMA once observed.",
    labelnames=("endpoint", "bucket"))


def _cfg(name, default):
    try:
        from .. import config
        return config.get(name, default)
    except Exception:
        return default


class StepCostEWMA:
    """Per-bucket exponentially-weighted moving average of device step time.

    ``observe(bucket, us)`` is fed by every executed batch (and by warmup's
    one execution per bucket, so estimates exist before the first request).
    ``estimate(bucket)`` falls back to the nearest observed bucket scaled by
    the row ratio — a crude linear-in-rows model that is only used until the
    real bucket has been observed once.

    With a ``prior`` hook (``bucket -> predicted_us | None``, the learned
    cost model via ``telemetry.costmodel.make_prior``), never-seen buckets
    are priced by prediction instead of row-ratio, and a just-seen bucket
    blends linearly from prior to measured over ``blend_n`` observations
    (``MXNET_COSTMODEL_BLEND_N`` when unset) — measured always wins once the
    bucket is warm, so scheduling with a prior converges to exactly the
    no-prior behavior. The prior is consulted once per bucket and cached;
    it runs *outside* the internal lock (it may take the ledger ring lock).
    ``name`` labels the live ``mxtpu_step_cost_est_us`` gauge; anonymous
    instances export nothing.
    """

    def __init__(self, alpha: float = 0.25, name: Optional[str] = None,
                 prior: Optional[Callable[[int], Optional[float]]] = None,
                 blend_n: Optional[int] = None):
        self.alpha = float(alpha)
        self.name = name
        self._prior_fn = prior
        self._blend_n_pinned = blend_n
        self._lock = threading.Lock()
        self._est: Dict[int, float] = {}
        self._n: Dict[int, int] = {}
        self._prior_cache: Dict[int, Optional[float]] = {}

    def _blend_n(self) -> int:
        if self._blend_n_pinned is not None:
            return max(0, int(self._blend_n_pinned))
        return max(0, int(_cfg("MXNET_COSTMODEL_BLEND_N", 5)))

    def _gauge(self, bucket: int, value: float):
        if self.name is None:
            return
        try:
            # bounded: buckets come from the fixed padding ladder
            _EST_G.labels(
                self.name, str(bucket)).set(value)  # mxlint: disable=MET301
        except Exception:
            pass

    def _prior_for(self, bucket: int) -> Optional[float]:
        """Cached prior for a bucket; computed outside ``_lock``."""
        if self._prior_fn is None:
            return None
        with self._lock:
            if bucket in self._prior_cache:
                return self._prior_cache[bucket]
            measured = bucket in self._est
        try:
            v = self._prior_fn(bucket)
        except Exception:
            v = None
        if v is not None and (v <= 0 or v != v):
            v = None
        with self._lock:
            self._prior_cache[bucket] = v
        if v is not None and not measured:
            self._gauge(bucket, v)
        return v

    def prior(self, bucket: int) -> Optional[float]:
        """The (cached) model prior for ``bucket``, or None without one."""
        return self._prior_for(bucket)

    def observe(self, bucket: int, step_us: float):
        with self._lock:
            prev = self._est.get(bucket)
            self._est[bucket] = step_us if prev is None else \
                prev + self.alpha * (step_us - prev)
            self._n[bucket] = self._n.get(bucket, 0) + 1
            est = self._est[bucket]
        self._gauge(bucket, est)

    def estimate(self, bucket: int) -> float:
        """Estimated step microseconds for ``bucket``. Cold bucket with a
        prior: the prediction. Warming bucket (< blend_n observations):
        linear blend prior -> measured. Otherwise: the measured EWMA, with
        the legacy nearest-bucket row-ratio (or 0.0 on a fully empty
        table) when no prior exists."""
        with self._lock:
            got = self._est.get(bucket)
            n = self._n.get(bucket, 0)
        blend_n = self._blend_n() if self._prior_fn is not None else 0
        prior = None
        if self._prior_fn is not None and (got is None or n < blend_n):
            prior = self._prior_for(bucket)
        if got is None:
            if prior is not None:
                return prior
            with self._lock:
                if not self._est:
                    return 0.0
                nearest = min(self._est, key=lambda b: abs(b - bucket))
                return self._est[nearest] * (bucket / nearest)
        if prior is not None and n < blend_n:
            w = (blend_n - n) / float(blend_n)
            return w * prior + (1.0 - w) * got
        return got

    def snapshot(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._est)

    def snapshot_detail(self) -> Dict[str, object]:
        """Measured + prior + blend state per bucket, for /statusz and
        /costz (``snapshot()`` keeps its legacy measured-only shape)."""
        with self._lock:
            buckets = sorted(set(self._est) | set(self._prior_cache))
            detail = {
                int(b): {
                    "measured_us": self._est.get(b),
                    "n": self._n.get(b, 0),
                    "prior_us": self._prior_cache.get(b),
                }
                for b in buckets
            }
        blend_n = self._blend_n() if self._prior_fn is not None else 0
        for b, info in detail.items():
            info["est_us"] = self.estimate(b)
        return {"buckets": detail, "prior": self._prior_fn is not None,
                "blend_n": blend_n}


class Tenant:
    """One endpoint's seat at the scheduler: its queue, its circuit breaker
    (per-tenant shedding: this tenant's overload degrades this tenant's
    admission, not the whole server), its optional SLO (``slo_us`` is
    both the scheduling deadline default and the latency objective the SLO
    monitor burns against ``slo_target``), and its brownout criticality
    ``tier`` (gold/silver/bulk — what the degradation ladder sheds first)."""

    __slots__ = ("name", "endpoint", "queue", "breaker", "slo_us",
                 "slo_target", "tier")

    def __init__(self, name: str, endpoint, queue: EndpointQueue,
                 breaker, slo_us: Optional[int] = None,
                 slo_target: Optional[float] = None, tier: str = "gold"):
        self.name = name
        self.endpoint = endpoint
        self.queue = queue
        self.breaker = breaker
        self.slo_us = slo_us
        self.slo_target = slo_target
        self.tier = tier


class Router:
    """EDF-with-measured-step-cost selection across registered tenants.

    All methods except nothing are called with the server's condition lock
    held; the Router adds no locking of its own.
    """

    def __init__(self, batch_timeout_us: int, starvation_factor: float = 8.0):
        self.batch_timeout_us = int(batch_timeout_us)
        self.starvation_factor = float(starvation_factor)
        self._tenants: Dict[str, Tenant] = {}

    # -- registry -----------------------------------------------------------
    def add(self, tenant: Tenant):
        self._tenants[tenant.name] = tenant

    def get(self, name: str) -> Tenant:
        return self._tenants[name]

    def find(self, name: str) -> Optional[Tenant]:
        return self._tenants.get(name)

    def names(self) -> List[str]:
        return sorted(self._tenants)

    def tenants(self) -> List[Tenant]:
        return list(self._tenants.values())

    def __contains__(self, name: str) -> bool:
        return name in self._tenants

    # -- scheduling inputs --------------------------------------------------
    def effective_batch_timeout_us(self) -> float:
        """The batching deadline in force right now: the configured timeout
        widened by the brownout ladder (level >= 1 trades per-request
        latency for fuller batches before anyone is refused)."""
        from .batcher import brownout_timeout_boost
        return self.batch_timeout_us * brownout_timeout_boost()

    def est_step_us(self, tenant: Tenant) -> float:
        """Estimated device time of the batch this tenant would run next:
        the EWMA for the bucket its pending prefix actually lands in."""
        ep = tenant.endpoint
        rows = min(max(tenant.queue.pending_rows, 1), ep.max_batch_size)
        return ep.step_cost.estimate(bucketing.bucket_for(rows, ep.buckets))

    def effective_deadline_us(self, tenant: Tenant) -> int:
        """Head request's deadline, or enqueue + SLO, or the batch deadline."""
        head_dl = tenant.queue.head_deadline_us()
        if head_dl is not None:
            return head_dl
        budget = tenant.slo_us if tenant.slo_us \
            else self.effective_batch_timeout_us()
        return int(tenant.queue.head_enqueue_us() + budget)

    def slack_us(self, tenant: Tenant, now_us: int) -> float:
        return self.effective_deadline_us(tenant) - now_us - \
            self.est_step_us(tenant)

    def _starvation_us(self, tenant: Tenant) -> float:
        return self.starvation_factor * \
            (self.effective_batch_timeout_us() + self.est_step_us(tenant))

    # -- the decision -------------------------------------------------------
    def select(self, now_us: int, flush: bool = False) -> Optional[Tenant]:
        """The next tenant to assemble a batch for, or None when no queue is
        ready. See the module docstring for the policy."""
        ready = [t for t in self._tenants.values()
                 if t.queue.ready(now_us, flush)]
        if not ready:
            return None
        if len(ready) == 1:
            return ready[0]
        meetable: List[Tuple[float, Tenant]] = []
        late: List[Tenant] = []
        for t in ready:
            s = self.slack_us(t, now_us)
            if s >= 0:
                meetable.append((s, t))
            else:
                late.append(t)
        if meetable:
            return min(meetable, key=lambda st: st[0])[1]
        starving = [t for t in late
                    if now_us - t.queue.head_enqueue_us() >
                    self._starvation_us(t)]
        if starving:
            return min(starving, key=lambda t: t.queue.head_enqueue_us())
        return min(late, key=self.est_step_us)

    # -- bookkeeping for the dispatch loops ---------------------------------
    def pending_requests(self) -> int:
        return sum(len(t.queue) for t in self._tenants.values())

    def next_wakeup_us(self) -> Optional[int]:
        wakeups = [w for t in self._tenants.values()
                   for w in (t.queue.next_wakeup_us(),) if w is not None]
        return min(wakeups) if wakeups else None

    def fail_all(self, exc: Exception) -> int:
        """Fail every queued request (non-drain stop / abandoned drain);
        returns how many requests were failed."""
        n = 0
        for t in self._tenants.values():
            n += len(t.queue)
            t.queue.fail_all(exc)
        return n
