"""ModelEndpoint: a loaded model plus its shape-bucketed executable cache.

One endpoint owns one inference program — a HybridBlock (including
``quantize_net``-converted int8 graphs and bf16-cast nets) or a SymbolBlock
reloaded from an exported checkpoint — traced once through the same
``pure_apply`` primitive CachedOp uses (gluon/block.py), then AOT-compiled per
shape bucket with ``jax.jit(...).lower(avals).compile()``. Compiling through
the AOT path (instead of letting ``jax.jit`` cache internally) makes the
executable cache explicit: the endpoint counts every compile, so the
"recompiles only once per bucket" property is assertable, and ``warmup()``
can pre-build every bucket at load time so no request ever pays a compile.

Params ride as executable *arguments*, not closure constants (PERF.md round-4
lesson: constants bloat the compile payload), so a checkpoint reload swaps
weights without invalidating the compiled buckets.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as onp

from ..base import Context, DTypes, MXNetError, current_context
from ..ndarray.ndarray import NDArray
from . import bucketing
from .router import StepCostEWMA
from .stats import EndpointStats

__all__ = ["ModelEndpoint"]

# name -> endpoint; the registry behind mxnet_tpu.serving.stats()
_ENDPOINTS: Dict[str, "ModelEndpoint"] = {}
_REG_LOCK = threading.Lock()


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class ModelEndpoint:
    """A named, servable model with bucketed compiled executables.

    Parameters
    ----------
    name : str
        Registry key; ``serving.stats()`` reports under this name.
    block : HybridBlock
        The model. Must be runnable in inference mode. bf16 nets (via
        ``block.cast('bfloat16')``) and ``quantize_net``-converted int8 nets
        are first-class — they trace like any other HybridBlock.
    input_shapes : shape | sequence of shapes
        Per-example shape (without the batch axis) of each model input.
        A single shape tuple means a single-input model.
    dtype : str | sequence of str
        Input dtype(s); requests are cast on the host before device transfer.
    max_batch_size : int
        Largest served batch; also the largest bucket.
    buckets : sequence of int, optional
        Ascending batch-size buckets. Default: powers of two up to
        ``max_batch_size``.
    ctx : Context, optional
        Device the endpoint serves from (default: current context).
    """

    def __init__(self, name: str, block, input_shapes, dtype="float32",
                 max_batch_size: int = 32,
                 buckets: Optional[Sequence[int]] = None,
                 ctx: Optional[Context] = None):
        self.name = name
        self.block = block
        self.ctx = ctx if ctx is not None else current_context()
        self.max_batch_size = int(max_batch_size)
        if self.max_batch_size < 1:
            raise MXNetError("max_batch_size must be >= 1")
        if buckets is None:
            buckets = bucketing.pow2_buckets(self.max_batch_size)
        self.buckets = bucketing.validate_buckets(buckets,
                                                  self.max_batch_size)

        if input_shapes and isinstance(input_shapes[0], int):
            input_shapes = (input_shapes,)
        self.input_shapes: Tuple[Tuple[int, ...], ...] = tuple(
            tuple(int(d) for d in s) for s in input_shapes)
        if isinstance(dtype, (list, tuple)):
            dts = tuple(dtype)
        else:
            dts = (dtype,) * len(self.input_shapes)
        if len(dts) != len(self.input_shapes):
            raise MXNetError("one dtype per input required")
        self._jnp_dtypes = tuple(DTypes.jnp(d) for d in dts)
        self.np_dtypes = tuple(onp.dtype(d) for d in self._jnp_dtypes)

        self.stats = EndpointStats(name)
        self.step_cost = StepCostEWMA()       # per-bucket step-time model
        self._lock = threading.Lock()
        self._execs: Dict[int, object] = {}   # bucket -> compiled executable
        self._jfn = None
        self._params = None                   # ordered Parameter list
        # double-buffer parity slots: the pipeline's prep stage writes the
        # input-buffer set for parity p while the executable reads parity 1-p
        self._parity_bufs: list = [None, None]
        self._probe()

        with _REG_LOCK:
            _ENDPOINTS[name] = self

    # ------------------------------------------------------------------
    # checkpoint loading
    # ------------------------------------------------------------------
    @classmethod
    def from_checkpoint(cls, name: str, symbol_file: str, param_file: str,
                        input_shapes, **kwargs) -> "ModelEndpoint":
        """Load an endpoint from an exported checkpoint (HybridBlock.export's
        ``-symbol.json`` + ``.params``) — no defining Python class needed.
        The export must have been made with ``dynamic_batch=True`` so the
        embedded program accepts every bucket's batch size (a fixed-batch
        export can only ever run at its traced batch)."""
        import json as _json
        from ..gluon.block import SymbolBlock
        with open(symbol_file) as f:
            meta = _json.load(f)
        if not meta.get("dynamic_batch", False):
            raise MXNetError(
                f"{symbol_file} was exported with a fixed batch size; "
                "re-export with HybridBlock.export(..., dynamic_batch=True) "
                "to serve it across shape buckets")
        blk = SymbolBlock.imports(symbol_file, input_names=None,
                                  param_file=param_file)
        return cls(name, blk, input_shapes, **kwargs)

    # ------------------------------------------------------------------
    # model preparation
    # ------------------------------------------------------------------
    def _zeros_batch(self, rows: int):
        return tuple(
            NDArray(onp.zeros((rows,) + s, dt), ctx=self.ctx)
            for s, dt in zip(self.input_shapes, self.np_dtypes))

    def _probe(self):
        """One eager forward with a bucket-1 zero batch: triggers deferred
        parameter init, validates the declared input signature, and records
        the output arity for per-request slicing."""
        from .. import autograd
        dummy = self._zeros_batch(1)
        with autograd._RecordingStateScope(False, False):
            out = self.block(*dummy)
        outs = out if isinstance(out, (list, tuple)) else (out,)
        self.num_outputs = len(outs)
        for o in outs:
            if not (hasattr(o, "shape") and o.shape and o.shape[0] == 1):
                raise MXNetError(
                    f"endpoint {self.name!r}: every model output must be "
                    "batch-major (leading axis = batch) so per-request rows "
                    f"can be sliced back out; got output shape {getattr(o, 'shape', None)}")
        self._params = list(self.block.collect_params().values())

    def _donate_inputs(self) -> bool:
        """Donate input buffers to the executable on backends that implement
        buffer donation (TPU/GPU): the double-buffered pipeline then recycles
        each parity set's memory instead of allocating per step. CPU ignores
        donation (with a warning), so keep it off there. Decided once, before
        the first compile, so every bucket shares one executable signature —
        the compiled-once-per-bucket property is preserved."""
        return self.ctx.jax_device().platform in ("tpu", "gpu")

    def _infer_fn(self):
        if self._jfn is None:
            import jax
            from ..gluon.block import pure_apply
            block, plist = self.block, self._params

            def infer(param_datas, *input_datas):
                outs, _, _ = pure_apply(block, plist, param_datas, input_datas,
                                        None, training=False)
                return outs

            donate = tuple(range(1, 1 + len(self.input_shapes))) \
                if self._donate_inputs() else ()
            self._jfn = jax.jit(infer, donate_argnums=donate)
        return self._jfn

    def _param_datas(self):
        return tuple(p.data(self.ctx).data for p in self._params)

    # ------------------------------------------------------------------
    # the shape-bucketed executable cache
    # ------------------------------------------------------------------
    def _get_executable(self, bucket: int):
        comp = self._execs.get(bucket)
        if comp is not None:
            self.stats.bump("cache_hits")
            return comp
        with self._lock:
            comp = self._execs.get(bucket)
            if comp is not None:
                self.stats.bump("cache_hits")
                return comp
            import jax
            from .. import telemetry
            from ..resilience import faults as _faults
            t0 = _now_us()
            _faults.check("compile")
            with telemetry.span("serving.compile", endpoint=self.name,
                                bucket=bucket):
                param_sds = tuple(
                    jax.ShapeDtypeStruct(tuple(p.shape),
                                         p.data(self.ctx).data.dtype)
                    for p in self._params)
                in_sds = tuple(
                    jax.ShapeDtypeStruct((bucket,) + s, dt)
                    for s, dt in zip(self.input_shapes, self._jnp_dtypes))
                comp = self._infer_fn().lower(param_sds, *in_sds).compile()
            self._execs[bucket] = comp
            self.stats.record_compile(_now_us() - t0)
            return comp

    def warmup(self, execute: bool = True):
        """Compile (and by default execute once) every bucket, so serving
        traffic never hits a compile — first-request latency is steady-state
        latency. Each warmup execution is timed into ``step_cost``, seeding
        the scheduler's per-bucket EWMA before the first real request.
        Returns the number of buckets compiled."""
        import jax
        n = 0
        for b in self.buckets:
            fresh = b not in self._execs
            comp = self._get_executable(b)
            if fresh:
                n += 1
                if execute:
                    ins = tuple(a.data for a in self._zeros_batch(b))
                    t0 = _now_us()
                    jax.block_until_ready(comp(self._param_datas(), *ins))
                    self.step_cost.observe(b, _now_us() - t0)
        return n

    # ------------------------------------------------------------------
    # execution: prepare (host half) / execute (device half)
    # ------------------------------------------------------------------
    def prepare(self, host_inputs: Sequence[onp.ndarray], rows: int,
                parity: int = 0):
        """Host half of one batch step: pad pre-concatenated host inputs to
        the shape bucket and transfer them into the ``parity`` input-buffer
        set. Safe to run on the pipeline's prep thread while the worker
        executes the other parity — it never touches a compiled executable.

        Returns ``(device_inputs, bucket, padded_host)``; ``padded_host`` is
        kept with the prepared batch so a retry can rebuild donated buffers.
        """
        import jax
        bucket = bucketing.bucket_for(rows, self.buckets)
        padded = tuple(bucketing.pad_rows(a, bucket) for a in host_inputs)
        dev = self.ctx.jax_device()
        ins = tuple(jax.device_put(a, dev) for a in padded)
        self._parity_bufs[parity % 2] = (bucket, ins)
        return ins, bucket, padded

    def execute(self, device_inputs, bucket: int, rows: int,
                padded_host: Optional[Sequence[onp.ndarray]] = None):
        """Device half: run the bucket's cached executable over prepared
        input buffers. Worker-thread only (the single-dispatcher rule).
        Returns a tuple of device output arrays with ``bucket`` rows each;
        callers slice [0:rows] back out per request."""
        import jax
        from .. import telemetry
        comp = self._get_executable(bucket)
        # a donated executable consumed these buffers on a previous (failed)
        # attempt: rebuild them from the retained padded host copy
        if padded_host is not None and any(
                getattr(a, "is_deleted", lambda: False)()
                for a in device_inputs):
            dev = self.ctx.jax_device()
            device_inputs = tuple(jax.device_put(a, dev) for a in padded_host)
        # child of the caller's serving.batch span (same thread): the trace
        # id stamped at submit reaches the compiled device step
        with telemetry.span("serving.device_step", endpoint=self.name,
                            bucket=bucket, rows=rows):
            t0 = _now_us()
            outs = comp(self._param_datas(), *device_inputs)
            jax.block_until_ready(outs)
            self.step_cost.observe(bucket, _now_us() - t0)
        self.stats.bump("batches")
        self.stats.bump("real_rows", rows)
        self.stats.bump("padded_rows", bucket - rows)
        return outs

    def run_batch(self, host_inputs: Sequence[onp.ndarray], rows: int):
        """Serial prepare-then-step over pre-concatenated host inputs (the
        pre-pipeline dispatch path; kept for direct callers and as the
        bitwise reference the pipelined path is tested against).

        Returns (outputs, bucket) exactly as before the prepare/execute
        split."""
        ins, bucket, padded = self.prepare(host_inputs, rows)
        outs = self.execute(ins, bucket, rows, padded_host=padded)
        return outs, bucket

    def __repr__(self):
        return (f"ModelEndpoint({self.name!r}, inputs={self.input_shapes}, "
                f"buckets={self.buckets})")


def get_endpoint(name: str) -> ModelEndpoint:
    with _REG_LOCK:
        if name not in _ENDPOINTS:
            raise MXNetError(f"unknown endpoint {name!r}; registered: "
                             f"{sorted(_ENDPOINTS)}")
        return _ENDPOINTS[name]


def list_endpoints():
    with _REG_LOCK:
        return sorted(_ENDPOINTS)


def unregister(name: str):
    with _REG_LOCK:
        _ENDPOINTS.pop(name, None)
