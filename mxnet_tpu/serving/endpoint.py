"""ModelEndpoint: a loaded model plus its shape-bucketed executable cache.

One endpoint owns one inference program — a HybridBlock (including
``quantize_net``-converted int8 graphs and bf16-cast nets) or a SymbolBlock
reloaded from an exported checkpoint — traced once through the same
``pure_apply`` primitive CachedOp uses (gluon/block.py), then AOT-compiled per
shape bucket with ``jax.jit(...).lower(avals).compile()``. Compiling through
the AOT path (instead of letting ``jax.jit`` cache internally) makes the
executable cache explicit: the endpoint counts every compile, so the
"recompiles only once per bucket" property is assertable, and ``warmup()``
can pre-build every bucket at load time so no request ever pays a compile.

Params ride as executable *arguments*, not closure constants (PERF.md round-4
lesson: constants bloat the compile payload), so a checkpoint reload swaps
weights without invalidating the compiled buckets.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as onp

from ..base import Context, DTypes, MXNetError, current_context
from .. import telemetry as _telemetry
from ..ndarray.ndarray import NDArray
from . import bucketing
from .errors import HotSwapError
from .router import StepCostEWMA
from .stats import EndpointStats

__all__ = ["ModelEndpoint"]

_HOT_SWAPS = _telemetry.counter(
    "mxtpu_serving_hot_swaps_total",
    "Weight hot-swap attempts by outcome: ok (staged, probe-validated, "
    "committed) / rolled_back (probe validation failed; old weights kept) / "
    "rejected (corrupt or mismatched checkpoint, refused before staging).",
    labelnames=("outcome",))

# name -> endpoint; the registry behind mxnet_tpu.serving.stats()
_ENDPOINTS: Dict[str, "ModelEndpoint"] = {}
_REG_LOCK = threading.Lock()


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class ModelEndpoint:
    """A named, servable model with bucketed compiled executables.

    Parameters
    ----------
    name : str
        Registry key; ``serving.stats()`` reports under this name.
    block : HybridBlock
        The model. Must be runnable in inference mode. bf16 nets (via
        ``block.cast('bfloat16')``) and ``quantize_net``-converted int8 nets
        are first-class — they trace like any other HybridBlock.
    input_shapes : shape | sequence of shapes
        Per-example shape (without the batch axis) of each model input.
        A single shape tuple means a single-input model.
    dtype : str | sequence of str
        Input dtype(s); requests are cast on the host before device transfer.
    max_batch_size : int
        Largest served batch; also the largest bucket.
    buckets : sequence of int, optional
        Ascending batch-size buckets. Default: powers of two up to
        ``max_batch_size``.
    ctx : Context, optional
        Device the endpoint serves from (default: current context).
    """

    #: devices one replica of this endpoint occupies — the weight
    #: ServingPool.submit divides queue load by, so a 4-chip sharded
    #: replica attracts ~4x a single-chip one's share
    capacity = 1

    #: cost-model site label: the stream step observations and priors are
    #: keyed by in the ledger, metrics and residual drift detection
    cost_site = "serving_step"

    def __init__(self, name: str, block, input_shapes, dtype="float32",
                 max_batch_size: int = 32,
                 buckets: Optional[Sequence[int]] = None,
                 ctx: Optional[Context] = None):
        self.name = name
        self.block = block
        self.ctx = ctx if ctx is not None else current_context()
        self.max_batch_size = int(max_batch_size)
        if self.max_batch_size < 1:
            raise MXNetError("max_batch_size must be >= 1")
        if buckets is None:
            buckets = bucketing.pow2_buckets(self.max_batch_size)
        self.buckets = bucketing.validate_buckets(buckets,
                                                  self.max_batch_size)

        if input_shapes and isinstance(input_shapes[0], int):
            input_shapes = (input_shapes,)
        self.input_shapes: Tuple[Tuple[int, ...], ...] = tuple(
            tuple(int(d) for d in s) for s in input_shapes)
        if isinstance(dtype, (list, tuple)):
            dts = tuple(dtype)
        else:
            dts = (dtype,) * len(self.input_shapes)
        if len(dts) != len(self.input_shapes):
            raise MXNetError("one dtype per input required")
        self._jnp_dtypes = tuple(DTypes.jnp(d) for d in dts)
        self.np_dtypes = tuple(onp.dtype(d) for d in self._jnp_dtypes)

        self.stats = EndpointStats(name)
        # per-bucket step-time model: measured EWMA, with the learned cost
        # model (when MXNET_COSTMODEL_PATH is active) pricing never-seen
        # buckets through the prior hook — sharded subclasses inherit this
        # with their mesh-labeled _compile_key, so the prior is per-slice
        from ..telemetry import costmodel as _costmodel
        self.step_cost = StepCostEWMA(
            name=name,
            prior=_costmodel.make_prior(self.cost_site, self._compile_key))
        self._lock = threading.Lock()
        self._execs: Dict[int, object] = {}   # bucket -> compiled executable
        self._jfn = None
        self._params = None                   # ordered Parameter list
        # hot-swap state: once a swap commits, _active_params (device
        # arrays) is the weight set executables run with; the reference is
        # swapped atomically at a batch boundary by the dispatching thread,
        # so no batch ever sees a half-loaded model
        self._active_params: Optional[Tuple] = None
        self._weights_epoch = 0
        # parity slots of the host pipeline: the prep stage writes the
        # input-buffer set for parity p while the executable reads another;
        # a depth-d pipeline keeps at most d+1 batches alive, so slots are
        # keyed by parity mod (depth+1) — sized lazily as parities appear
        self._parity_bufs: Dict[int, tuple] = {}
        # zero-copy ingest: preallocated host staging buffers, one set per
        # (bucket, parity slot) — request rows are written in place instead
        # of concatenated, so steady state allocates nothing per batch
        self._staging: Dict[tuple, tuple] = {}
        self._probe()

        with _REG_LOCK:
            _ENDPOINTS[name] = self

    # ------------------------------------------------------------------
    # checkpoint loading
    # ------------------------------------------------------------------
    @classmethod
    def from_checkpoint(cls, name: str, symbol_file: str, param_file: str,
                        input_shapes, **kwargs) -> "ModelEndpoint":
        """Load an endpoint from an exported checkpoint (HybridBlock.export's
        ``-symbol.json`` + ``.params``) — no defining Python class needed.
        The export must have been made with ``dynamic_batch=True`` so the
        embedded program accepts every bucket's batch size (a fixed-batch
        export can only ever run at its traced batch)."""
        import json as _json
        from ..gluon.block import SymbolBlock
        with open(symbol_file) as f:
            meta = _json.load(f)
        if not meta.get("dynamic_batch", False):
            raise MXNetError(
                f"{symbol_file} was exported with a fixed batch size; "
                "re-export with HybridBlock.export(..., dynamic_batch=True) "
                "to serve it across shape buckets")
        blk = SymbolBlock.imports(symbol_file, input_names=None,
                                  param_file=param_file)
        return cls(name, blk, input_shapes, **kwargs)

    # ------------------------------------------------------------------
    # model preparation
    # ------------------------------------------------------------------
    def _zeros_batch(self, rows: int):
        return tuple(
            NDArray(onp.zeros((rows,) + s, dt), ctx=self.ctx)
            for s, dt in zip(self.input_shapes, self.np_dtypes))

    def _probe(self):
        """One eager forward with a bucket-1 zero batch: triggers deferred
        parameter init, validates the declared input signature, and records
        the output arity for per-request slicing."""
        from .. import autograd
        dummy = self._zeros_batch(1)
        with autograd._RecordingStateScope(False, False):
            out = self.block(*dummy)
        outs = out if isinstance(out, (list, tuple)) else (out,)
        self.num_outputs = len(outs)
        for o in outs:
            if not (hasattr(o, "shape") and o.shape and o.shape[0] == 1):
                raise MXNetError(
                    f"endpoint {self.name!r}: every model output must be "
                    "batch-major (leading axis = batch) so per-request rows "
                    f"can be sliced back out; got output shape {getattr(o, 'shape', None)}")
        self._params = list(self.block.collect_params().values())
        # HBM attribution: the weight set actually served (post-hot-swap
        # device arrays when present) and the pipeline's double-buffered
        # input sets, sized live at every memstats reconcile
        from ..telemetry import memstats as _memstats
        _memstats.register(
            "serving", f"{self.name}.params", owner=self,
            device=self._device_label(),
            sizer=lambda ep: _memstats.nbytes_of(ep._param_datas()))
        _memstats.register(
            "serving", f"{self.name}.parity_bufs", owner=self,
            device=self._device_label(),
            sizer=lambda ep: _memstats.nbytes_of(
                [slot[1] for slot in ep._parity_bufs.values() if slot]))

    def _device_label(self) -> str:
        """The memstats/ledger device label ('cpu:0', 'tpu:3', ...)."""
        try:
            d = self.ctx.jax_device()
            return f"{d.platform}:{d.id}"
        except (AttributeError, RuntimeError, ValueError, ImportError):
            # no jax device behind this ctx (stub backends) — holders
            # registered with an empty label roll up under "unassigned"
            return ""

    def _donate_inputs(self) -> bool:
        """Donate input buffers to the executable on backends that implement
        buffer donation (TPU/GPU): the double-buffered pipeline then recycles
        each parity set's memory instead of allocating per step. CPU ignores
        donation (with a warning), so keep it off there. Decided once, before
        the first compile, so every bucket shares one executable signature —
        the compiled-once-per-bucket property is preserved."""
        return self.ctx.jax_device().platform in ("tpu", "gpu")

    def _place_inputs(self, arrays):
        """Host->device placement of one batch's input arrays. The hook a
        mesh-sharded endpoint overrides (NamedSharding placement); the base
        endpoint puts everything on its single context device."""
        import jax
        dev = self.ctx.jax_device()
        return tuple(jax.device_put(a, dev) for a in arrays)

    def _jit_infer(self, infer, donate):
        """Wrap the traced inference function in ``jax.jit``. Sharded
        endpoints override to pin NamedSharding in/out shardings."""
        import jax
        return jax.jit(infer, donate_argnums=donate)

    def _infer_fn(self):
        if self._jfn is None:
            from ..gluon.block import pure_apply
            block, plist = self.block, self._params

            def infer(param_datas, *input_datas):
                outs, _, _ = pure_apply(block, plist, param_datas, input_datas,
                                        None, training=False)
                return outs

            donate = tuple(range(1, 1 + len(self.input_shapes))) \
                if self._donate_inputs() else ()
            self._jfn = self._jit_infer(infer, donate)
        return self._jfn

    def _param_datas(self):
        if self._active_params is not None:
            return self._active_params
        return tuple(p.data(self.ctx).data for p in self._params)

    @property
    def weights_epoch(self) -> int:
        """Monotonic hot-swap generation of the weights currently served."""
        return self._weights_epoch

    # ------------------------------------------------------------------
    # the shape-bucketed executable cache
    # ------------------------------------------------------------------
    def _compile_key(self, bucket: int) -> Dict[str, object]:
        """The compile-ledger / executable-cache trigger key for one bucket.
        Everything in it must be stable across process restarts that should
        share cached executables — a sharded endpoint overrides the device
        entry with its slice *shape* so a restarted replica on the same
        slice topology hits the fleet cache instead of recompiling."""
        return {"endpoint": self.name, "bucket": bucket,
                "dtype": str(self._jnp_dtypes[0].__name__
                             if hasattr(self._jnp_dtypes[0], "__name__")
                             else self._jnp_dtypes[0]),
                "device": self._device_label()}

    def _get_executable(self, bucket: int):
        comp = self._execs.get(bucket)
        if comp is not None:
            self.stats.bump("cache_hits")
            return comp
        with self._lock:
            comp = self._execs.get(bucket)
            if comp is not None:
                self.stats.bump("cache_hits")
                return comp
            import jax
            from .. import telemetry
            from ..telemetry import compile_ledger as _ledger
            from ..telemetry import memstats as _memstats
            from ..resilience import faults as _faults
            t0 = _now_us()
            _faults.check("compile")
            with telemetry.span("serving.compile", endpoint=self.name,
                                bucket=bucket):
                param_sds = tuple(
                    jax.ShapeDtypeStruct(tuple(p.shape),
                                         p.data(self.ctx).data.dtype)
                    for p in self._params)
                in_sds = tuple(
                    jax.ShapeDtypeStruct((bucket,) + s, dt)
                    for s, dt in zip(self.input_shapes, self._jnp_dtypes))
                # compiling under the endpoint lock is the compile-once
                # gate: contenders need this bucket's executable and must
                # wait for it either way (a double-checked compile outside
                # the lock would just duplicate device compilations)
                comp = _ledger.lower_and_compile(  # mxlint: disable=CONC202
                    self._infer_fn(), (param_sds,) + in_sds,
                    site="serving_bucket", key=self._compile_key(bucket),
                    expect_donation=self._donate_inputs())
            self._adopt_compiled(comp)
            self._execs[bucket] = comp
            # attribute the executable's own device footprint (output +
            # scratch + generated code; arguments belong to params/inputs)
            mem = _ledger._memory_analysis(comp)
            _memstats.register(
                "serving", f"{self.name}.exec_b{bucket}", owner=self,
                device=self._device_label(),
                nbytes=sum(mem.get(k, 0) for k in
                           ("output_bytes", "temp_bytes", "code_bytes")))
            self.stats.record_compile(_now_us() - t0)
            return comp

    def warmup(self, execute: bool = True):
        """Compile (and by default execute once) every bucket, so serving
        traffic never hits a compile — first-request latency is steady-state
        latency. Each warmup execution is timed into ``step_cost``, seeding
        the scheduler's per-bucket EWMA before the first real request.
        Returns the number of buckets compiled."""
        import jax
        n = 0
        for b in self.buckets:
            fresh = b not in self._execs
            comp = self._get_executable(b)
            if fresh:
                n += 1
                if execute:
                    ins = self._warmup_inputs(b)
                    t0 = _now_us()
                    jax.block_until_ready(comp(self._param_datas(), *ins))
                    self._observe_step(b, _now_us() - t0)
        return n

    def predicted_warmup_s(self, fresh: bool = True) -> float:
        """Cost-model predicted cold-compile wall (seconds) to warm every
        bucket — the autoscaler's scale-up lead time for a replica that
        starts with an empty executable cache. ``fresh=False`` prices only
        the buckets this instance has not compiled yet. 0.0 without an
        active model (the autoscaler then behaves exactly as before)."""
        try:
            from ..telemetry import costmodel as _costmodel
            total = 0.0
            for b in self.buckets:
                if not fresh and b in self._execs:
                    continue
                v = _costmodel.predict_compile_s(self._compile_key(b),
                                                 site="serving_bucket")
                if v:
                    total += float(v)
            return total
        except Exception:
            return 0.0

    def _observe_step(self, bucket: int, us: float,
                      rows: Optional[int] = None):
        """Feed one measured device step: the scheduler's EWMA always, and
        the cost observatory (rate-limited kind="step" ledger record +
        predicted-vs-measured residual) when telemetry is live."""
        self.step_cost.observe(bucket, us)
        try:
            from ..telemetry import costmodel as _costmodel
            _costmodel.on_step_observed(
                self.cost_site, self._compile_key(bucket), bucket, us,
                rows=rows, prior_us=self.step_cost.prior(bucket))
        except Exception:
            pass

    def _warmup_inputs(self, bucket: int):
        """Zero inputs for one warmup execution of ``bucket``."""
        return tuple(a.data for a in self._zeros_batch(bucket))

    def _adopt_compiled(self, comp):
        """Hook: inspect a just-obtained executable before first use.
        Sharded endpoints adopt a cache-deserialized executable's device
        assignment here; the single-device path needs nothing."""

    # ------------------------------------------------------------------
    # execution: prepare (host half) / execute (device half)
    # ------------------------------------------------------------------
    def staging_buffers(self, bucket: int, parity: int):
        """Preallocated host staging buffers for one (bucket, parity slot):
        the zero-copy prep path writes request rows straight into these and
        zeroes the padding tail, instead of concat + pad allocating per
        batch. The parity discipline that protects the device-side buffer
        sets protects these too — the slot being written is never the slot
        an in-flight batch still references."""
        key = (int(bucket), int(parity))
        bufs = self._staging.get(key)
        if bufs is None:
            bufs = tuple(onp.zeros((bucket,) + s, dt)
                         for s, dt in zip(self.input_shapes, self.np_dtypes))
            self._staging[key] = bufs
        return bufs

    def prepare(self, host_inputs: Sequence[onp.ndarray], rows: int,
                parity: int = 0):
        """Host half of one batch step: pad pre-concatenated host inputs to
        the shape bucket and transfer them into the ``parity`` input-buffer
        set. Safe to run on the pipeline's prep thread while the worker
        executes the other parity — it never touches a compiled executable.

        Returns ``(device_inputs, bucket, padded_host)``; ``padded_host`` is
        kept with the prepared batch so a retry can rebuild donated buffers.
        """
        bucket = bucketing.bucket_for(rows, self.buckets)
        padded = tuple(bucketing.pad_rows(a, bucket) for a in host_inputs)
        ins = self._place_inputs(padded)
        self._parity_bufs[parity] = (bucket, ins)
        return ins, bucket, padded

    def execute(self, device_inputs, bucket: int, rows: int,
                padded_host: Optional[Sequence[onp.ndarray]] = None):
        """Device half: run the bucket's cached executable over prepared
        input buffers. Worker-thread only (the single-dispatcher rule).
        Returns a tuple of device output arrays with ``bucket`` rows each;
        callers slice [0:rows] back out per request."""
        import jax
        from .. import telemetry
        comp = self._get_executable(bucket)
        # a donated executable consumed these buffers on a previous (failed)
        # attempt: rebuild them from the retained padded host copy
        if padded_host is not None and any(
                getattr(a, "is_deleted", lambda: False)()
                for a in device_inputs):
            device_inputs = self._place_inputs(padded_host)
        # child of the caller's serving.batch span (same thread): the trace
        # id stamped at submit reaches the compiled device step
        with telemetry.span("serving.device_step", endpoint=self.name,
                            bucket=bucket, rows=rows):
            t0 = _now_us()
            outs = comp(self._param_datas(), *device_inputs)
            jax.block_until_ready(outs)
            self._observe_step(bucket, _now_us() - t0, rows=rows)
        self.stats.bump("batches")
        self.stats.bump("real_rows", rows)
        self.stats.bump("padded_rows", bucket - rows)
        return outs

    def run_batch(self, host_inputs: Sequence[onp.ndarray], rows: int):
        """Serial prepare-then-step over pre-concatenated host inputs (the
        pre-pipeline dispatch path; kept for direct callers and as the
        bitwise reference the pipelined path is tested against).

        Returns (outputs, bucket) exactly as before the prepare/execute
        split."""
        ins, bucket, padded = self.prepare(host_inputs, rows)
        outs = self.execute(ins, bucket, rows, padded_host=padded)
        return outs, bucket

    # ------------------------------------------------------------------
    # zero-downtime weight hot-swap
    # ------------------------------------------------------------------
    def save_checkpoint(self, manager, step: int, probe_seed: int = 0):
        """Producer-side half of hot-swap: write this endpoint's weights as
        an atomic, checksummed serving checkpoint (CheckpointManager layout)
        *plus a recorded probe*: a seeded random smallest-bucket batch and
        the outputs these exact weights produce for it. A consumer's
        ``hot_swap`` replays the probe against the staged weights and
        requires bitwise-equal outputs before cutting over — corrupt bytes,
        a mixed-up param file, or a wrong-architecture checkpoint all fail
        validation instead of reaching clients.

        Call this from the training/export job (or a stopped endpoint) —
        it invokes a compiled executable, so inside a live server it belongs
        to the worker thread only."""
        from ..resilience.checkpoint import capture_state
        bucket = self.buckets[0]
        rng = onp.random.RandomState(probe_seed & 0x7FFFFFFF)
        probe_in = tuple(
            rng.standard_normal((bucket,) + s).astype(dt)
            for s, dt in zip(self.input_shapes, self.np_dtypes))
        import jax
        comp = self._get_executable(bucket)
        ins = self._place_inputs(probe_in)
        outs = comp(self._param_datas(), *ins)
        jax.block_until_ready(outs)
        state = capture_state(block=self.block, include_rng=False)
        state["serving"] = {
            "bucket": int(bucket), "probe_seed": int(probe_seed),
            "probe": {f"i{i}": a for i, a in enumerate(probe_in)},
            "expected": {f"o{i}": onp.asarray(jax.device_get(o))
                         for i, o in enumerate(outs)},
        }
        return manager.save(step, state=state)

    def load_swap_source(self, source):
        """Resolve a hot-swap source into ``(host_params, probe, label)``
        WITHOUT touching the served weights. ``source`` may be a checkpoint
        directory (a single ``ckpt-*`` dir or a CheckpointManager root, in
        which case the newest intact checkpoint is used — every file is
        checksum-verified first), or an explicit state tree as written by
        :meth:`save_checkpoint` / ``capture_state(block=...)``. Raises
        HotSwapError on corruption or model mismatch — the caller never
        stages bad weights."""
        import os
        from ..resilience.checkpoint import verify_checkpoint_dir
        label = "<state>"
        state = None
        if isinstance(source, str):
            label = source
            try:
                if os.path.isfile(os.path.join(source, "MANIFEST.json")):
                    state = verify_checkpoint_dir(source)
                else:
                    names = sorted(n for n in os.listdir(source)
                                   if n.startswith("ckpt-"))
                    for name in reversed(names):
                        try:
                            state = verify_checkpoint_dir(
                                os.path.join(source, name))
                            label = os.path.join(source, name)
                            break
                        except Exception:
                            continue
            except OSError as e:
                raise HotSwapError(f"cannot read swap source {source!r}: {e}")
            if state is None:
                _HOT_SWAPS.labels("rejected").inc()
                raise HotSwapError(
                    f"no intact checkpoint under {source!r}: every candidate "
                    "failed checksum verification")
        elif isinstance(source, dict):
            state = source
        else:
            raise HotSwapError(
                f"unsupported hot_swap source {type(source).__name__}; pass "
                "a checkpoint directory or a state tree")
        mod = state.get("model")
        if mod is None:
            _HOT_SWAPS.labels("rejected").inc()
            raise HotSwapError(
                f"swap source {label} has no 'model' component "
                f"(holds {sorted(state)})")
        try:
            n = int(mod["n_params"])
            if n != len(self._params):
                raise HotSwapError(
                    f"checkpoint holds {n} params, endpoint {self.name!r} "
                    f"serves {len(self._params)} ({mod.get('param_names')})")
            host = []
            for i, p in enumerate(self._params):
                arr = onp.asarray(mod["params"][f"p{i}"])
                if tuple(arr.shape) != tuple(p.shape):
                    raise HotSwapError(
                        f"checkpoint param {i} shape {arr.shape} != endpoint "
                        f"param shape {tuple(p.shape)}")
                host.append(arr)
        except (KeyError, TypeError, ValueError) as e:
            _HOT_SWAPS.labels("rejected").inc()
            raise HotSwapError(f"malformed swap source {label}: {e!r}")
        except HotSwapError:
            _HOT_SWAPS.labels("rejected").inc()
            raise
        probe = state.get("serving")
        return host, probe, label

    def _place_params(self, arrays):
        """Host->device placement of a full weight set (hot-swap staging).
        Sharded endpoints override with their per-param NamedShardings."""
        import jax
        dev = self.ctx.jax_device()
        return tuple(jax.device_put(a, dev) for a in arrays)

    def stage_weights(self, host_params):
        """Transfer new weights into fresh device buffers (the off-parity
        set: in-flight steps keep reading the old arrays untouched). Host
        work only — safe off the worker thread."""
        return self._place_params(tuple(
            a.astype(p.data(self.ctx).data.dtype, copy=False)
            if onp.dtype(a.dtype) != p.data(self.ctx).data.dtype else a
            for a, p in zip(host_params, self._params)))

    def validate_and_commit(self, staged, probe=None) -> dict:
        """Dispatcher-thread half of a hot-swap: run the validation probe
        against the STAGED weights (the serving weights are untouched), then
        cut over atomically. With a recorded probe (``save_checkpoint``),
        the staged outputs must be bitwise-equal to the recorded ones;
        without one, outputs must at least be finite. Any validation failure
        raises HotSwapError with nothing committed — automatic rollback."""
        import jax
        if probe is not None:
            bucket = int(probe["bucket"])
            ins_h = [onp.asarray(probe["probe"][f"i{i}"])
                     for i in range(len(self.input_shapes))]
            expected = [onp.asarray(probe["expected"][f"o{i}"])
                        for i in range(self.num_outputs)]
        else:
            bucket = self.buckets[0]
            ins_h = [onp.zeros((bucket,) + s, dt)
                     for s, dt in zip(self.input_shapes, self.np_dtypes)]
            expected = None
        comp = self._get_executable(bucket)
        ins = self._place_inputs(ins_h)
        try:
            outs = comp(staged, *ins)
            jax.block_until_ready(outs)
            outs_h = [onp.asarray(jax.device_get(o)) for o in outs]
        except Exception as e:
            _HOT_SWAPS.labels("rolled_back").inc()
            raise HotSwapError(
                f"staged weights failed the probe execution: {e}") from e
        if expected is not None:
            for i, (got, want) in enumerate(zip(outs_h, expected)):
                if not onp.array_equal(got, want):
                    _HOT_SWAPS.labels("rolled_back").inc()
                    raise HotSwapError(
                        f"probe output {i} does not match the recorded "
                        "outputs of the checkpointed weights; rolled back "
                        "(old weights keep serving)")
        else:
            for i, got in enumerate(outs_h):
                if not onp.all(onp.isfinite(got)):
                    _HOT_SWAPS.labels("rolled_back").inc()
                    raise HotSwapError(
                        f"probe output {i} contains non-finite values; "
                        "rolled back (old weights keep serving)")
        # commit: one reference assignment — the next batch's _param_datas()
        # sees the full new weight set, the in-flight one kept the old
        self._active_params = staged
        self._weights_epoch += 1
        # keep the block's Parameters in sync so direct block(...) forwards
        # and later save_checkpoint calls reflect the served weights
        for p, a in zip(self._params, staged):
            p.set_data(NDArray(onp.asarray(jax.device_get(a))))
        self.stats.bump("hot_swaps")
        _HOT_SWAPS.labels("ok").inc()
        return {"endpoint": self.name, "weights_epoch": self._weights_epoch,
                "probe": "recorded" if probe is not None else "finite",
                "bucket": bucket}

    def hot_swap(self, source) -> dict:
        """Inline hot-swap for a *stopped* (or never-served) endpoint: load +
        verify ``source``, stage, probe-validate, cut over; HotSwapError
        rolls back to the old weights. Inside a running InferenceServer use
        ``server.hot_swap(name, source)`` instead — it routes the validation
        and cutover through the worker thread at a batch boundary, so no
        request is ever dropped or served from a half-loaded model."""
        host, probe, label = self.load_swap_source(source)
        staged = self.stage_weights(host)
        report = self.validate_and_commit(staged, probe)
        report["source"] = label
        return report

    def __repr__(self):
        return (f"ModelEndpoint({self.name!r}, inputs={self.input_shapes}, "
                f"buckets={self.buckets})")


def get_endpoint(name: str) -> ModelEndpoint:
    with _REG_LOCK:
        if name not in _ENDPOINTS:
            raise MXNetError(f"unknown endpoint {name!r}; registered: "
                             f"{sorted(_ENDPOINTS)}")
        return _ENDPOINTS[name]


def list_endpoints():
    with _REG_LOCK:
        return sorted(_ENDPOINTS)


def unregister(name: str):
    with _REG_LOCK:
        _ENDPOINTS.pop(name, None)
