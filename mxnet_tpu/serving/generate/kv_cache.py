"""Paged KV cache: preallocated on-device block pools for autoregressive decode.

Contiguous per-sequence KV buffers force the classic serving dilemma:
reserve max_seq_len per sequence (wasting most of it on short outputs) or
reallocate as sequences grow (fragmenting HBM and recompiling shapes). The
paged layout decouples the two — the pool preallocates a fixed grid of
fixed-size pages ONCE, per-sequence page tables map logical positions to
physical pages, and the decode executables take the pool arrays as
*arguments* (the params-as-arguments lesson from PERF.md round 4), so the
compiled prefill/decode-step programs are independent of pool contents and
of which sequence owns which page.

Layout: ``(num_layers, num_pages, page_size, kv_dim)`` per pool (one for K,
one for V). **Page 0 is reserved as a scratch page** and never allocated:
scatter writes for padded/invalid positions are routed to it, and padded
page-table entries gather from it. Whatever garbage accumulates there is
masked to an exactly-zero softmax weight before it can touch a real row
(``_NEG_INF`` underflow — see ops/pallas/flash_attention.py
``single_query_attention``), which is the property the batched-vs-serial
bitwise decode oracle rests on.

Host-side management (alloc/free/defrag, counters, the memstats holder) is
in :class:`PagedKVPool`; the jit-side scatter/gather helpers
(:func:`write_prefill`, :func:`write_step`, :func:`gather_ctx`) are pure
functions traced into the compiled executables.
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional

import numpy as onp

from ... import config as _config
from ... import telemetry as _telemetry
from ...base import MXNetError
from ...resilience import faults as _faults
from ..errors import KVPoolExhausted

__all__ = ["PagedKVPool", "KVPoolExhausted", "write_prefill", "write_step",
           "gather_ctx"]

_POOL_PAGES = _telemetry.gauge(
    "mxtpu_kv_pool_pages",
    "Usable pages preallocated in one paged KV pool (page 0, the scratch "
    "page for masked writes, is excluded).",
    labelnames=("pool",))
_IN_USE = _telemetry.gauge(
    "mxtpu_kv_pages_in_use",
    "Pages currently owned by live sequence page tables.",
    labelnames=("pool",))
_ALLOCATED = _telemetry.counter(
    "mxtpu_kv_pages_allocated_total",
    "Pages handed out by reserve() over the pool's lifetime.",
    labelnames=("pool",))
_FREED = _telemetry.counter(
    "mxtpu_kv_pages_freed_total",
    "Pages returned by free() (sequence finished/cancelled/failed).",
    labelnames=("pool",))
_EXHAUSTED = _telemetry.counter(
    "mxtpu_kv_pool_exhausted_total",
    "reserve() calls refused for lack of free pages; the scheduler keeps "
    "the sequence queued, so a climbing rate means the pool is sized below "
    "the offered concurrency * sequence length.",
    labelnames=("pool",))
_DEFRAGS = _telemetry.counter(
    "mxtpu_kv_defrags_total",
    "Compaction passes run on the pool.", labelnames=("pool",))
_DEFRAG_MOVED = _telemetry.counter(
    "mxtpu_kv_defrag_pages_moved_total",
    "Physical pages relocated by compaction passes.", labelnames=("pool",))


# ---------------------------------------------------------------------------
# jit-side helpers: pure functions over pool arrays, traced into the
# prefill / decode-step executables
# ---------------------------------------------------------------------------
def write_prefill(pool, vals, table_row, length, page_size: int):
    """Scatter one sequence's prefill projections into the pool.

    ``pool`` (num_layers, num_pages, page_size, kv_dim); ``vals``
    (num_layers, S, kv_dim) — per-position K (or V) for positions 0..S-1;
    ``table_row`` (P,) int32 physical page ids (0-padded); ``length`` scalar
    int32 — positions >= length are padding and their writes are routed to
    scratch page 0 (where duplicate slots may land in any order; nothing
    ever reads page 0 unmasked)."""
    import jax.numpy as jnp
    S = vals.shape[1]
    pos = jnp.arange(S, dtype=jnp.int32)
    page = table_row[pos // page_size]
    page = jnp.where(pos < length, page, 0)
    slot = pos % page_size
    return pool.at[:, page, slot, :].set(vals)


def write_step(pool, vals, tables, positions, valid, page_size: int):
    """Scatter one decode step's new K (or V) row per sequence.

    ``vals`` (num_layers, B, kv_dim); ``tables`` (B, P) int32;
    ``positions`` (B,) int32 — the lane each row's new token occupies;
    ``valid`` (B,) bool — padding rows route to scratch page 0."""
    import jax.numpy as jnp
    B = tables.shape[0]
    page = tables[jnp.arange(B), positions // page_size]
    page = jnp.where(valid, page, 0)
    slot = positions % page_size
    return pool.at[:, page, slot, :].set(vals)


def gather_ctx(pool, tables):
    """Gather each sequence's cached context: (num_layers, num_pages,
    page_size, kv_dim) x (B, P) -> (num_layers, B, P*page_size, kv_dim),
    lane j = position j. Padding table entries gather scratch page 0 —
    masked by the attention length mask before use."""
    g = pool[:, tables]                      # (L, B, P, page, kv)
    L, B = g.shape[0], g.shape[1]
    return g.reshape(L, B, g.shape[2] * g.shape[3], g.shape[4])


# ---------------------------------------------------------------------------
# host-side pool management
# ---------------------------------------------------------------------------
class PagedKVPool:
    """Preallocated paged KV storage plus its free-list allocator.

    Thread-safety: all mutators take the internal lock, but array
    replacement (``update_arrays``) and ``defrag`` follow the serving
    single-dispatcher rule — only the decode worker thread runs them, so a
    step never races a compaction.
    """

    def __init__(self, name: str, num_layers: int, kv_dim: int,
                 max_seq_len: int, page_size: Optional[int] = None,
                 num_pages: Optional[int] = None, dtype="float32"):
        import jax.numpy as jnp
        if page_size is None:
            page_size = int(_config.get("MXNET_KV_PAGE_SIZE"))
        if num_pages is None:
            num_pages = int(_config.get("MXNET_KV_POOL_PAGES"))
        if page_size < 1 or num_pages < 2:
            raise MXNetError(
                f"KV pool needs page_size >= 1 and num_pages >= 2 (one "
                f"scratch + one usable), got page_size={page_size}, "
                f"num_pages={num_pages}")
        self.name = name
        self.num_layers = int(num_layers)
        self.kv_dim = int(kv_dim)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_seq_len = int(max_seq_len)
        self.pages_per_seq = int(math.ceil(self.max_seq_len / self.page_size))
        if self.pages_per_seq > self.num_pages - 1:
            raise MXNetError(
                f"KV pool {name!r}: one sequence needs {self.pages_per_seq} "
                f"pages for max_seq_len={max_seq_len} but the pool only has "
                f"{self.num_pages - 1} usable pages")
        shape = (self.num_layers, self.num_pages, self.page_size, self.kv_dim)
        self.k_pool = jnp.zeros(shape, dtype=dtype)
        self.v_pool = jnp.zeros(shape, dtype=dtype)
        self._lock = threading.Lock()
        # LIFO free list, page 0 (scratch) excluded for the pool's lifetime
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._tables: Dict[int, List[int]] = {}
        self._m_pages = _POOL_PAGES.labels(name)
        self._m_in_use = _IN_USE.labels(name)
        self._m_alloc = _ALLOCATED.labels(name)
        self._m_freed = _FREED.labels(name)
        self._m_exhausted = _EXHAUSTED.labels(name)
        self._m_defrags = _DEFRAGS.labels(name)
        self._m_moved = _DEFRAG_MOVED.labels(name)
        self._m_pages.set(self.num_pages - 1)
        self._m_in_use.set(0)
        from ...telemetry import memstats as _memstats
        _memstats.register(
            "serving", f"{name}.kv_pool", owner=self,
            device=self._device_label(),
            sizer=lambda p: int(p.k_pool.nbytes) + int(p.v_pool.nbytes))

    def _device_label(self) -> str:
        try:
            d = next(iter(self.k_pool.devices()))
            return f"{d.platform}:{d.id}"
        except Exception:
            return ""

    # -- allocation ---------------------------------------------------------
    def reserve(self, sid: int, total_tokens: int):
        """Grow ``sid``'s page table to cover ``total_tokens`` positions.

        The decode scheduler reserves a sequence's WHOLE budget
        (prompt + max_new_tokens) at admission, so exhaustion can only
        happen here — never mid-decode — and a refused sequence simply
        stays queued with nothing to unwind. Raises
        :class:`KVPoolExhausted` when the free list is short (including the
        injected ``kv_exhausted`` fault, which simulates exactly that)."""
        if total_tokens > self.max_seq_len:
            raise MXNetError(
                f"sequence {sid} wants {total_tokens} tokens, pool "
                f"{self.name!r} is laid out for max_seq_len="
                f"{self.max_seq_len}")
        need = int(math.ceil(total_tokens / self.page_size))
        try:
            _faults.check("decode")
        except _faults.FaultInjected as e:
            if e.kind == "kv_exhausted":
                self._m_exhausted.inc()
                raise KVPoolExhausted(str(e))
            raise
        with self._lock:
            table = self._tables.setdefault(sid, [])
            delta = need - len(table)
            if delta <= 0:
                return
            if delta > len(self._free):
                self._m_exhausted.inc()
                raise KVPoolExhausted(
                    f"RESOURCE_EXHAUSTED: KV pool {self.name!r} has "
                    f"{len(self._free)} free pages, sequence {sid} needs "
                    f"{delta} more (of {need} for {total_tokens} tokens)")
            for _ in range(delta):
                table.append(self._free.pop())
            in_use = (self.num_pages - 1) - len(self._free)
        self._m_alloc.inc(delta)
        self._m_in_use.set(in_use)

    def free(self, sid: int) -> int:
        """Return ``sid``'s pages to the free list; pages are reused by later
        reservations (the free -> realloc path the oracle test covers)."""
        with self._lock:
            table = self._tables.pop(sid, None)
            if not table:
                return 0
            self._free.extend(reversed(table))
            n = len(table)
            in_use = (self.num_pages - 1) - len(self._free)
        self._m_freed.inc(n)
        self._m_in_use.set(in_use)
        ratio = float(_config.get("MXNET_KV_DEFRAG_RATIO"))
        if ratio > 0 and self.spread() > ratio:
            self.defrag()
        return n

    def table(self, sid: int) -> onp.ndarray:
        """``sid``'s page table padded with scratch-page zeros to the fixed
        (pages_per_seq,) executable shape."""
        out = onp.zeros((self.pages_per_seq,), onp.int32)
        with self._lock:
            pages = self._tables.get(sid, ())
            out[:len(pages)] = pages
        return out

    # -- accounting ---------------------------------------------------------
    @property
    def pages_in_use(self) -> int:
        with self._lock:
            return (self.num_pages - 1) - len(self._free)

    def occupancy(self) -> float:
        """Fraction of usable pages owned by live sequences (0..1)."""
        return self.pages_in_use / max(1, self.num_pages - 1)

    def spread(self) -> float:
        """Fragmentation proxy: highest allocated page id / pages in use.
        1.0 means perfectly compact; large values mean live pages are
        scattered across a mostly-empty pool."""
        with self._lock:
            used = [p for t in self._tables.values() for p in t]
            if not used:
                return 1.0
            return max(used) / len(used)

    def snapshot(self) -> Dict:
        with self._lock:
            used = (self.num_pages - 1) - len(self._free)
            return {
                "pool": self.name,
                "pages": self.num_pages - 1,
                "page_size": self.page_size,
                "in_use": used,
                "occupancy": used / max(1, self.num_pages - 1),
                "sequences": len(self._tables),
                "pages_per_seq": self.pages_per_seq,
                "bytes": int(self.k_pool.nbytes) + int(self.v_pool.nbytes),
            }

    # -- engine hooks -------------------------------------------------------
    def update_arrays(self, k_pool, v_pool):
        """Install the pool arrays a compiled step returned (worker thread
        only — the single-dispatcher rule, so no lock: defrag() and this
        never run concurrently)."""
        self.k_pool = k_pool    # mxlint: disable=CONC200
        self.v_pool = v_pool    # mxlint: disable=CONC200

    def defrag(self) -> int:
        """Compact live pages down to the lowest physical ids.

        Page-granular allocation never *functionally* fragments (any free
        page serves any reservation), so this is an optional compaction that
        keeps the high-numbered region of the pool untouched — gathers stay
        cache-local and the tail could be released to a resize. The move is
        a single gather+scatter copy (no arithmetic), so decode output
        stays bitwise identical across a compaction. Worker-thread only.
        Returns the number of pages moved."""
        import jax.numpy as jnp
        with self._lock:
            order = sorted(
                (p, sid, i)
                for sid, t in self._tables.items() for i, p in enumerate(t))
            moves = [(old, new + 1, sid, i)
                     for new, (old, sid, i) in enumerate(order)
                     if old != new + 1]
            if moves:
                old_ids = jnp.asarray([m[0] for m in moves], jnp.int32)
                new_ids = jnp.asarray([m[1] for m in moves], jnp.int32)
                self.k_pool = self.k_pool.at[:, new_ids].set(
                    self.k_pool[:, old_ids])
                self.v_pool = self.v_pool.at[:, new_ids].set(
                    self.v_pool[:, old_ids])
                for old, new, sid, i in moves:
                    self._tables[sid][i] = new
            n_used = len(order)
            self._free = list(range(self.num_pages - 1, n_used, -1))
        self._m_defrags.inc()
        self._m_moved.inc(len(moves))
        return len(moves)
