"""TokenStream: the client half of a streaming generation.

The scheduler emits tokens into a bounded buffer; the client consumes them
with a blocking iterator (or a per-token callback). Backpressure is
cooperative and lossless: ``put`` never drops a token — it appends and then
reports whether the buffer is now full, and the scheduler reacts by pausing
the sequence (it keeps its KV pages, it just stops being stepped). When the
consumer drains the buffer below half, the stream fires its resume callback
and the scheduler puts the sequence back in the running set.

Lock ordering: the scheduler calls ``put``/``close`` while holding its own
condition lock, taking the stream lock second; the consumer holds the stream
lock first and may then need the scheduler lock (resume). To keep the order
acyclic, the resume callback is always invoked *after* the stream lock is
released — the decision is made under the lock, the call is not.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Iterator, Optional

from ...base import MXNetError

__all__ = ["TokenStream"]

#: sentinel get() timeout meaning "block forever"
_FOREVER = None


class TokenStream:
    """Bounded, closable token queue for one generation request.

    Clients iterate it (``for tok in stream``) or call ``result()`` for the
    full token list; either blocks until the scheduler emits. ``cancel()``
    asks the scheduler to retire the sequence at the next step boundary —
    already-buffered tokens remain readable.
    """

    def __init__(self, sid: int, maxsize: int,
                 on_token: Optional[Callable[[int], None]] = None,
                 resume_cb: Optional[Callable[[int], None]] = None):
        if maxsize < 2:
            raise MXNetError(f"stream buffer must be >= 2, got {maxsize}")
        self.sid = sid
        self._maxsize = int(maxsize)
        self._dq: deque = deque()
        self._cv = threading.Condition(threading.Lock())
        self._closed = False
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self._on_token = on_token
        self._resume_cb = resume_cb
        self.tokens_delivered = 0

    # ------------------------------------------------------------------
    # scheduler side
    # ------------------------------------------------------------------
    def put(self, tok: int) -> bool:
        """Append one token. Returns False when the buffer is now full —
        the token is NOT lost; the scheduler should pause the sequence
        until the resume callback fires."""
        cb = self._on_token
        with self._cv:
            self._dq.append(tok)
            full = len(self._dq) >= self._maxsize
            self._cv.notify_all()
        if cb is not None:
            try:
                cb(tok)
            except Exception:
                pass        # a client callback must not take down the loop
        return not full

    def close(self, error: Optional[BaseException] = None):
        """End of stream. With ``error``, the consumer sees it raised after
        draining whatever was already buffered."""
        with self._cv:
            self._closed = True
            if error is not None and self._error is None:
                self._error = error
            self._cv.notify_all()

    # ------------------------------------------------------------------
    # client side
    # ------------------------------------------------------------------
    def cancel(self):
        """Request cancellation; the scheduler retires the sequence (and
        frees its pages) at the next step boundary."""
        with self._cv:
            self._cancelled = True
            self._cv.notify_all()

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed and not self._dq

    def get(self, timeout: Optional[float] = _FOREVER) -> Optional[int]:
        """Next token, or None when the stream is finished. Raises the
        scheduler-reported error (failed sequence, abandoned drain) once the
        buffer is drained. Raises TimeoutError if ``timeout`` seconds pass
        without a token."""
        resume = False
        try:
            with self._cv:
                while not self._dq and not self._closed:
                    if not self._cv.wait(timeout):
                        raise TimeoutError(
                            f"no token within {timeout}s on stream "
                            f"{self.sid}")
                if self._dq:
                    tok = self._dq.popleft()
                    self.tokens_delivered += 1
                    resume = len(self._dq) <= self._maxsize // 2
                    return tok
                if self._error is not None:
                    raise self._error
                return None
        finally:
            if resume and self._resume_cb is not None:
                self._resume_cb(self.sid)

    def __iter__(self) -> Iterator[int]:
        while True:
            tok = self.get()
            if tok is None:
                return
            yield tok

    def result(self, timeout: Optional[float] = _FOREVER):
        """Drain the stream to completion; returns the full token list."""
        return [tok for tok in iter(lambda: self.get(timeout), None)]

    def __repr__(self):
        with self._cv:
            return (f"TokenStream(sid={self.sid}, buffered={len(self._dq)}, "
                    f"closed={self._closed}, cancelled={self._cancelled})")
