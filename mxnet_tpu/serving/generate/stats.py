"""Decode-path observability: per-endpoint counters for the generative loop.

Same discipline as serving/stats.py — shared-registry families labeled by
endpoint, children pre-bound at construction so the per-step/per-token cost
is one counter bump, and fine-resolution local LatencyHistograms behind the
``snapshot()`` dict for exact percentiles (the registry histograms serve the
export surface). The load-bearing numbers are the gate metrics: decode
tokens/steps (tok/s/chip once divided by wall clock and chip count) and the
inter-token latency distribution (the per-tenant SLO unit).
"""
from __future__ import annotations

import threading
from typing import Dict

from ... import telemetry as _telemetry
from ..stats import LatencyHistogram

__all__ = ["DecodeStats"]

_TOKENS = _telemetry.counter(
    "mxtpu_decode_tokens_total",
    "Tokens emitted to client streams (prefill first-tokens included).",
    labelnames=("endpoint",))
_STEPS = _telemetry.counter(
    "mxtpu_decode_steps_total",
    "Batched decode steps executed (each advances every running sequence "
    "by one token).",
    labelnames=("endpoint",))
_SEQS = _telemetry.counter(
    "mxtpu_decode_seqs_total",
    "Sequence lifecycle events: submitted / admitted / finished / "
    "cancelled / failed / requeued (failover) / paused / resumed "
    "(stream backpressure).",
    labelnames=("endpoint", "event"))
_OCCUPANCY = _telemetry.gauge(
    "mxtpu_decode_batch_occupancy",
    "Running sequences / padded batch bucket at the last decode step "
    "(0..1); persistently low means the bucket ladder is too coarse for "
    "the offered concurrency.",
    labelnames=("endpoint",))
_QUEUE_DEPTH = _telemetry.gauge(
    "mxtpu_decode_queue_depth",
    "Sequences admitted-but-waiting for a batch slot or KV pages.",
    labelnames=("endpoint",))
_INTERTOKEN = _telemetry.histogram(
    "mxtpu_decode_intertoken_us",
    "Gap between consecutive tokens of one sequence as emitted by the "
    "scheduler (microseconds) — the unit per-tenant decode SLOs are "
    "expressed in.",
    labelnames=("endpoint", "tenant"))
_PREFILL = _telemetry.histogram(
    "mxtpu_decode_prefill_us",
    "Prefill executable latency per admitted sequence (microseconds).",
    labelnames=("endpoint",))
_STEP_LAT = _telemetry.histogram(
    "mxtpu_decode_step_us",
    "Batched decode-step executable latency (microseconds).",
    labelnames=("endpoint",))
_BACKPRESSURE = _telemetry.counter(
    "mxtpu_decode_stream_backpressure_total",
    "Sequences paused because their client stream buffer filled; the "
    "sequence keeps its KV pages and resumes when the consumer drains.",
    labelnames=("endpoint",))
_FAILOVERS = _telemetry.counter(
    "mxtpu_decode_failovers_total",
    "Decode-worker failovers by reason (worker_dead = the loop thread "
    "died, e.g. an injected decode_stall); running sequences are requeued "
    "with pages and emitted tokens intact.",
    labelnames=("endpoint", "reason"))

_SEQ_EVENTS = ("submitted", "admitted", "finished", "cancelled", "failed",
               "requeued", "paused", "resumed")


class DecodeStats:
    """Counters + histograms for one decode endpoint."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "tokens": 0, "steps": 0, "compiles": 0,
            **{f"seq_{ev}": 0 for ev in _SEQ_EVENTS},
        }
        self.prefill = LatencyHistogram()
        self.step = LatencyHistogram()
        self.intertoken = LatencyHistogram()
        self._m_tokens = _TOKENS.labels(name)
        self._m_steps = _STEPS.labels(name)
        self._m_seqs = {ev: _SEQS.labels(name, ev) for ev in _SEQ_EVENTS}
        self._m_occupancy = _OCCUPANCY.labels(name)
        self._m_queue_depth = _QUEUE_DEPTH.labels(name)
        self._m_prefill = _PREFILL.labels(name)
        self._m_step = _STEP_LAT.labels(name)
        self._m_backpressure = _BACKPRESSURE.labels(name)
        self._m_intertoken: Dict[str, object] = {}

    def seq_event(self, event: str, delta: int = 1):
        with self._lock:
            self.counters[f"seq_{event}"] += delta
        self._m_seqs[event].inc(delta)

    def tokens(self, n: int = 1):
        with self._lock:
            self.counters["tokens"] += n
        self._m_tokens.inc(n)

    def record_step(self, dur_us: float, rows: int, bucket: int):
        with self._lock:
            self.counters["steps"] += 1
            self.step.record(dur_us)
        self._m_steps.inc()
        self._m_step.observe(dur_us)
        self._m_occupancy.set(rows / bucket if bucket else 0.0)

    def record_prefill(self, dur_us: float):
        with self._lock:
            self.prefill.record(dur_us)
        self._m_prefill.observe(dur_us)

    def record_intertoken(self, tenant: str, dur_us: float):
        with self._lock:
            self.intertoken.record(dur_us)
            child = self._m_intertoken.get(tenant)
            if child is None:
                child = self._m_intertoken.setdefault(
                    tenant, _INTERTOKEN.labels(self.name, tenant))
        child.observe(dur_us)

    def record_compile(self):
        with self._lock:
            self.counters["compiles"] += 1

    def backpressure(self):
        self._m_backpressure.inc()

    def failover(self, reason: str):
        _FAILOVERS.labels(self.name, reason).inc()

    def set_queue_depth(self, n: int):
        self._m_queue_depth.set(n)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "prefill": self.prefill.snapshot(),
                "step": self.step.snapshot(),
                "intertoken": self.intertoken.snapshot(),
            }
