"""DecodeEndpoint: one generative model plus its paged KV pool and the two
AOT executable families decode needs.

Per the endpoint design (serving/endpoint.py), everything rides as
executable *arguments* — params, token ids, page tables, and the KV pool
arrays themselves — so the compiled programs are independent of weights and
cache contents. Two families, both routed through
``compile_ledger.lower_and_compile`` so the ledger's duplicate-fingerprint
accounting covers decode traffic:

- **prefill**, bucketed by sequence length (``seq_buckets`` ladder): one
  full causal forward of a single prompt (``TransformerLM.prefill_collect``
  traced via ``pure_apply(..., method=...)``), scattering every layer's K/V
  into the sequence's pages and returning the first generated token.
- **decode-step**, bucketed by batch size (pow2 ladder): one token for every
  running sequence — gather each row's cached context through its page
  table, run ``TransformerLM.decode_step`` (single_query_attention inside),
  scatter the new K/V row, greedy-argmax the next token on device.

Bitwise contract: every model op is per-row and masked lanes carry exactly
zero softmax weight, so a row's output depends only on its own tokens and
pages — not on batch composition, bucket size, physical page placement, or
stale pool contents. That is what makes batched continuous decode
bitwise-equal to one-sequence-at-a-time greedy decode (the tier-1 oracle).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as onp

from ... import config as _config
from ...base import Context, MXNetError, current_context
from .. import bucketing
from ..router import StepCostEWMA
from .kv_cache import PagedKVPool, gather_ctx, write_prefill, write_step
from .stats import DecodeStats

__all__ = ["DecodeEndpoint"]


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class DecodeEndpoint:
    """A named generative model with bucketed prefill/decode executables.

    ``block`` must expose the incremental-decode protocol of
    ``gluon.model_zoo.bert.TransformerLM``: ``num_layers``/``units``
    attributes, ``prefill_collect(tokens)`` and
    ``decode_step(ids, positions, *kv_ctx)``.

    Device work (``prefill``/``decode_step``/``warmup``/pool mutation)
    follows the serving single-dispatcher rule: one thread — the decode
    scheduler's worker — runs it.
    """

    def __init__(self, name: str, block, *, max_seq_len: int = 128,
                 max_batch_size: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 decode_buckets: Optional[Sequence[int]] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 ctx: Optional[Context] = None):
        self.name = name
        self.block = block
        self.ctx = ctx if ctx is not None else current_context()
        self.max_seq_len = int(max_seq_len)
        if max_batch_size is None:
            max_batch_size = int(_config.get("MXNET_DECODE_MAX_BATCH"))
        self.max_batch_size = int(max_batch_size)
        if self.max_batch_size < 1:
            raise MXNetError("max_batch_size must be >= 1")
        if decode_buckets is None:
            decode_buckets = bucketing.pow2_buckets(self.max_batch_size)
        self.decode_buckets = bucketing.validate_buckets(
            decode_buckets, self.max_batch_size)
        self.prefill_buckets = bucketing.seq_buckets(
            self.max_seq_len, ladder=prefill_buckets)
        max_len = getattr(block, "max_length", None)
        if max_len is not None and self.max_seq_len > int(max_len):
            raise MXNetError(
                f"max_seq_len={self.max_seq_len} exceeds the model's "
                f"position-embedding table ({max_len})")

        self.stats = DecodeStats(name)
        # per-bucket cost models (us): measured EWMA with the learned cost
        # model as the cold-bucket prior. The key closures read self lazily
        # — the KV pool (whose dtype the key carries) is built below.
        from ...telemetry import costmodel as _costmodel
        self.step_cost = StepCostEWMA(      # per decode batch bucket
            name=f"{name}.decode",
            prior=_costmodel.make_prior(
                "decode_step", lambda b: self._cost_key("step", b)))
        self.prefill_cost = StepCostEWMA(   # per prefill seq bucket
            name=f"{name}.prefill",
            prior=_costmodel.make_prior(
                "decode_prefill", lambda b: self._cost_key("prefill", b)))
        self._lock = threading.Lock()
        self._prefill_execs: Dict[int, object] = {}
        self._decode_execs: Dict[int, object] = {}
        self._pf_jfn = None
        self._dec_jfn = None
        self._probe()
        self.pool = PagedKVPool(name, int(block.num_layers),
                                int(block.units), self.max_seq_len,
                                page_size=page_size, num_pages=num_pages,
                                dtype=self._param_datas()[0].dtype)

    # ------------------------------------------------------------------
    def _probe(self):
        """One eager prefill-bucket forward: triggers deferred parameter
        init and validates the block's decode protocol."""
        from ... import autograd
        from ...ndarray.ndarray import NDArray
        for attr in ("num_layers", "units", "prefill_collect", "decode_step"):
            if not hasattr(self.block, attr):
                raise MXNetError(
                    f"decode endpoint {self.name!r}: block lacks the "
                    f"incremental-decode protocol member {attr!r} "
                    "(see gluon.model_zoo.bert.TransformerLM)")
        dummy = NDArray(onp.zeros((1, self.prefill_buckets[0]), onp.int32),
                        ctx=self.ctx)
        with autograd._RecordingStateScope(False, False):
            self.block(dummy)
        self._params = list(self.block.collect_params().values())
        from ...telemetry import memstats as _memstats
        _memstats.register(
            "serving", f"{self.name}.params", owner=self,
            device=self._device_label(),
            sizer=lambda ep: _memstats.nbytes_of(ep._param_datas()))

    def _device_label(self) -> str:
        try:
            d = self.ctx.jax_device()
            return f"{d.platform}:{d.id}"
        except (AttributeError, RuntimeError, ValueError, ImportError):
            return ""

    def _donate_pools(self) -> bool:
        """Donate the KV pool arguments on backends with buffer donation:
        the pool is the largest recurring operand and every step consumes
        the previous step's arrays, so donation makes the cache update
        in-place on TPU/GPU. CPU warns on donation — keep it off there."""
        try:
            return self.ctx.jax_device().platform in ("tpu", "gpu")
        except Exception:
            return False

    def _param_datas(self):
        return tuple(p.data(self.ctx).data for p in self._params)

    def _adopt_compiled(self, comp):
        """Hook: inspect a just-obtained executable before first use.
        Sharded twins adopt a cache-deserialized executable's device
        assignment here; the single-device path needs nothing."""

    def _jit_prefill(self, fn, donate):
        """Wrap the traced prefill; sharded twins add in/out shardings."""
        import jax
        return jax.jit(fn, donate_argnums=donate)

    def _jit_decode(self, fn, donate):
        """Wrap the traced decode step; sharded twins add shardings."""
        import jax
        return jax.jit(fn, donate_argnums=donate)

    # ------------------------------------------------------------------
    # traced programs
    # ------------------------------------------------------------------
    def _prefill_fn(self):
        if self._pf_jfn is None:
            import jax
            import jax.numpy as jnp
            from ...gluon.block import pure_apply
            block, plist = self.block, self._params
            page_size = int(_config.get("MXNET_KV_PAGE_SIZE")) \
                if not hasattr(self, "pool") else self.pool.page_size

            def prefill(param_datas, tokens, length, table, k_pool, v_pool):
                outs, _, _ = pure_apply(block, plist, param_datas, (tokens,),
                                        None, training=False,
                                        method="prefill_collect")
                logits = outs[0]                       # (1, S, V)
                ks = jnp.stack(outs[1::2], 0)[:, 0]    # (layers, S, kv)
                vs = jnp.stack(outs[2::2], 0)[:, 0]
                k_pool = write_prefill(k_pool, ks, table[0], length[0],
                                       page_size)
                v_pool = write_prefill(v_pool, vs, table[0], length[0],
                                       page_size)
                next_id = jnp.argmax(logits[0, length[0] - 1]) \
                    .astype(jnp.int32)
                return next_id.reshape(1), k_pool, v_pool

            donate = (4, 5) if self._donate_pools() else ()
            self._pf_jfn = self._jit_prefill(prefill, donate)
        return self._pf_jfn

    def _decode_fn(self):
        if self._dec_jfn is None:
            import jax
            import jax.numpy as jnp
            from ...gluon.block import pure_apply
            block, plist = self.block, self._params
            page_size = self.pool.page_size
            num_layers = int(block.num_layers)

            def decode(param_datas, ids, positions, tables, valid,
                       k_pool, v_pool):
                gk = gather_ctx(k_pool, tables)    # (layers, B, L, kv)
                gv = gather_ctx(v_pool, tables)
                inputs = (ids, positions)
                for i in range(num_layers):
                    inputs = inputs + (gk[i], gv[i])
                outs, _, _ = pure_apply(block, plist, param_datas, inputs,
                                        None, training=False,
                                        method="decode_step")
                logits = outs[0]                   # (B, V)
                ks = jnp.stack(outs[1::2], 0)      # (layers, B, kv)
                vs = jnp.stack(outs[2::2], 0)
                k_pool = write_step(k_pool, ks, tables, positions, valid,
                                    page_size)
                v_pool = write_step(v_pool, vs, tables, positions, valid,
                                    page_size)
                next_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return next_ids, k_pool, v_pool

            donate = (5, 6) if self._donate_pools() else ()
            self._dec_jfn = self._jit_decode(decode, donate)
        return self._dec_jfn

    # ------------------------------------------------------------------
    # the bucketed executable caches
    # ------------------------------------------------------------------
    def _pool_sds(self):
        import jax
        return (jax.ShapeDtypeStruct(self.k_pool_shape, self.pool_dtype),
                jax.ShapeDtypeStruct(self.k_pool_shape, self.pool_dtype))

    @property
    def k_pool_shape(self):
        return tuple(self.pool.k_pool.shape)

    @property
    def pool_dtype(self):
        return self.pool.k_pool.dtype

    def _cost_key(self, kind: str, bucket: int) -> Dict[str, object]:
        """The compile-ledger / cost-model trigger key for one (kind,
        bucket) executable — also what the cold-bucket prior featurizes."""
        return {"endpoint": self.name, "kind": kind, "bucket": bucket,
                "dtype": str(self.pool_dtype),
                "device": self._device_label()}

    def _observe_cost(self, ewma, kind: str, site: str, bucket: int,
                      us: float, rows: Optional[int] = None):
        """Feed one measured wall: the scheduling EWMA always, plus the
        cost observatory (step ledger record + residual vs the prior)."""
        ewma.observe(bucket, us)
        try:
            from ...telemetry import costmodel as _costmodel
            _costmodel.on_step_observed(site, self._cost_key(kind, bucket),
                                        bucket, us, rows=rows,
                                        prior_us=ewma.prior(bucket))
        except Exception:
            pass

    def _compile(self, cache, bucket, jfn, arg_sds, kind):
        comp = cache.get(bucket)
        if comp is not None:
            return comp
        with self._lock:
            comp = cache.get(bucket)
            if comp is not None:
                return comp
            import jax
            from ... import telemetry
            from ...resilience import faults as _faults
            from ...telemetry import compile_ledger as _ledger
            from ...telemetry import memstats as _memstats
            t0 = _now_us()
            _faults.check("compile")
            param_sds = tuple(
                jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                for a in self._param_datas())
            with telemetry.span("serving.compile", endpoint=self.name,
                                bucket=bucket, kind=kind):
                # compile-once gate (see ModelEndpoint._get_executable):
                # contenders need this executable and wait for it either way
                comp = _ledger.lower_and_compile(  # mxlint: disable=CONC202
                    jfn, (param_sds,) + arg_sds,
                    site=f"decode_{kind}",
                    key=self._cost_key(kind, bucket),
                    expect_donation=self._donate_pools())
            self._adopt_compiled(comp)
            cache[bucket] = comp
            mem = _ledger._memory_analysis(comp)
            _memstats.register(
                "serving", f"{self.name}.{kind}_b{bucket}", owner=self,
                device=self._device_label(),
                nbytes=sum(mem.get(k, 0) for k in
                           ("output_bytes", "temp_bytes", "code_bytes")))
            self.stats.record_compile()
            _ = _now_us() - t0
            return comp

    def _get_prefill(self, seq_bucket: int):
        import jax
        import jax.numpy as jnp
        P = self.pool.pages_per_seq
        arg_sds = (jax.ShapeDtypeStruct((1, seq_bucket), jnp.int32),
                   jax.ShapeDtypeStruct((1,), jnp.int32),
                   jax.ShapeDtypeStruct((1, P), jnp.int32)) + self._pool_sds()
        return self._compile(self._prefill_execs, seq_bucket,
                             self._prefill_fn(), arg_sds, "prefill")

    def _get_decode(self, batch_bucket: int):
        import jax
        import jax.numpy as jnp
        P = self.pool.pages_per_seq
        arg_sds = (jax.ShapeDtypeStruct((batch_bucket,), jnp.int32),
                   jax.ShapeDtypeStruct((batch_bucket,), jnp.int32),
                   jax.ShapeDtypeStruct((batch_bucket, P), jnp.int32),
                   jax.ShapeDtypeStruct((batch_bucket,), jnp.bool_)) \
            + self._pool_sds()
        return self._compile(self._decode_execs, batch_bucket,
                             self._decode_fn(), arg_sds, "step")

    def warmup(self, execute: bool = True) -> int:
        """Compile every prefill and decode bucket (and by default execute
        each once to seed the cost EWMAs). Warmup traffic only ever writes
        scratch page 0 — zero page tables, zero valid masks — so it cannot
        perturb a later sequence. Returns the number of executables built."""
        import jax
        n = 0
        P = self.pool.pages_per_seq
        for b in self.prefill_buckets:
            fresh = b not in self._prefill_execs
            comp = self._get_prefill(b)
            if fresh:
                n += 1
                if execute:
                    toks = onp.zeros((1, b), onp.int32)
                    length = onp.asarray([1], onp.int32)
                    table = onp.zeros((1, P), onp.int32)
                    t0 = _now_us()
                    out = comp(self._param_datas(), toks, length, table,
                               self.pool.k_pool, self.pool.v_pool)
                    jax.block_until_ready(out)
                    self.pool.update_arrays(out[1], out[2])
                    self._observe_cost(self.prefill_cost, "prefill",
                                       "decode_prefill", b, _now_us() - t0)
        for b in self.decode_buckets:
            fresh = b not in self._decode_execs
            comp = self._get_decode(b)
            if fresh:
                n += 1
                if execute:
                    ids = onp.zeros((b,), onp.int32)
                    pos = onp.zeros((b,), onp.int32)
                    tables = onp.zeros((b, P), onp.int32)
                    valid = onp.zeros((b,), bool)
                    t0 = _now_us()
                    out = comp(self._param_datas(), ids, pos, tables, valid,
                               self.pool.k_pool, self.pool.v_pool)
                    jax.block_until_ready(out)
                    self.pool.update_arrays(out[1], out[2])
                    self._observe_cost(self.step_cost, "step",
                                       "decode_step", b, _now_us() - t0)
        return n

    # ------------------------------------------------------------------
    # execution (decode-worker thread only)
    # ------------------------------------------------------------------
    def prefill(self, prompt: Sequence[int], table: onp.ndarray) -> int:
        """Run one prompt through its sequence-length bucket's prefill
        executable; the sequence's pages fill with K/V and the first
        generated token comes back."""
        import jax
        n = len(prompt)
        S = bucketing.bucket_for(n, self.prefill_buckets)
        comp = self._get_prefill(S)
        toks = onp.zeros((1, S), onp.int32)
        toks[0, :n] = prompt
        length = onp.asarray([n], onp.int32)
        t0 = _now_us()
        next_id, k, v = comp(self._param_datas(), toks, length,
                             table.reshape(1, -1), self.pool.k_pool,
                             self.pool.v_pool)
        out = int(onp.asarray(next_id)[0])     # sync point
        self.pool.update_arrays(k, v)
        dt = _now_us() - t0
        self._observe_cost(self.prefill_cost, "prefill", "decode_prefill",
                           S, dt, rows=n)
        self.stats.record_prefill(dt)
        return out

    def decode_step(self, rows: Sequence[Tuple[int, int, onp.ndarray]]
                    ) -> Tuple[int, ...]:
        """One batched decode step. ``rows`` is ``(input_id, position,
        page_table)`` per running sequence; returns the next token id per
        row. Padding rows (bucket fill) carry zero tables and a False valid
        mask — their writes land on scratch page 0."""
        n = len(rows)
        B = bucketing.bucket_for(n, self.decode_buckets)
        P = self.pool.pages_per_seq
        ids = onp.zeros((B,), onp.int32)
        pos = onp.zeros((B,), onp.int32)
        tables = onp.zeros((B, P), onp.int32)
        valid = onp.zeros((B,), bool)
        for i, (tok, p, table) in enumerate(rows):
            ids[i] = tok
            pos[i] = p
            tables[i] = table
            valid[i] = True
        comp = self._get_decode(B)
        t0 = _now_us()
        next_ids, k, v = comp(self._param_datas(), ids, pos, tables, valid,
                              self.pool.k_pool, self.pool.v_pool)
        out = onp.asarray(next_ids)            # sync point
        self.pool.update_arrays(k, v)
        dt = _now_us() - t0
        self._observe_cost(self.step_cost, "step", "decode_step",
                           B, dt, rows=n)
        self.stats.record_step(dt, n, B)
        return tuple(int(x) for x in out[:n])

    def snapshot(self) -> Dict:
        return {
            "endpoint": self.name,
            "prefill_buckets": list(self.prefill_buckets),
            "decode_buckets": list(self.decode_buckets),
            "executables": len(self._prefill_execs) + len(self._decode_execs),
            "stats": self.stats.snapshot(),
            "kv_pool": self.pool.snapshot(),
        }

    def __repr__(self):
        return (f"DecodeEndpoint({self.name!r}, "
                f"prefill_buckets={self.prefill_buckets}, "
                f"decode_buckets={self.decode_buckets})")
