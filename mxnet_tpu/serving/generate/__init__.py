"""mxnet_tpu.serving.generate — autoregressive decode serving.

The generative counterpart of the request/response InferenceServer path:
instead of one device step per request, a sequence costs one *prefill* step
plus one *decode* step per generated token, and the scheduling unit is the
token, not the request.

Four pieces (one module each):

- :class:`PagedKVPool` (kv_cache.py): preallocated on-device K/V block
  pools with per-sequence page tables. Page 0 is a scratch page for masked
  writes/gathers; pools ride as executable *arguments*, so the compiled
  programs are independent of pool contents.
- :class:`DecodeEndpoint` (engine.py): one generative model (the
  ``TransformerLM`` incremental-decode protocol) with two AOT executable
  families per bucket — prefill (by sequence length, ``seq_buckets``) and
  decode-step (by batch size, pow2) — routed through
  ``compile_ledger.lower_and_compile``.
- :class:`DecodeScheduler` (scheduler.py): token-granularity continuous
  batching — sequences join/retire from the running batch every step, EDF
  admission priced by the live StepCostEWMA against per-tenant inter-token
  SLOs, lossless stream backpressure, graceful drain, and worker failover
  that requeues partial sequences with pages/position/tokens intact.
- :class:`TokenStream` (streams.py): the client half — a bounded blocking
  iterator (or per-token callback) with a resume callback for backpressure.

Numerics contract (tier-1 tested): batched continuous decode is BITWISE
equal to one-sequence-at-a-time greedy decode — including sequences joining
and retiring mid-batch and KV pages being freed and reallocated between
sequences. Every model op is per-row; masked attention lanes carry exactly
zero softmax weight (``_NEG_INF`` underflow), so stale page contents, batch
composition, bucket padding and physical page placement are all invisible
to a row's output.

    from mxnet_tpu.serving.generate import DecodeEndpoint, DecodeScheduler

    eng = DecodeEndpoint("lm", TransformerLM(...), max_seq_len=128)
    with DecodeScheduler(eng) as sched:
        stream = sched.submit([1, 2, 3], max_new_tokens=16)
        for tok in stream:
            ...

Or through the server facade: ``server.register_generator(eng)`` then
``server.generate("lm", prompt)``.
"""
from __future__ import annotations

from .engine import DecodeEndpoint
from .kv_cache import PagedKVPool, gather_ctx, write_prefill, write_step
from .scheduler import DecodeScheduler
from .stats import DecodeStats
from .streams import TokenStream
from ..errors import KVPoolExhausted

__all__ = ["DecodeEndpoint", "DecodeScheduler", "TokenStream", "PagedKVPool",
           "DecodeStats", "KVPoolExhausted", "gather_ctx", "write_prefill",
           "write_step"]
