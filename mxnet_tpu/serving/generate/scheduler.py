"""DecodeScheduler: token-granularity continuous batching for one endpoint.

Unlike the request-batched InferenceServer — where a batch forms once and
runs to completion — the decode batch is re-formed *every step*: a finished
sequence leaves at the step boundary it emits EOS (its pages free
immediately), and a waiting sequence joins the moment a slot and pages are
available, without waiting for the rest of the batch to finish. Admission is
EDF over waiting sequences, slack priced with the live per-token step cost
(``StepCostEWMA`` over decode buckets), against per-tenant SLOs expressed as
inter-token latency.

Correctness invariants (the chaos scenario asserts all three):

- **Atomic emission**: a token is appended to the client stream and the
  sequence's position advanced under one lock, *after* the device step
  completes. A worker that dies mid-step has emitted nothing for that step.
- **Whole-budget reservation**: ``ceil((prompt+max_new)/page_size)`` pages
  are reserved at admission, so KV exhaustion can only happen *before* a
  sequence starts — it stays queued (``KVPoolExhausted`` is absorbed) and
  there is never a half-generated sequence to unwind or re-prefill (which
  would not be bitwise-safe across the prefill/decode paths).
- **Failover requeues, never replays**: a monitor thread polls the worker's
  liveness; on death every RUNNING sequence goes back to the waiting queue
  with its pages, position and emitted tokens intact (``prefilled=True``
  skips re-prefill), the epoch fences the zombie out, and a fresh worker
  continues each sequence at exactly the next token — no duplicates, no
  drops, bitwise-identical output.

Backpressure is lossless: a full client stream pauses the sequence (state
PAUSED, pages kept, not stepped); the stream's resume callback re-runs it.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from ... import config as _config
from ... import telemetry as _telemetry
from ...base import MXNetError
from ...resilience import faults as _faults
from ...resilience.faults import FaultInjected
from ...telemetry import flight as _flight
from .. import tailguard as _tailguard
from ..errors import DeadlineExceeded, KVPoolExhausted, ServerClosedError
from .streams import TokenStream

__all__ = ["DecodeScheduler"]

_RUNNING, _DRAINING, _STOPPED = "running", "draining", "stopped"

# sequence states
_S_WAITING, _S_RUNNING, _S_PAUSED = "waiting", "running", "paused"
_S_DONE, _S_FAILED, _S_CANCELLED = "done", "failed", "cancelled"


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class _Tenant:
    __slots__ = ("name", "slo_us")

    def __init__(self, name: str, slo_us: float):
        self.name = name
        self.slo_us = float(slo_us)


class _Seq:
    __slots__ = ("sid", "tenant", "prompt", "max_new", "eos_id", "stream",
                 "state", "emitted", "pos", "prefilled", "enqueue_us",
                 "last_token_us", "deadline")

    def __init__(self, sid: int, tenant: _Tenant, prompt: Sequence[int],
                 max_new: int, eos_id: Optional[int], stream: TokenStream,
                 deadline=None):
        self.sid = sid
        self.tenant = tenant
        self.prompt = list(prompt)
        self.max_new = max_new
        self.eos_id = eos_id
        self.stream = stream
        self.state = _S_WAITING
        self.emitted: List[int] = []
        self.pos = len(self.prompt)      # tokens materialised in the KV cache
        self.prefilled = False
        self.enqueue_us = _now_us()
        self.last_token_us = 0
        self.deadline = deadline         # propagated tailguard.Deadline


class DecodeScheduler:
    """Continuous-batching loop over one :class:`DecodeEndpoint`.

    One worker thread owns all device work (prefill + decode steps); a
    monitor thread supervises it and drives failover. Clients interact only
    through :meth:`submit` and the returned :class:`TokenStream`.
    """

    def __init__(self, engine, *, default_slo_ms: Optional[float] = None,
                 stream_buffer: Optional[int] = None,
                 poll_s: Optional[float] = None):
        self.engine = engine
        self._stats = engine.stats
        if default_slo_ms is None:
            default_slo_ms = float(_config.get("MXNET_DECODE_SLO_MS"))
        self._default_slo_us = default_slo_ms * 1000.0
        self._stream_buffer = int(
            stream_buffer if stream_buffer is not None
            else _config.get("MXNET_DECODE_STREAM_BUFFER"))
        self._poll_s = float(poll_s if poll_s is not None
                             else _config.get("MXNET_SUPERVISOR_POLL_S"))
        self._cond = threading.Condition(threading.Lock())
        self._state = _STOPPED
        self._epoch = 0
        self._thread: Optional[threading.Thread] = None
        self._monitor: Optional[threading.Thread] = None
        self._monitor_stop = threading.Event()
        self._waiting: deque = deque()
        self._active: List[_Seq] = []       # RUNNING + PAUSED, batch order
        self._by_sid: Dict[int, _Seq] = {}
        self._sids = itertools.count(1)
        self._tenants: Dict[str, _Tenant] = {
            "default": _Tenant("default", self._default_slo_us)}
        self.reports: list = []             # failover reports, newest last

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def add_tenant(self, name: str, slo_ms: Optional[float] = None
                   ) -> "DecodeScheduler":
        """Register a tenant with its inter-token SLO (ms per token)."""
        slo_us = (float(slo_ms) * 1000.0 if slo_ms is not None
                  else self._default_slo_us)
        with self._cond:
            self._tenants[name] = _Tenant(name, slo_us)
        return self

    def start(self) -> "DecodeScheduler":
        with self._cond:
            if self._state == _RUNNING:
                return self
            self._state = _RUNNING
            self._spawn_worker_locked()
        self._monitor_stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name=f"mxtpu-decode-mon-{self.engine.name}",
            daemon=True)
        self._monitor.start()
        return self

    def _spawn_worker_locked(self):    # mxlint: disable=CONC200
        self._epoch += 1
        self._thread = threading.Thread(
            target=self._loop, args=(self._epoch,),
            name=f"mxtpu-decode-{self.engine.name}-gen{self._epoch}",
            daemon=True)
        self._thread.start()

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the loop. ``drain=True`` (graceful) finishes every in-flight
        AND waiting sequence first, refusing new submits; past ``timeout``
        seconds the remainder fail with ServerClosedError."""
        if timeout is None:
            timeout = float(_config.get("MXNET_SERVING_DRAIN_TIMEOUT_S"))
        with self._cond:
            if self._state == _STOPPED and self._thread is None:
                return
            self._state = _DRAINING if drain else _STOPPED
            t = self._thread
            self._cond.notify_all()
        if t is not None:
            t.join(timeout=timeout if drain else 2.0)
        self._monitor_stop.set()
        m, self._monitor = self._monitor, None
        with self._cond:
            self._state = _STOPPED
            self._cond.notify_all()
            leftovers = list(self._active) + list(self._waiting)
            self._active.clear()
            self._waiting.clear()
            for seq in leftovers:
                self._retire_locked(
                    seq, _S_FAILED, "failed",
                    error=ServerClosedError(
                        f"decode scheduler for {self.engine.name!r} stopped "
                        f"before sequence {seq.sid} finished"))
            self._thread = None
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        if m is not None:
            m.join(timeout=self._poll_s * 4 + 1.0)

    def __enter__(self) -> "DecodeScheduler":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------------
    # client surface
    # ------------------------------------------------------------------
    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               tenant: str = "default", eos_id: Optional[int] = None,
               on_token=None, deadline=None) -> TokenStream:
        """Queue one generation; returns its :class:`TokenStream`.

        The prompt plus generation budget must fit the endpoint's
        ``max_seq_len`` — the whole KV budget is reserved at admission so a
        running sequence can never hit pool exhaustion mid-generation.

        ``deadline`` (a propagated :class:`~..tailguard.Deadline`) bounds
        the whole generation: an expired budget refuses admission, and the
        decode loop retires the sequence mid-generation the moment the
        budget runs out (site ``decode_token``). Under brownout (level >= 1)
        ``max_new_tokens`` is clamped to MXNET_BROWNOUT_MAX_NEW_TOKENS —
        generations shorten before anyone is refused.
        """
        if deadline is not None:
            deadline.check("ingress")
        if max_new_tokens is None:
            max_new_tokens = int(_config.get("MXNET_DECODE_MAX_TOKENS"))
        max_new_tokens = _tailguard.BROWNOUT.clamp_max_new_tokens(
            max_new_tokens)
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise MXNetError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise MXNetError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        total = len(prompt) + max_new_tokens
        if total > self.engine.max_seq_len:
            raise MXNetError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) = {total} exceeds max_seq_len "
                f"{self.engine.max_seq_len}")
        with self._cond:
            if self._state != _RUNNING:
                raise ServerClosedError(
                    f"decode scheduler for {self.engine.name!r} is "
                    f"{self._state}; not accepting new sequences")
            ten = self._tenants.get(tenant)
            if ten is None:
                raise MXNetError(f"unknown tenant {tenant!r}; registered: "
                                 f"{sorted(self._tenants)}")
            sid = next(self._sids)
            stream = TokenStream(sid, self._stream_buffer,
                                 on_token=on_token, resume_cb=self._resume)
            seq = _Seq(sid, ten, prompt, int(max_new_tokens), eos_id, stream,
                       deadline=deadline)
            self._waiting.append(seq)
            self._by_sid[sid] = seq
            self._stats.seq_event("submitted")
            self._stats.set_queue_depth(len(self._waiting))
            self._cond.notify_all()
        return stream

    def _resume(self, sid: int):
        """Stream resume callback (consumer thread, stream lock NOT held)."""
        with self._cond:
            seq = self._by_sid.get(sid)
            if seq is not None and seq.state == _S_PAUSED:
                seq.state = _S_RUNNING
                self._stats.seq_event("resumed")
                self._cond.notify_all()

    # ------------------------------------------------------------------
    # the decode loop (worker thread)
    # ------------------------------------------------------------------
    def _loop(self, epoch: int):
        while True:
            with self._cond:
                if self._epoch != epoch:
                    return              # fenced-out zombie generation
                if self._state == _STOPPED:
                    return
                if self._state == _DRAINING and not self._waiting \
                        and not self._active:
                    return
                if self._state == _RUNNING and not self._waiting \
                        and not self._active:
                    self._cond.wait(0.05)
                    continue
                admits = self._admit_locked()
            for seq in admits:
                if seq.prefilled:
                    continue            # requeued by failover: pages intact
                try:
                    tok = self.engine.prefill(
                        seq.prompt, self.engine.pool.table(seq.sid))
                except BaseException as e:
                    with self._cond:
                        self._fail_seq_locked(seq, e)
                    if not isinstance(e, Exception):
                        raise           # WorkerKilled et al: thread dies
                    continue
                seq.prefilled = True
                with self._cond:
                    if self._epoch != epoch:
                        return
                    self._emit_locked(seq, tok)
            with self._cond:
                if self._epoch != epoch:
                    return
                # the per-token deadline hop: a sequence whose end-to-end
                # budget ran out mid-generation is retired BEFORE it costs
                # another device step
                for s in list(self._active):
                    if s.state == _S_RUNNING and s.deadline is not None \
                            and s.deadline.expired():
                        _tailguard.deadline_expired("decode_token")
                        self._fail_seq_locked(s, DeadlineExceeded(
                            f"sequence {s.sid} overran its deadline after "
                            f"{len(s.emitted)} of {s.max_new} tokens"))
                rows = [s for s in self._active if s.state == _S_RUNNING]
                if not rows:
                    if not admits:
                        self._cond.wait(0.005)   # all paused / pool-blocked
                    continue
                batch = [(s, s.emitted[-1], s.pos,
                          self.engine.pool.table(s.sid)) for s in rows]
            try:
                _faults.check("decode")
                toks = self.engine.decode_step(
                    [(tok, pos, table) for _, tok, pos, table in batch])
            except FaultInjected as e:
                _telemetry.event("decode_fault_absorbed", kind=e.kind,
                                 endpoint=self.engine.name)
                continue                # transient: re-form and retry
            except Exception as e:
                with self._cond:
                    for s, _, _, _ in batch:
                        self._fail_seq_locked(s, e)
                continue
            # one decode step = one unit of real work funding the decode
            # tier's retry budget (failover requeues spend from it)
            _tailguard.retry_deposit("decode")
            with self._cond:
                if self._epoch != epoch:
                    return              # died-and-replaced mid-step: the
                                        # new generation already owns these
                                        # sequences; emitting now would dup
                for (s, _, _, _), tok in zip(batch, toks):
                    if s.state not in (_S_RUNNING, _S_PAUSED):
                        continue        # retired concurrently (cancel)
                    s.pos += 1
                    self._emit_locked(s, tok)
                self._stats.set_queue_depth(len(self._waiting))

    def _admit_locked(self) -> List[_Seq]:    # mxlint: disable=CONC200
        """EDF admission: pull waiting sequences into free batch slots,
        most-negative slack first, reserving their whole KV budget. A
        sequence the pool cannot host yet stays queued (smaller later
        arrivals may still fit — no head-of-line blocking)."""
        free = self.engine.max_batch_size - len(self._active)
        if free <= 0 or not self._waiting:
            return []
        now = _now_us()
        rows = max(1, len(self._active))
        bucket = rows if rows in self.engine.decode_buckets else \
            self.engine.decode_buckets[-1]
        for b in self.engine.decode_buckets:
            if rows <= b:
                bucket = b
                break
        per_tok = self.engine.step_cost.estimate(bucket) / max(1, rows)
        ordered = sorted(self._waiting, key=lambda s: self._slack(s, now,
                                                                  per_tok))
        admits: List[_Seq] = []
        for seq in ordered:
            if len(admits) >= free:
                break
            try:
                self.engine.pool.reserve(seq.sid,
                                         len(seq.prompt) + seq.max_new)
            except KVPoolExhausted:
                continue                # stays queued; retried next step
            self._waiting.remove(seq)
            seq.state = _S_RUNNING
            self._active.append(seq)
            self._stats.seq_event("admitted")
            admits.append(seq)
        self._stats.set_queue_depth(len(self._waiting))
        return admits

    def _slack(self, seq: _Seq, now: int, per_tok_us: float) -> float:
        """EDF key: time remaining until the sequence's next token misses
        its tenant's inter-token SLO, minus the predicted cost of producing
        it. A requeued sequence's deadline anchors on its last emitted
        token; a fresh one on its enqueue time."""
        anchor = seq.last_token_us or seq.enqueue_us
        slo = seq.tenant.slo_us or 1e9      # SLO-less: FIFO by anchor
        return (anchor + slo) - now - per_tok_us

    # ------------------------------------------------------------------
    # emission / retirement (caller holds self._cond)
    # ------------------------------------------------------------------
    def _emit_locked(self, seq: _Seq, tok: int):    # mxlint: disable=CONC200
        now = _now_us()
        seq.emitted.append(tok)
        self._stats.tokens(1)
        if seq.last_token_us:
            self._stats.record_intertoken(seq.tenant.name,
                                          now - seq.last_token_us)
        seq.last_token_us = now
        delivered = seq.stream.put(tok)
        if seq.stream.cancelled:
            self._retire_locked(seq, _S_CANCELLED, "cancelled")
            return
        if (seq.eos_id is not None and tok == seq.eos_id) \
                or len(seq.emitted) >= seq.max_new:
            self._retire_locked(seq, _S_DONE, "finished")
            return
        if not delivered and seq.state == _S_RUNNING:
            seq.state = _S_PAUSED
            self._stats.seq_event("paused")
            self._stats.backpressure()

    def _retire_locked(self, seq: _Seq, state: str,    # mxlint: disable=CONC200
                       event: str, error: Optional[BaseException] = None):
        seq.state = state
        if seq in self._active:
            self._active.remove(seq)
        self.engine.pool.free(seq.sid)
        self._by_sid.pop(seq.sid, None)
        seq.stream.close(error)
        self._stats.seq_event(event)

    def _fail_seq_locked(self, seq: _Seq,    # mxlint: disable=CONC200
                         error: BaseException):
        if seq in self._waiting:
            self._waiting.remove(seq)
        self._retire_locked(seq, _S_FAILED, "failed", error=error)

    # ------------------------------------------------------------------
    # supervision (monitor thread)
    # ------------------------------------------------------------------
    def _monitor_loop(self):
        while not self._monitor_stop.wait(self._poll_s):
            try:
                self._check_worker()
            except Exception:
                pass        # supervision must outlive any single bad poll

    def _check_worker(self):
        report = None
        with self._cond:
            if self._state == _STOPPED:
                return
            t = self._thread
            if t is None or t.is_alive():
                return
            candidates = [s for s in self._active if s.state == _S_RUNNING]
            requeued, shed = [], 0
            for seq in candidates:
                self._active.remove(seq)
                # a failover requeue IS a retry of this sequence's remaining
                # tokens: it must win a decode-tier budget token, so a
                # crash-looping worker converts into bounded shed instead of
                # requeueing the same sequences forever
                if not _tailguard.retry_allowed("decode"):
                    self._retire_locked(seq, _S_FAILED, "failed",
                                        error=ServerClosedError(
                                            f"sequence {seq.sid} shed: decode "
                                            "retry budget exhausted during "
                                            "worker failover"))
                    shed += 1
                    continue
                seq.state = _S_WAITING
                self._waiting.appendleft(seq)
                self._stats.seq_event("requeued")
                requeued.append(seq)
            report = {
                "endpoint": self.engine.name,
                "reason": "worker_dead",
                "requeued": len(requeued),
                "shed": shed,
                "paused_kept": len(self._active),
                "epoch": self._epoch,
            }
            self.reports.append(report)
            self._stats.failover("worker_dead")
            self._spawn_worker_locked()
        _telemetry.event("decode_failover", **report)
        _flight.trigger("decode_failover", **report)

    @property
    def failovers(self) -> int:
        with self._cond:
            return len(self.reports)

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._cond:
            return {
                "state": self._state,
                "epoch": self._epoch,
                "waiting": len(self._waiting),
                "running": sum(1 for s in self._active
                               if s.state == _S_RUNNING),
                "paused": sum(1 for s in self._active
                              if s.state == _S_PAUSED),
                "tenants": {n: t.slo_us / 1000.0
                            for n, t in self._tenants.items()},
                "failovers": len(self.reports),
            }
