"""SLO-driven serving autoscaler over a replica pool of InferenceServers.

The persistent executable cache (``mxnet_tpu.cache``) makes replicas cheap:
a new ``InferenceServer`` warms its buckets compile-free by deserializing
the fleet's stored executables, so scale-up costs deserialize time, not an
XLA storm. This module closes the loop the PR 9 telemetry opened — the
per-tenant burn-rate monitor (``telemetry/slo.py``) and queue-depth gauges
become the *inputs* of a control loop that changes the fleet:

**ServingPool** owns N replicas built by a ``replica_factory(replica_id)``
callable (each returns an InferenceServer with its endpoints registered —
endpoint warmup hits the executable cache). Client traffic enters through
``pool.submit(...)`` which routes to the least-loaded replica *in
rotation*; a replica leaves rotation before it drains, so scale-down never
drops an admitted request, and an overloaded replica's rejection falls
through to the next one before the client ever sees it — the zero-downtime
cutover discipline of the hot-swap path, applied to whole replicas.

**Autoscaler** polls every ``MXNET_AUTOSCALE_POLL_S``: the worst fast-window
burn rate and active-alert count across SLO objectives, plus the pool's
queue pressure (worst-endpoint pending rows as a fraction of the queue
bound, averaged over replicas). The decision rule is deliberately boring —

  * over-pressure (alert latched, fast burn over the SLO monitor's
    threshold, or queue pressure over ``MXNET_AUTOSCALE_QUEUE_HIGH``) on
    ``MXNET_AUTOSCALE_UP_N`` *consecutive* polls scales up by one;
  * idleness (no alert, fast burn under 1.0, queue pressure under
    ``MXNET_AUTOSCALE_QUEUE_LOW``) on ``MXNET_AUTOSCALE_DOWN_N``
    consecutive polls scales down by one (drain via the bounded-drain
    path);
  * every action respects ``MXNET_AUTOSCALE_{MIN,MAX}_REPLICAS`` and a
    ``MXNET_AUTOSCALE_COOLDOWN_S`` settle period, and leaves an
    ``autoscale_up`` / ``autoscale_down`` flight event naming the signals
    that justified it — every decision is auditable post-hoc.

``Autoscaler.tick()`` is public and deterministic (pass ``now``), so tests
and chaos drills drive the loop without sleeping.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import CancelledError, Future
from typing import Callable, List, Optional, Tuple

from ..base import MXNetError
from .. import config as _config
from .. import telemetry as _telemetry
from ..resilience import faults as _faults
from ..telemetry import debug_server as _debug
from ..telemetry import flight as _flight
from ..telemetry.slo import MONITOR as _SLO_MONITOR
from . import tailguard as _tailguard
from .batcher import fail as _fail_fut, resolve as _resolve_fut
from .errors import ServerClosedError, ServerOverloadError
from .server import InferenceServer

__all__ = ["ServingPool", "Autoscaler"]


def _now_us() -> int:
    return time.perf_counter_ns() // 1000

_REPLICAS_G = _telemetry.gauge(
    "mxtpu_autoscale_replicas",
    "Serving replicas currently in the pool's rotation.")
_EVENTS = _telemetry.counter(
    "mxtpu_autoscale_events_total",
    "Autoscaler actions taken, by direction (up / down).",
    labelnames=("direction",))
_CAPACITY_G = _telemetry.gauge(
    "mxtpu_pool_replica_capacity",
    "Devices backing each pool replica (a mesh-sharded replica reports its "
    "slice size; single-chip replicas report 1) — the weight submit() "
    "divides queue load by.",
    labelnames=("rid",))


class _Replica:
    __slots__ = ("rid", "server", "capacity")

    def __init__(self, rid: int, server: InferenceServer, capacity: int = 1):
        self.rid = rid
        self.server = server
        self.capacity = max(int(capacity), 1)


class ServingPool:
    """A replica set of InferenceServers behind one submit() front door.

    Parameters
    ----------
    replica_factory : callable
        ``replica_factory(replica_id) -> InferenceServer`` builds one
        replica with its endpoints registered (warmup rides the executable
        cache, so this is deserialize-fast on a warm fleet). The pool
        starts the returned server if the factory did not.
    initial_replicas : int
        Replicas built immediately (default 1).
    """

    def __init__(self, replica_factory: Callable[[int], InferenceServer],
                 initial_replicas: int = 1):
        self._factory = replica_factory
        self._lock = threading.Lock()
        self._replicas: List[_Replica] = []
        self._next_rid = 0
        _debug.attach_pool(self)      # weak: /statusz + /fleetz render us
        for _ in range(max(int(initial_replicas), 0)):
            self.scale_up()

    # ------------------------------------------------------------------
    # elasticity
    # ------------------------------------------------------------------
    def scale_up(self) -> int:
        """Build, start, and put one new replica in rotation; returns its
        replica id. The heavy work (factory + warmup) happens before the
        pool lock is taken — traffic keeps flowing to existing replicas."""
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        server = self._factory(rid)
        if server.state != "running":
            server.start()
        with server._cond:
            capacity = max((getattr(t.endpoint, "capacity", 1)
                            for t in server._router.tenants()), default=1)
        with self._lock:
            self._replicas.append(_Replica(rid, server, capacity))
            n = len(self._replicas)
        _REPLICAS_G.set(n)
        # bounded: rids recycle within MXNET_AUTOSCALE_MAX_REPLICAS
        _CAPACITY_G.labels(str(rid)).set(capacity)  # mxlint: disable=MET301
        return rid

    def scale_down(self, drain_timeout_s: Optional[float] = None
                   ) -> Optional[int]:
        """Remove the newest replica from rotation, THEN drain it — every
        admitted request completes, new traffic already routes elsewhere.
        Returns the drained replica id, or None when the pool is down to
        one replica (never drains the last)."""
        with self._lock:
            if len(self._replicas) <= 1:
                return None
            victim = self._replicas.pop()      # out of rotation first
            n = len(self._replicas)
        _REPLICAS_G.set(n)
        victim.server.stop(drain=True, timeout=drain_timeout_s)
        return victim.rid

    # ------------------------------------------------------------------
    # client surface
    # ------------------------------------------------------------------
    def _rotation(self) -> List[_Replica]:
        with self._lock:
            return list(self._replicas)

    def submit(self, name: str, inputs, deadline_ms: Optional[float] = None,
               deadline=None):
        """Route one request to the least-loaded replica in rotation,
        where load is queued rows divided by replica capacity — a 4-chip
        mesh-sharded replica keeps attracting traffic until it holds ~4x a
        single chip's queue, so heterogeneous pools utilize every chip.
        A replica that sheds (overload / mid-cutover close) falls through
        to the next-least-loaded one before the error reaches the client.

        With hedging enabled (``MXNET_HEDGE_ENABLE`` + a >=2 replica pool),
        a request still pending after the adaptive hedge delay is duplicated
        onto the next-least-loaded replica; the first response settles the
        returned Future and the loser is cancelled (dropped at batch
        assembly, never mid-step). ``deadline`` is the end-to-end
        :class:`~.tailguard.Deadline` minted at ingress; it rides into the
        replica's queue unchanged."""
        _faults.check("pool_submit")
        if deadline is not None:
            deadline.check("pool_submit")
        replicas = self._rotation()
        if not replicas:
            raise ServerClosedError("serving pool has no replicas")
        ranked = sorted(replicas, key=self._load_of)
        _tailguard.hedge_deposit()
        born_us = _now_us()
        primary, primary_rep = self._submit_ranked(
            name, inputs, deadline_ms, deadline, ranked)
        hedge_pool = [r for r in ranked if r is not primary_rep]
        if not (_tailguard.HEDGER.enabled() and hedge_pool):
            primary.add_done_callback(
                lambda f: _tailguard.HEDGER.observe_latency(
                    _now_us() - born_us))
            return primary
        return self._hedged(name, inputs, deadline_ms, deadline,
                            hedge_pool, primary, born_us)

    def _submit_ranked(self, name: str, inputs,
                       deadline_ms: Optional[float], deadline,
                       ranked: List[_Replica]) -> Tuple[Future, _Replica]:
        """The fallthrough core: try replicas in load order, returning the
        admitted Future and the replica that took it."""
        last_exc: Optional[Exception] = None
        for rep in ranked:
            try:
                # the span stamps this attempt's replica into the journey
                # AND hands its trace id to the request the batcher builds
                # inside submit() — the replica hop is traceable end to end
                with _telemetry.span("pool.submit", replica=rep.rid,
                                     endpoint=name):
                    return rep.server.submit(
                        name, inputs, deadline_ms=deadline_ms,
                        deadline=deadline), rep
            except (ServerOverloadError, ServerClosedError) as e:
                last_exc = e
        raise last_exc

    def _predicted_step_us(self, name: str) -> float:
        """Cost-model / EWMA predicted device time of this endpoint's next
        batch (the Router's scheduling estimate) — the hedge delay's prior
        for workloads the latency ring has not warmed yet. 0.0 when
        unknowable."""
        try:
            replicas = self._rotation()
            if not replicas:
                return 0.0
            srv = replicas[0].server
            with srv._cond:
                tenant = srv._router.find(name)
                if tenant is None:
                    return 0.0
                return float(srv._router.est_step_us(tenant))
        except Exception:
            return 0.0

    def _hedged(self, name: str, inputs, deadline_ms: Optional[float],
                deadline, hedge_pool: List[_Replica], primary: Future,
                born_us: int) -> Future:
        """Wrap an admitted primary with the hedge race: after the adaptive
        delay a budgeted duplicate goes to the next replica; the first
        *successful* arm settles the client Future (a failed arm defers to
        the other while it is still pending), the loser is cancelled."""
        out: Future = Future()
        lock = threading.Lock()
        state = {"done": False, "hedge": None, "timer": None}

        def settle(f: Future, is_hedge: bool):
            try:
                err = f.exception()
            except CancelledError:
                return                    # the cancelled loser reporting in
            with lock:
                if state["done"]:
                    return
                other = primary if is_hedge else state["hedge"]
                if err is not None and other is not None \
                        and not other.done():
                    return                # lost by failing; other arm decides
                state["done"] = True
                timer = state["timer"]
                loser = other
            if timer is not None:
                timer.cancel()
            _tailguard.HEDGER.observe_latency(_now_us() - born_us)
            if is_hedge and err is None:
                _tailguard.hedge_won()
            if loser is not None:
                if loser.cancel():
                    _tailguard.hedge_cancelled()
                else:
                    _tailguard.hedge_wasted()
            if err is not None:
                _fail_fut(out, err)
            else:
                _resolve_fut(out, f.result())

        def launch_hedge():
            with lock:
                if state["done"]:
                    return
            if deadline is not None and deadline.expired():
                return                    # no budget left to speculate into
            if not _tailguard.hedge_allowed():
                return
            try:
                hf, _rep = self._submit_ranked(
                    name, inputs, deadline_ms, deadline, hedge_pool)
            except Exception:
                return                    # no replica would take the hedge
            _tailguard.hedge_launched()
            lost_race = False
            with lock:
                if state["done"]:
                    lost_race = True
                else:
                    state["hedge"] = hf
            if lost_race:                 # primary settled while we admitted
                if hf.cancel():
                    _tailguard.hedge_cancelled()
                else:
                    _tailguard.hedge_wasted()
                return
            hf.add_done_callback(lambda f: settle(f, True))

        delay_s = _tailguard.HEDGER.delay_s(self._predicted_step_us(name))
        timer = threading.Timer(delay_s, launch_hedge)  # mxlint: disable=THR400
        timer.daemon = True
        state["timer"] = timer
        primary.add_done_callback(lambda f: settle(f, False))
        with lock:
            fast = state["done"]
        if not fast:                      # don't spawn timers for requests
            timer.start()                 # that already finished
        return out

    def predict(self, name: str, inputs, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None):
        return self.submit(name, inputs, deadline_ms).result(timeout=timeout)

    @staticmethod
    def _raw_load(rep: _Replica) -> int:
        srv = rep.server
        with srv._cond:
            return sum(len(t.queue) for t in srv._router.tenants())

    @classmethod
    def _load_of(cls, rep: _Replica) -> float:
        return cls._raw_load(rep) / rep.capacity

    # ------------------------------------------------------------------
    # signals / lifecycle
    # ------------------------------------------------------------------
    def queue_pressure(self) -> float:
        """Worst-endpoint pending rows over the queue bound, averaged over
        replicas in rotation — 0.0 idle, 1.0 every queue full."""
        replicas = self._rotation()
        if not replicas:
            return 0.0
        vals = []
        for rep in replicas:
            srv = rep.server
            with srv._cond:
                tenants = srv._router.tenants()
            worst = 0.0
            for t in tenants:
                cap = max(t.queue.max_queue_rows, 1)
                worst = max(worst, t.queue.pending_rows / cap)
            vals.append(worst)
        return sum(vals) / len(vals)

    def size(self) -> int:
        with self._lock:
            return len(self._replicas)

    def snapshot(self) -> dict:
        replicas = self._rotation()
        return {"replicas": [{"rid": r.rid, "state": r.server.state,
                              "capacity": r.capacity,
                              "load": self._raw_load(r),
                              "weighted_load": round(self._load_of(r), 4)}
                             for r in replicas],
                "size": len(replicas),
                "queue_pressure": round(self.queue_pressure(), 4)}

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop every replica (drained by default)."""
        with self._lock:
            replicas, self._replicas = self._replicas, []
        _REPLICAS_G.set(0)
        for rep in replicas:
            rep.server.stop(drain=drain, timeout=timeout)

    def __enter__(self) -> "ServingPool":
        return self

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False


class Autoscaler:
    """The control loop: SLO burn + queue pressure in, scale actions out.

    Every constructor argument pins the matching ``MXNET_AUTOSCALE_*`` knob
    (None = read it live each poll, the SLOMonitor convention). ``tick()``
    is the whole loop body — call it directly (with an explicit ``now``)
    for deterministic tests, or ``start()`` the poll thread.
    """

    def __init__(self, pool: ServingPool, monitor=None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 poll_s: Optional[float] = None,
                 up_n: Optional[int] = None,
                 down_n: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 queue_high: Optional[float] = None,
                 queue_low: Optional[float] = None,
                 time_fn=time.monotonic):
        self.pool = pool
        self._monitor = monitor if monitor is not None else _SLO_MONITOR
        self._min = min_replicas
        self._max = max_replicas
        self._poll = poll_s
        self._up_n = up_n
        self._down_n = down_n
        self._cooldown = cooldown_s
        self._q_high = queue_high
        self._q_low = queue_low
        self._now = time_fn
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._over_polls = 0
        self._idle_polls = 0
        self._last_action_ts: Optional[float] = None
        self.actions: list = []      # action report dicts, newest last
        _debug.attach_autoscaler(self)   # weak: /statusz + /fleetz

    # -- knob-backed settings (read live unless pinned) --------------------
    @property
    def min_replicas(self) -> int:
        return self._min if self._min is not None else \
            int(_config.get("MXNET_AUTOSCALE_MIN_REPLICAS", 1))

    @property
    def max_replicas(self) -> int:
        return self._max if self._max is not None else \
            int(_config.get("MXNET_AUTOSCALE_MAX_REPLICAS", 4))

    @property
    def poll_s(self) -> float:
        return self._poll if self._poll is not None else \
            float(_config.get("MXNET_AUTOSCALE_POLL_S", 1.0))

    @property
    def up_n(self) -> int:
        return self._up_n if self._up_n is not None else \
            int(_config.get("MXNET_AUTOSCALE_UP_N", 2))

    @property
    def down_n(self) -> int:
        return self._down_n if self._down_n is not None else \
            int(_config.get("MXNET_AUTOSCALE_DOWN_N", 5))

    @property
    def cooldown_s(self) -> float:
        return self._cooldown if self._cooldown is not None else \
            float(_config.get("MXNET_AUTOSCALE_COOLDOWN_S", 10.0))

    @property
    def queue_high(self) -> float:
        return self._q_high if self._q_high is not None else \
            float(_config.get("MXNET_AUTOSCALE_QUEUE_HIGH", 0.5))

    @property
    def queue_low(self) -> float:
        return self._q_low if self._q_low is not None else \
            float(_config.get("MXNET_AUTOSCALE_QUEUE_LOW", 0.05))

    # ------------------------------------------------------------------
    # signals + decision
    # ------------------------------------------------------------------
    def predicted_warmup_s(self) -> float:
        """Cost-model predicted compile wall (seconds) for warming one
        fresh replica: the sum of every tenant endpoint's
        ``predicted_warmup_s()`` on the first replica in rotation (all
        replicas serve the same endpoint set). 0.0 without an active
        model — scale-up timing is then exactly the pre-model behavior."""
        try:
            replicas = self.pool._rotation()
            if not replicas:
                return 0.0
            srv = replicas[0].server
            with srv._cond:
                tenants = list(srv._router.tenants())
        except Exception:
            return 0.0
        total = 0.0
        for t in tenants:
            fn = getattr(t.endpoint, "predicted_warmup_s", None)
            if fn is None:
                continue
            try:
                total += float(fn() or 0.0)
            except Exception:
                pass
        return total

    def signals(self) -> dict:
        """One poll's worth of evidence: the worst fast-window burn rate and
        the active-alert count across SLO objectives, plus the pool's queue
        pressure and the cost model's predicted replica warm-up time."""
        max_fast = 0.0
        alerts = 0
        for st in self._monitor.check_all():
            max_fast = max(max_fast, float(st.get("fast_burn", 0.0)))
            alerts += 1 if st.get("alert_active") else 0
        return {"max_fast_burn": round(max_fast, 3),
                "alerts_active": alerts,
                "queue_pressure": round(self.pool.queue_pressure(), 4),
                "predicted_warmup_s": round(self.predicted_warmup_s(), 3),
                "replicas": self.pool.size()}

    def _decide(self, sig: dict, now: float) -> Optional[str]:
        """Pure-ish decision core: updates hysteresis counters, returns
        'up' / 'down' / None. Cooldown and min/max bounds are enforced
        here so every caller of tick() gets the same discipline.

        The predicted warm-up signal buys lead time: every full poll
        period of predicted compile wall a new replica will spend warming
        shaves one poll off the scale-up hysteresis (never below one) —
        an expensive-to-warm fleet commits earlier, because the capacity
        it is buying arrives later."""
        over = (sig["alerts_active"] > 0
                or sig["max_fast_burn"] >= self._monitor.burn_threshold
                or sig["queue_pressure"] >= self.queue_high)
        idle = (sig["alerts_active"] == 0
                and sig["max_fast_burn"] < 1.0
                and sig["queue_pressure"] <= self.queue_low)
        up_need = self.up_n
        lead = float(sig.get("predicted_warmup_s", 0.0) or 0.0)
        if lead > 0.0:
            up_need = max(1, up_need - int(lead // max(self.poll_s, 1e-9)))
        with self._lock:
            self._over_polls = self._over_polls + 1 if over else 0
            self._idle_polls = self._idle_polls + 1 if idle else 0
            in_cooldown = (self._last_action_ts is not None
                           and now - self._last_action_ts < self.cooldown_s)
            if in_cooldown:
                return None
            if over and self._over_polls >= up_need \
                    and sig["replicas"] < self.max_replicas:
                self._over_polls = 0
                self._last_action_ts = now
                return "up"
            if idle and self._idle_polls >= self.down_n \
                    and sig["replicas"] > self.min_replicas:
                self._idle_polls = 0
                self._last_action_ts = now
                return "down"
        return None

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """One control-loop turn: read signals, decide, act. Returns the
        action report ({"action", "rid", **signals}) or None."""
        if now is None:
            now = self._now()
        # the brownout ladder rides this poll loop for free: same cadence,
        # same burn evidence, no thread of its own
        _tailguard.BROWNOUT.tick(now)
        sig = self.signals()
        verdict = self._decide(sig, now)
        if verdict is None:
            return None
        if verdict == "up":
            rid = self.pool.scale_up()
        else:
            rid = self.pool.scale_down()
            if rid is None:          # pool refused (last replica)
                return None
        report = dict(sig, action=verdict, rid=rid,
                      replicas=self.pool.size())
        _EVENTS.labels(verdict).inc()
        _flight.event(f"autoscale_{verdict}", **report)
        with self._lock:
            self.actions.append(report)
        return report

    # ------------------------------------------------------------------
    # poll thread
    # ------------------------------------------------------------------
    def start(self) -> "Autoscaler":
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            if self.poll_s <= 0:
                raise MXNetError("MXNET_AUTOSCALE_POLL_S must be > 0")
            self._stop_ev.clear()
            self._thread = threading.Thread(
                target=self._run, name="mxtpu-autoscaler", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        with self._lock:
            self._stop_ev.set()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.poll_s * 4 + 1.0)

    def _run(self):
        while not self._stop_ev.wait(self.poll_s):
            try:
                self.tick()
            except Exception:
                pass        # scaling must outlive any single bad poll

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def snapshot(self) -> dict:
        with self._lock:
            actions = list(self.actions)
            over, idle = self._over_polls, self._idle_polls
            last_ts = self._last_action_ts
        now = self._now()
        age = (now - last_ts) if last_ts is not None else None
        return {"pool": self.pool.snapshot(), "actions": actions,
                "over_polls": over, "idle_polls": idle,
                "up_n": self.up_n, "down_n": self.down_n,
                "cooldown_s": self.cooldown_s,
                "last_action_age_s": round(age, 3) if age is not None
                else None,
                "in_cooldown": bool(age is not None
                                    and age < self.cooldown_s),
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas}
