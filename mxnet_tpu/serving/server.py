"""InferenceServer: the request -> batch -> device -> response loop.

One worker thread owns all device work (the single-dispatcher discipline the
reference gets from its engine thread): client threads only validate, cast to
host numpy, and enqueue under the shared condition — so arbitrary client
concurrency never races JAX dispatch. The worker waits until some endpoint
queue is ready (full batch, batch timeout, or drain), assembles a batch with
expired requests dropped, runs the padded bucket step, slices per-request
rows back out, and resolves futures AFTER the device result is ready — so the
recorded request latency is honest end-to-end time.

Shutdown is graceful by default: ``stop(drain=True)`` flushes every admitted
request through the device before the thread exits, while new submissions are
already being refused; ``drain=False`` fails pending futures immediately.

When the profiler is running, every device step is recorded through the same
``_dispatch_profiled`` sink ops and CachedOp use, so serving steps land in the
chrome trace / aggregate table alongside per-op events.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional, Tuple

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .batcher import (EndpointQueue, Request, concat_inputs, fail,
                      resolve)
from .endpoint import ModelEndpoint
from .errors import ServerClosedError, ServerOverloadError

__all__ = ["InferenceServer"]

_RUNNING, _DRAINING, _STOPPED = "running", "draining", "stopped"


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class InferenceServer:
    """Dynamic-batching inference front-end over registered ModelEndpoints.

    Parameters
    ----------
    batch_timeout_ms : float
        Max time the oldest queued request waits before a partial batch is
        dispatched anyway (the latency half of the batching trade-off).
    max_queue : int
        Admission-control bound, in rows, per endpoint. Submissions beyond it
        raise ServerOverloadError instead of growing the queue.
    """

    def __init__(self, batch_timeout_ms: float = 2.0, max_queue: int = 256):
        self._batch_timeout_us = int(batch_timeout_ms * 1000)
        self._max_queue_rows = int(max_queue)
        self._queues: Dict[str, EndpointQueue] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._state = _STOPPED
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # endpoint management
    # ------------------------------------------------------------------
    def register(self, endpoint: ModelEndpoint, warmup: bool = True
                 ) -> ModelEndpoint:
        """Attach an endpoint; by default compiles every shape bucket now so
        no request ever pays first-compile latency."""
        with self._cond:
            if endpoint.name in self._queues:
                raise MXNetError(f"endpoint {endpoint.name!r} already registered")
            self._queues[endpoint.name] = EndpointQueue(
                endpoint, self._max_queue_rows, self._batch_timeout_us)
        if warmup:
            endpoint.warmup()
        return endpoint

    def endpoints(self):
        with self._cond:
            return sorted(self._queues)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "InferenceServer":
        with self._cond:
            if self._state != _STOPPED:
                raise MXNetError(f"server is {self._state}")
            self._state = _RUNNING
            self._thread = threading.Thread(
                target=self._loop, name="mxtpu-serving-worker", daemon=True)
            self._thread.start()
        return self

    def stop(self, drain: bool = True):
        """Stop serving. ``drain=True`` (default) processes every admitted
        request before returning; ``drain=False`` fails them immediately."""
        with self._cond:
            if self._state == _STOPPED:
                return
            if drain:
                self._state = _DRAINING
            else:
                self._state = _STOPPED
                exc = ServerClosedError("server stopped without drain")
                for q in self._queues.values():
                    q.fail_all(exc)
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    @property
    def state(self) -> str:
        return self._state

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False

    # ------------------------------------------------------------------
    # client surface
    # ------------------------------------------------------------------
    def submit(self, name: str, inputs, deadline_ms: Optional[float] = None
               ) -> Future:
        """Enqueue a request; returns a Future resolving to the endpoint's
        output (an NDArray, or a tuple for multi-output models). A single
        example (no batch axis) resolves without a batch axis; a batch of n
        rows resolves to n-row outputs.

        Raises ServerOverloadError when the bounded queue is full and
        ServerClosedError when the server is not accepting work."""
        with self._cond:
            if name not in self._queues:
                raise MXNetError(f"unknown endpoint {name!r}; registered: "
                                 f"{sorted(self._queues)}")
            q = self._queues[name]
        req = self._make_request(q.endpoint, inputs, deadline_ms)
        with self._cond:
            if self._state != _RUNNING:
                raise ServerClosedError(f"server is {self._state}")
            if not q.offer(req):
                raise ServerOverloadError(
                    f"endpoint {name!r} queue full "
                    f"({q.pending_rows} rows >= {q.max_queue_rows}); retry with backoff")
            self._cond.notify()
        return req.future

    def predict(self, name: str, inputs, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None):
        """Blocking convenience wrapper over submit()."""
        return self.submit(name, inputs, deadline_ms).result(timeout=timeout)

    def _make_request(self, ep: ModelEndpoint, inputs,
                      deadline_ms: Optional[float]) -> Request:
        """Validate + host-normalize one request OUTSIDE the lock: every
        input becomes a contiguous numpy batch in the endpoint dtype."""
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)
        if len(inputs) != len(ep.input_shapes):
            raise MXNetError(f"endpoint {ep.name!r} takes "
                             f"{len(ep.input_shapes)} inputs, got {len(inputs)}")
        host = []
        rows = None
        squeeze = None
        for i, (x, shape, npdt) in enumerate(
                zip(inputs, ep.input_shapes, ep.np_dtypes)):
            a = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
            if a.shape == shape:
                a = a[None]
                sq = True
            elif a.shape[1:] == shape:
                sq = False
            else:
                raise MXNetError(
                    f"endpoint {ep.name!r} input {i}: expected per-example "
                    f"shape {shape} (optionally batched), got {a.shape}")
            if rows is None:
                rows, squeeze = a.shape[0], sq
            elif a.shape[0] != rows:
                raise MXNetError(f"endpoint {ep.name!r}: inputs disagree on "
                                 f"batch rows ({rows} vs {a.shape[0]})")
            if a.dtype != npdt:
                a = a.astype(npdt)
            host.append(onp.ascontiguousarray(a))
        if rows > ep.max_batch_size:
            raise MXNetError(
                f"request of {rows} rows exceeds endpoint {ep.name!r} "
                f"max_batch_size={ep.max_batch_size}; split the request")
        return Request(tuple(host), rows, squeeze, deadline_ms)

    # ------------------------------------------------------------------
    # worker loop
    # ------------------------------------------------------------------
    def _loop(self):
        while True:
            with self._cond:
                batch, q = self._wait_for_batch()
                if batch is None:
                    self._state = _STOPPED
                    return
            if batch:
                self._dispatch(q, batch)

    def _wait_for_batch(self):
        """Block (holding the lock) until some queue is ready, a drain can
        finish, or the server stops. Returns (requests, queue); requests may
        be [] when all ready work had expired, and (None, None) on exit."""
        while True:
            if self._state == _STOPPED:
                return None, None
            now = _now_us()
            flush = self._state == _DRAINING
            ready = [q for q in self._queues.values() if q.ready(now, flush)]
            if ready:
                # oldest head request first: closest to its latency budget
                q = min(ready, key=lambda q: q._pending[0].enqueue_us)
                return q.take_batch(now), q
            if flush:                      # draining and nothing pending
                return None, None
            wakeups = [t for q in self._queues.values()
                       for t in (q.next_wakeup_us(),) if t is not None]
            timeout = (max(min(wakeups) - now, 0) / 1e6) if wakeups else None
            self._cond.wait(timeout=timeout)

    def _dispatch(self, q: EndpointQueue, batch):
        from .. import telemetry
        ep = q.endpoint
        rows = sum(r.rows for r in batch)
        host_inputs = concat_inputs(batch, len(ep.input_shapes))
        from ..ops.registry import _profiler_running
        profiling = _profiler_running()
        t0 = _now_us()
        try:
            # adopt the oldest request's trace id for the whole batch step:
            # its end-to-end trace (submit -> batch -> device) is the one
            # closest to the latency budget, and the span records how many
            # requests/rows rode along
            with telemetry.span("serving.batch", trace_id=batch[0].trace_id,
                                endpoint=ep.name, rows=rows,
                                requests=len(batch)):
                if profiling:
                    from .. import profiler
                    outs, bucket = profiler._dispatch_profiled(
                        f"serving[{ep.name}]b{rows}",
                        lambda: ep.run_batch(host_inputs, rows), cat="serving")
                else:
                    outs, bucket = ep.run_batch(host_inputs, rows)
        except Exception as e:  # compile/runtime failure fails the whole batch
            for r in batch:
                fail(r.future, e)
            return
        step_us = _now_us() - t0
        ep.stats.record_step(step_us)
        off = 0
        done = _now_us()
        for r in batch:
            sliced = tuple(
                NDArray(o[off] if r.squeeze else o[off:off + r.rows], ctx=ep.ctx)
                for o in outs)
            resolve(r.future, sliced[0] if ep.num_outputs == 1 else sliced)
            ep.stats.record_latency(done - r.enqueue_us)
            ep.stats.bump("completed")
            if profiling:
                from .. import profiler
                profiler.record_duration(f"serving[{ep.name}].request",
                                         r.enqueue_us, done - r.enqueue_us,
                                         cat="serving")
            off += r.rows
