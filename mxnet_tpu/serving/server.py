"""InferenceServer: the request -> batch -> device -> response loop.

r6 rebuilt this from a one-endpoint-at-a-time, prepare-then-step loop into a
pipelined multi-tenant scheduler. Three coupled pieces:

**Router** (router.py): many ModelEndpoints (tenants) multiplex over the one
device-owning dispatch path. The next batch is picked earliest-deadline-first
across tenants, priced by each bucket's measured step-time EWMA, with
shortest-job-first among already-late tenants — a long batch cannot convoy
short requests — plus an anti-starvation escalation. Batches assemble at the
last moment (continuous batching): rows arriving during device step k join
the assembly for step k+1 instead of waiting out the in-flight step.

**Double-buffered host pipeline** (pipeline.py): a prep thread assembles and
``device_put``s batch k+1 into the next parity's input-buffer set while the
worker executes batch k, handing fully-built device buffers to the worker
under the shared condition — host time leaves the critical path (the
host/device overlap discipline of TensorFlow's dataflow executor). The
dispatch discipline stays single-owner: only the worker thread invokes
compiled executables; the prep thread touches JAX for host->device transfer
alone; client threads only validate, cast to host numpy, and enqueue.
``pipeline=False`` keeps the serial prepare-then-step path (same scheduler,
same executables — the bitwise reference for the pipelined path).

**Per-tenant shedding**: each tenant gets its own CircuitBreaker (unless the
server was built with an explicit shared ``breaker`` — the legacy
single-tenant contract), so one tenant's failures or stalls tighten *that
tenant's* admission (DEGRADED: half its queue bound; OPEN: shed all) while
the others keep serving. ``health()`` reports the worst circuit across
tenants plus per-tenant states.

Everything the serial server guaranteed still holds: bounded-queue
backpressure (ServerOverloadError at admission), per-request deadlines
enforced at assembly (expired work never occupies device rows), graceful
*bounded* drain (``stop(drain=True)`` flushes admitted work, abandons past
``drain_timeout_s`` — counted in ``mxtpu_drain_abandoned_total``), bitwise
per-request outputs (same executables, same padding), and per-batch
RetryPolicy + Watchdog + profiler integration on every device step.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as onp

from ..base import MXNetError
from .. import config as _config
from .. import telemetry as _telemetry
from ..ndarray.ndarray import NDArray
from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy
from ..resilience.watchdog import (CircuitBreaker, Watchdog, DEGRADED,
                                   HALF_OPEN, HEALTHY, OPEN)
from .batcher import EndpointQueue, Request, fail, resolve
from .endpoint import ModelEndpoint
from .errors import ServerClosedError, ServerOverloadError
from .pipeline import OverlapTracker, PreparedBatch, prepare_batch
from .router import Router, Tenant

__all__ = ["InferenceServer"]

_RUNNING, _DRAINING, _STOPPED = "running", "draining", "stopped"

#: how bad is a circuit state, for the worst-of health aggregation
_CIRCUIT_SEVERITY = {HEALTHY: 0, DEGRADED: 1, HALF_OPEN: 2, OPEN: 3}

_DRAIN_ABANDONED = _telemetry.counter(
    "mxtpu_drain_abandoned_total",
    "Requests abandoned because stop(drain=True) hit its timeout with the "
    "worker wedged; each one was failed with ServerClosedError.")


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class InferenceServer:
    """Pipelined, multi-tenant dynamic-batching front-end over registered
    ModelEndpoints.

    Parameters
    ----------
    batch_timeout_ms : float
        Max time the oldest queued request waits before a partial batch is
        dispatched anyway (the latency half of the batching trade-off).
    max_queue : int
        Default admission-control bound, in rows, per endpoint (override
        per tenant at :meth:`register`). Submissions beyond it raise
        ServerOverloadError instead of growing the queue.
    retry_policy : resilience.RetryPolicy, optional
        Per-batch device-step retry (default: MXNET_RETRY_* config).
    breaker : resilience.CircuitBreaker, optional
        When given, ALL tenants share this breaker (the legacy single-tenant
        contract). When omitted, each tenant gets its own
        ``CircuitBreaker(scope="serving:<name>")`` — per-tenant shedding.
    watchdog_stall_s : float, optional
        Hang threshold for one device batch step (default
        MXNET_WATCHDOG_STALL_S). A stall degrades the stalled tenant's
        circuit breaker.
    drain_timeout_s : float, optional
        Bound on stop(drain=True) (default MXNET_SERVING_DRAIN_TIMEOUT_S).
    pipeline : bool
        True (default): double-buffered host pipeline — a prep thread
        overlaps batch k+1's concat/pad/device_put with device step k.
        False: serial prepare-then-step in the worker thread (bitwise
        reference path; same scheduler, same executables).
    """

    #: prepared batches allowed to wait for the worker (1 + the in-flight
    #: batch = the two parities of the double buffer)
    _PIPELINE_DEPTH = 1

    def __init__(self, batch_timeout_ms: float = 2.0, max_queue: int = 256,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 watchdog_stall_s: Optional[float] = None,
                 drain_timeout_s: Optional[float] = None,
                 pipeline: bool = True):
        self._batch_timeout_us = int(batch_timeout_ms * 1000)
        self._max_queue_rows = int(max_queue)
        self._pipeline = bool(pipeline)
        self._router = Router(self._batch_timeout_us)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._state = _STOPPED
        self._thread: Optional[threading.Thread] = None       # worker
        self._prep_thread: Optional[threading.Thread] = None  # prep stage
        self._prep_done = True
        self._prepared: "list[PreparedBatch]" = []
        self._overlap = OverlapTracker()
        self._retry = retry_policy if retry_policy is not None \
            else RetryPolicy.from_config()
        self._shared_breaker = breaker          # None => per-tenant breakers
        self._breaker = breaker if breaker is not None \
            else CircuitBreaker(scope="serving")
        self._watchdog = Watchdog(stall_s=watchdog_stall_s,
                                  on_stall=self._on_stall)
        self._drain_timeout_s = float(
            drain_timeout_s if drain_timeout_s is not None
            else _config.get("MXNET_SERVING_DRAIN_TIMEOUT_S"))

    # ------------------------------------------------------------------
    # endpoint management
    # ------------------------------------------------------------------
    def register(self, endpoint: ModelEndpoint, warmup: bool = True,
                 max_queue: Optional[int] = None,
                 slo_ms: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None) -> ModelEndpoint:
        """Attach an endpoint as a tenant; by default compiles every shape
        bucket now so no request ever pays first-compile latency (warmup also
        seeds the scheduler's per-bucket step-cost EWMA).

        ``max_queue`` overrides the server default queue bound (the tenant's
        row quota); ``slo_ms`` sets the tenant's scheduling SLO — requests
        without an explicit deadline are scheduled as if due ``slo_ms`` after
        submit; ``breaker`` overrides the tenant's circuit breaker."""
        with self._cond:
            if endpoint.name in self._router:
                raise MXNetError(f"endpoint {endpoint.name!r} already registered")
            q = EndpointQueue(
                endpoint,
                int(max_queue) if max_queue is not None
                else self._max_queue_rows,
                self._batch_timeout_us)
            if breaker is None:
                breaker = self._shared_breaker if self._shared_breaker \
                    is not None else CircuitBreaker(
                        scope=f"serving:{endpoint.name}")
            self._router.add(Tenant(
                endpoint.name, endpoint, q, breaker,
                slo_us=int(slo_ms * 1000) if slo_ms is not None else None))
        if warmup:
            endpoint.warmup()
        return endpoint

    def endpoints(self):
        with self._cond:
            return self._router.names()

    def breaker_for(self, name: str) -> CircuitBreaker:
        """The named tenant's circuit breaker (per-tenant shedding state)."""
        with self._cond:
            if name not in self._router:
                raise MXNetError(f"unknown endpoint {name!r}; registered: "
                                 f"{self._router.names()}")
            return self._router.get(name).breaker

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "InferenceServer":
        with self._cond:
            if self._state != _STOPPED:
                raise MXNetError(f"server is {self._state}")
            for t in (self._thread, self._prep_thread):
                if t is not None and t.is_alive():
                    raise MXNetError(
                        "a previous worker is still wedged in a device call "
                        "(abandoned drain); this server cannot be restarted")
            self._state = _RUNNING
            self._prepared.clear()
            self._prep_done = not self._pipeline
            self._thread = threading.Thread(
                target=self._loop_exec if self._pipeline
                else self._loop_serial,
                name="mxtpu-serving-worker", daemon=True)
            if self._pipeline:
                self._prep_thread = threading.Thread(
                    target=self._loop_prep, name="mxtpu-serving-prep",
                    daemon=True)
                self._prep_thread.start()
            else:
                self._prep_thread = None
            self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop serving. ``drain=True`` (default) processes every admitted
        request before returning, but never waits longer than ``timeout``
        seconds (default ``drain_timeout_s``): past it the remaining requests
        are abandoned — failed with ServerClosedError and counted in
        ``mxtpu_drain_abandoned_total`` — so a wedged endpoint queue cannot
        hang shutdown forever. ``drain=False`` fails them immediately."""
        timeout = self._drain_timeout_s if timeout is None else float(timeout)
        with self._cond:
            if self._state == _STOPPED and self._thread is None and \
                    self._prep_thread is None:
                return
            # snapshot the thread handles under the lock: a concurrent stop()
            # (or a start() after abandon) must never see half-cleared
            # handles, so all joining below works on the locals
            worker, prep = self._thread, self._prep_thread
            if drain:
                self._state = _DRAINING
            else:
                self._state = _STOPPED
                exc = ServerClosedError("server stopped without drain")
                self._router.fail_all(exc)
                self._fail_prepared(exc)
            self._cond.notify_all()
        deadline = time.monotonic() + timeout
        for t in (prep, worker):
            if t is not None:
                t.join(max(deadline - time.monotonic(), 0.0))
        if any(t is not None and t.is_alive() for t in (prep, worker)):
            # drain wedged (hung device step / endpoint queue): abandon.
            # The daemon threads may eventually finish their in-flight call;
            # they will find the state _STOPPED and exit, and resolve() on
            # already-failed futures is a no-op.
            with self._cond:
                self._state = _STOPPED
                exc = ServerClosedError(
                    f"drain abandoned after {timeout:.1f}s "
                    "(worker wedged)")
                abandoned = self._router.fail_all(exc)
                abandoned += self._fail_prepared(exc)
                self._cond.notify_all()
            if abandoned:
                _DRAIN_ABANDONED.inc(abandoned)
            for t in (prep, worker):
                if t is not None:
                    t.join(1.0)
            if any(t is not None and t.is_alive() for t in (prep, worker)):
                # keep the handles: start() must refuse to run a second
                # worker beside a wedged one
                self._watchdog.stop()
                return
        with self._cond:
            if self._thread is worker:
                self._thread = None
            if self._prep_thread is prep:
                self._prep_thread = None
        self._watchdog.stop()

    @property
    def state(self) -> str:
        return self._state

    def health(self) -> dict:
        """Operator health snapshot: server lifecycle state, the worst
        circuit-breaker state across tenants (plus each tenant's own state
        and recent transitions), per-endpoint queue depth, and watchdog
        stall count."""
        with self._cond:
            state = self._state
            tenants = self._router.tenants()
        breakers = [self._breaker]
        endpoints = {}
        for t in tenants:
            if all(t.breaker is not b for b in breakers):
                breakers.append(t.breaker)
            endpoints[t.name] = {
                "pending_requests": len(t.queue),
                "pending_rows": t.queue.pending_rows,
                "circuit": t.breaker.state(),
                "slo_ms": t.slo_us / 1000.0 if t.slo_us else None,
            }
        worst = max((b.state() for b in breakers),
                    key=lambda s: _CIRCUIT_SEVERITY[s])
        return {"state": state,
                "circuit": worst,
                "breaker": self._breaker.snapshot(),
                "tenants": {t.name: t.breaker.snapshot() for t in tenants},
                "endpoints": endpoints,
                "prep_overlap_ratio": self._overlap.ratio(),
                "watchdog_stalls": self._watchdog.stalls}

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False

    # ------------------------------------------------------------------
    # client surface
    # ------------------------------------------------------------------
    def submit(self, name: str, inputs, deadline_ms: Optional[float] = None
               ) -> Future:
        """Enqueue a request; returns a Future resolving to the endpoint's
        output (an NDArray, or a tuple for multi-output models). A single
        example (no batch axis) resolves without a batch axis; a batch of n
        rows resolves to n-row outputs.

        Raises ServerOverloadError when the tenant's bounded queue is full
        or its circuit breaker is shedding load (OPEN: everything;
        HALF_OPEN: beyond the probe budget; DEGRADED: beyond half the queue
        bound) and ServerClosedError when the server is not accepting
        work."""
        with self._cond:
            if name not in self._router:
                raise MXNetError(f"unknown endpoint {name!r}; registered: "
                                 f"{self._router.names()}")
            tenant = self._router.get(name)
        q = tenant.queue
        if not tenant.breaker.allow():
            q.endpoint.stats.bump("rejected")
            q.endpoint.stats.record_shed(f"circuit_{tenant.breaker.state()}")
            raise ServerOverloadError(
                f"endpoint {name!r} circuit {tenant.breaker.state()}: "
                "shedding load until the device recovers; retry with backoff")
        req = self._make_request(q.endpoint, inputs, deadline_ms)
        with self._cond:
            if self._state != _RUNNING:
                raise ServerClosedError(f"server is {self._state}")
            # graceful degradation: while DEGRADED admit only up to half the
            # tenant's queue bound, so a struggling device sees less queued
            # latency — per-tenant: other tenants keep their full bound
            if tenant.breaker.state() == DEGRADED and \
                    q.pending_rows + req.rows > q.max_queue_rows // 2:
                q.endpoint.stats.bump("rejected")
                q.endpoint.stats.record_shed("degraded")
                raise ServerOverloadError(
                    f"endpoint {name!r} degraded: admission tightened to "
                    f"{q.max_queue_rows // 2} rows; retry with backoff")
            if not q.offer(req):
                q.endpoint.stats.record_shed("queue_full")
                raise ServerOverloadError(
                    f"endpoint {name!r} queue full "
                    f"({q.pending_rows} rows >= {q.max_queue_rows}); retry with backoff")
            self._cond.notify_all()
        return req.future

    def predict(self, name: str, inputs, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None):
        """Blocking convenience wrapper over submit()."""
        return self.submit(name, inputs, deadline_ms).result(timeout=timeout)

    def _make_request(self, ep: ModelEndpoint, inputs,
                      deadline_ms: Optional[float]) -> Request:
        """Validate + host-normalize one request OUTSIDE the lock: every
        input becomes a contiguous numpy batch in the endpoint dtype."""
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)
        if len(inputs) != len(ep.input_shapes):
            raise MXNetError(f"endpoint {ep.name!r} takes "
                             f"{len(ep.input_shapes)} inputs, got {len(inputs)}")
        host = []
        rows = None
        squeeze = None
        for i, (x, shape, npdt) in enumerate(
                zip(inputs, ep.input_shapes, ep.np_dtypes)):
            a = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
            if a.shape == shape:
                a = a[None]
                sq = True
            elif a.shape[1:] == shape:
                sq = False
            else:
                raise MXNetError(
                    f"endpoint {ep.name!r} input {i}: expected per-example "
                    f"shape {shape} (optionally batched), got {a.shape}")
            if rows is None:
                rows, squeeze = a.shape[0], sq
            elif a.shape[0] != rows:
                raise MXNetError(f"endpoint {ep.name!r}: inputs disagree on "
                                 f"batch rows ({rows} vs {a.shape[0]})")
            if a.dtype != npdt:
                a = a.astype(npdt)
            host.append(onp.ascontiguousarray(a))
        if rows > ep.max_batch_size:
            raise MXNetError(
                f"request of {rows} rows exceeds endpoint {ep.name!r} "
                f"max_batch_size={ep.max_batch_size}; split the request")
        return Request(tuple(host), rows, squeeze, deadline_ms)

    # ------------------------------------------------------------------
    # shared scheduling helpers (caller holds the condition lock)
    # ------------------------------------------------------------------
    def _next_assembly(self):  # mxlint: disable=CONC200
        """Block (holding the lock) until the Router yields a tenant whose
        batch should assemble now, a drain can finish, or the server stops.
        Returns (tenant, requests); requests may be [] when all ready work
        had expired, and None on exit (stopped, or drain complete)."""
        while True:
            if self._state == _STOPPED:
                return None
            now = _now_us()
            flush = self._state == _DRAINING
            if len(self._prepared) >= self._PIPELINE_DEPTH:
                # handoff slot occupied: nothing to do until the worker pops
                # it (notify_all) — do NOT wake on batch deadlines, assembly
                # cannot proceed anyway (bounded wait in case the worker
                # dies mid-batch; stop() notifies too)
                self._cond.wait(timeout=0.25)
                continue
            tenant = self._router.select(now, flush)
            if tenant is not None:
                return tenant, tenant.queue.take_batch(now)
            if flush:
                # slot free + nothing ready under flush => queues are empty
                return None
            wakeup = self._router.next_wakeup_us()
            timeout = (max(wakeup - now, 0) / 1e6) if wakeup is not None \
                else None
            self._cond.wait(timeout=timeout)

    def _fail_prepared(self, exc: Exception) -> int:  # mxlint: disable=CONC200
        """Fail every prepared-but-unexecuted batch (caller holds the lock);
        returns the number of requests failed."""
        n = 0
        while self._prepared:
            pb = self._prepared.pop(0)
            for r in pb.requests:
                pb.tenant.endpoint.stats.bump("cancelled")
                fail(r.future, exc)
                n += 1
        return n

    def _on_stall(self, name: str, dt: float):
        """Watchdog hook: a stalled device step degrades the *stalled
        tenant's* circuit (falling back to the server breaker when the watch
        name is not a tenant's)."""
        ep_name = name.partition("[")[2].rstrip("]")
        tenant = self._router.find(ep_name)
        br = tenant.breaker if tenant is not None else self._breaker
        br.force_degraded(f"stall {name} {dt:.1f}s")

    # ------------------------------------------------------------------
    # serial worker (pipeline=False): assemble -> prepare -> execute inline
    # ------------------------------------------------------------------
    def _loop_serial(self):
        while True:
            with self._cond:
                item = self._next_assembly()
                if item is None:
                    self._state = _STOPPED
                    self._cond.notify_all()
                    return
            tenant, batch = item
            if not batch:
                continue
            pb = self._prepare(tenant, batch, 0)
            if pb is not None:
                self._execute(pb)

    # ------------------------------------------------------------------
    # pipelined prep stage: assemble + device_put batch k+1 during step k
    # ------------------------------------------------------------------
    def _loop_prep(self):
        parity = 0
        while True:
            with self._cond:
                item = self._next_assembly()
                if item is None:
                    self._prep_done = True
                    self._cond.notify_all()
                    return
            tenant, batch = item
            if not batch:
                continue
            pb = self._prepare(tenant, batch, parity)
            if pb is None:
                continue                  # prep failed; futures already failed
            parity ^= 1                   # flip the double-buffer parity
            with self._cond:
                if self._state == _STOPPED:
                    exc = ServerClosedError("server stopped")
                    for r in pb.requests:
                        tenant.endpoint.stats.bump("cancelled")
                        fail(r.future, exc)
                    continue
                self._prepared.append(pb)
                self._cond.notify_all()

    def _prepare(self, tenant: Tenant, batch, parity: int
                 ) -> Optional[PreparedBatch]:
        """Run the host prep for one assembled batch (lock NOT held); on
        failure fail the batch's futures against the tenant's breaker."""
        try:
            return prepare_batch(tenant, batch, parity, self._overlap,
                                 self._retry)
        except Exception as e:
            tenant.breaker.record_failure()
            for r in batch:
                fail(r.future, e)
            return None

    # ------------------------------------------------------------------
    # pipelined worker: execute prepared batches (the only executable caller)
    # ------------------------------------------------------------------
    def _loop_exec(self):
        while True:
            with self._cond:
                pb = self._next_prepared()
                if pb is None:
                    self._state = _STOPPED
                    self._cond.notify_all()
                    return
            self._execute(pb)

    def _next_prepared(self) -> Optional[PreparedBatch]:  # mxlint: disable=CONC200
        """Block (holding the lock) for the next prepared batch; None on
        stop, or when a drain has flushed everything through."""
        while True:
            if self._state == _STOPPED:
                return None
            if self._prepared:
                pb = self._prepared.pop(0)
                self._cond.notify_all()    # the handoff slot is free again
                return pb
            if self._state == _DRAINING and self._prep_done:
                return None
            self._cond.wait()

    # ------------------------------------------------------------------
    # device dispatch (worker thread only)
    # ------------------------------------------------------------------
    def _execute(self, pb: PreparedBatch):
        from .. import telemetry
        ep = pb.tenant.endpoint
        from ..ops.registry import _profiler_running
        profiling = _profiler_running()
        t0 = _now_us()

        def run_step():
            _faults.check("serving_dispatch")
            step = lambda: ep.execute(pb.inputs, pb.bucket, pb.rows,
                                      padded_host=pb.padded_host)
            if profiling:
                from .. import profiler
                return profiler._dispatch_profiled(
                    f"serving[{ep.name}]b{pb.rows}", step, cat="serving")
            return step()

        self._overlap.step_begin()
        try:
            # adopt the oldest request's trace id for the whole batch step:
            # its end-to-end trace (submit -> batch -> device) is the one
            # closest to the latency budget, and the span records how many
            # requests/rows rode along
            with telemetry.span("serving.batch",
                                trace_id=pb.requests[0].trace_id,
                                endpoint=ep.name, rows=pb.rows,
                                requests=len(pb.requests)):
                with self._watchdog.watch(f"serving[{ep.name}]"):
                    # retries must respect what clients asked for: never back
                    # off past the earliest request deadline in the batch
                    outs = self._retry.run(run_step, site="serving_dispatch",
                                           deadline_us=pb.deadline_us)
        except Exception as e:  # retries exhausted / fatal: fail the batch
            pb.tenant.breaker.record_failure()
            for r in pb.requests:
                fail(r.future, e)
            return
        finally:
            self._overlap.step_end()
        pb.tenant.breaker.record_success()
        ep.stats.record_step(_now_us() - t0)
        off = 0
        done = _now_us()
        for r in pb.requests:
            sliced = tuple(
                NDArray(o[off] if r.squeeze else o[off:off + r.rows], ctx=ep.ctx)
                for o in outs)
            resolve(r.future, sliced[0] if ep.num_outputs == 1 else sliced)
            ep.stats.record_latency(done - r.enqueue_us)
            ep.stats.bump("completed")
            if profiling:
                from .. import profiler
                profiler.record_duration(f"serving[{ep.name}].request",
                                         r.enqueue_us, done - r.enqueue_us,
                                         cat="serving")
            off += r.rows
