"""InferenceServer: the request -> batch -> device -> response loop.

One worker thread owns all device work (the single-dispatcher discipline the
reference gets from its engine thread): client threads only validate, cast to
host numpy, and enqueue under the shared condition — so arbitrary client
concurrency never races JAX dispatch. The worker waits until some endpoint
queue is ready (full batch, batch timeout, or drain), assembles a batch with
expired requests dropped, runs the padded bucket step, slices per-request
rows back out, and resolves futures AFTER the device result is ready — so the
recorded request latency is honest end-to-end time.

Shutdown is graceful by default: ``stop(drain=True)`` flushes every admitted
request through the device before the thread exits, while new submissions are
already being refused; the drain is *bounded* — past ``drain_timeout_s`` the
remaining requests are abandoned (failed with ServerClosedError, counted in
``mxtpu_drain_abandoned_total``) so a wedged endpoint can never hang shutdown
forever. ``drain=False`` fails pending futures immediately.

Fault tolerance (mxnet_tpu.resilience): each device batch step runs under a
RetryPolicy — transient failures (device OOM, UNAVAILABLE) are retried with
backoff as long as the batch's earliest request deadline allows; a Watchdog
flags batch steps that hang past the stall threshold; and a CircuitBreaker
aggregates dispatch outcomes into HEALTHY → DEGRADED (admission tightens to
half the queue bound) → OPEN (every submit shed with ServerOverloadError) →
HALF_OPEN (bounded probes) → HEALTHY, surfaced via :meth:`health`.

When the profiler is running, every device step is recorded through the same
``_dispatch_profiled`` sink ops and CachedOp use, so serving steps land in the
chrome trace / aggregate table alongside per-op events.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional, Tuple

import numpy as onp

from ..base import MXNetError
from .. import config as _config
from .. import telemetry as _telemetry
from ..ndarray.ndarray import NDArray
from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy
from ..resilience.watchdog import CircuitBreaker, Watchdog, DEGRADED
from .batcher import (EndpointQueue, Request, concat_inputs, fail,
                      resolve)
from .endpoint import ModelEndpoint
from .errors import ServerClosedError, ServerOverloadError

__all__ = ["InferenceServer"]

_RUNNING, _DRAINING, _STOPPED = "running", "draining", "stopped"

_DRAIN_ABANDONED = _telemetry.counter(
    "mxtpu_drain_abandoned_total",
    "Requests abandoned because stop(drain=True) hit its timeout with the "
    "worker wedged; each one was failed with ServerClosedError.")


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class InferenceServer:
    """Dynamic-batching inference front-end over registered ModelEndpoints.

    Parameters
    ----------
    batch_timeout_ms : float
        Max time the oldest queued request waits before a partial batch is
        dispatched anyway (the latency half of the batching trade-off).
    max_queue : int
        Admission-control bound, in rows, per endpoint. Submissions beyond it
        raise ServerOverloadError instead of growing the queue.
    retry_policy : resilience.RetryPolicy, optional
        Per-batch device-step retry (default: MXNET_RETRY_* config).
    breaker : resilience.CircuitBreaker, optional
        Graceful-degradation state machine (default: MXNET_CIRCUIT_* config,
        scope "serving").
    watchdog_stall_s : float, optional
        Hang threshold for one device batch step (default
        MXNET_WATCHDOG_STALL_S). A stall degrades the circuit breaker.
    drain_timeout_s : float, optional
        Bound on stop(drain=True) (default MXNET_SERVING_DRAIN_TIMEOUT_S).
    """

    def __init__(self, batch_timeout_ms: float = 2.0, max_queue: int = 256,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 watchdog_stall_s: Optional[float] = None,
                 drain_timeout_s: Optional[float] = None):
        self._batch_timeout_us = int(batch_timeout_ms * 1000)
        self._max_queue_rows = int(max_queue)
        self._queues: Dict[str, EndpointQueue] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._state = _STOPPED
        self._thread: Optional[threading.Thread] = None
        self._retry = retry_policy if retry_policy is not None \
            else RetryPolicy.from_config()
        self._breaker = breaker if breaker is not None \
            else CircuitBreaker(scope="serving")
        self._watchdog = Watchdog(
            stall_s=watchdog_stall_s,
            on_stall=lambda name, dt: self._breaker.force_degraded(
                f"stall {name} {dt:.1f}s"))
        self._drain_timeout_s = float(
            drain_timeout_s if drain_timeout_s is not None
            else _config.get("MXNET_SERVING_DRAIN_TIMEOUT_S"))

    # ------------------------------------------------------------------
    # endpoint management
    # ------------------------------------------------------------------
    def register(self, endpoint: ModelEndpoint, warmup: bool = True
                 ) -> ModelEndpoint:
        """Attach an endpoint; by default compiles every shape bucket now so
        no request ever pays first-compile latency."""
        with self._cond:
            if endpoint.name in self._queues:
                raise MXNetError(f"endpoint {endpoint.name!r} already registered")
            self._queues[endpoint.name] = EndpointQueue(
                endpoint, self._max_queue_rows, self._batch_timeout_us)
        if warmup:
            endpoint.warmup()
        return endpoint

    def endpoints(self):
        with self._cond:
            return sorted(self._queues)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "InferenceServer":
        with self._cond:
            if self._state != _STOPPED:
                raise MXNetError(f"server is {self._state}")
            if self._thread is not None and self._thread.is_alive():
                raise MXNetError(
                    "a previous worker is still wedged in a device call "
                    "(abandoned drain); this server cannot be restarted")
            self._state = _RUNNING
            self._thread = threading.Thread(
                target=self._loop, name="mxtpu-serving-worker", daemon=True)
            self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop serving. ``drain=True`` (default) processes every admitted
        request before returning, but never waits longer than ``timeout``
        seconds (default ``drain_timeout_s``): past it the remaining requests
        are abandoned — failed with ServerClosedError and counted in
        ``mxtpu_drain_abandoned_total`` — so a wedged endpoint queue cannot
        hang shutdown forever. ``drain=False`` fails them immediately."""
        timeout = self._drain_timeout_s if timeout is None else float(timeout)
        with self._cond:
            if self._state == _STOPPED and self._thread is None:
                return
            # snapshot the worker handle under the lock: a concurrent stop()
            # (or a start() after abandon) must never see a half-cleared
            # self._thread, so all joining below works on the local
            thread = self._thread
            if drain:
                self._state = _DRAINING
            else:
                self._state = _STOPPED
                exc = ServerClosedError("server stopped without drain")
                for q in self._queues.values():
                    q.fail_all(exc)
            self._cond.notify_all()
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                # drain wedged (hung device step / endpoint queue): abandon.
                # The daemon worker may eventually finish its in-flight call;
                # it will find the state _STOPPED and exit, and resolve() on
                # already-failed futures is a no-op.
                abandoned = 0
                with self._cond:
                    self._state = _STOPPED
                    exc = ServerClosedError(
                        f"drain abandoned after {timeout:.1f}s "
                        "(worker wedged)")
                    for q in self._queues.values():
                        abandoned += len(q)
                        q.fail_all(exc)
                    self._cond.notify_all()
                if abandoned:
                    _DRAIN_ABANDONED.inc(abandoned)
                thread.join(1.0)
                if thread.is_alive():
                    # keep the handle: start() must refuse to run a second
                    # worker beside a wedged one
                    self._watchdog.stop()
                    return
            with self._cond:
                if self._thread is thread:
                    self._thread = None
        self._watchdog.stop()

    @property
    def state(self) -> str:
        return self._state

    def health(self) -> dict:
        """Operator health snapshot: server lifecycle state, circuit-breaker
        state machine (HEALTHY/DEGRADED/OPEN/HALF_OPEN + recent transitions),
        per-endpoint queue depth, and watchdog stall count."""
        with self._cond:
            state = self._state
            endpoints = {name: {"pending_requests": len(q),
                                "pending_rows": q.pending_rows}
                         for name, q in self._queues.items()}
        return {"state": state,
                "circuit": self._breaker.state(),
                "breaker": self._breaker.snapshot(),
                "endpoints": endpoints,
                "watchdog_stalls": self._watchdog.stalls}

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False

    # ------------------------------------------------------------------
    # client surface
    # ------------------------------------------------------------------
    def submit(self, name: str, inputs, deadline_ms: Optional[float] = None
               ) -> Future:
        """Enqueue a request; returns a Future resolving to the endpoint's
        output (an NDArray, or a tuple for multi-output models). A single
        example (no batch axis) resolves without a batch axis; a batch of n
        rows resolves to n-row outputs.

        Raises ServerOverloadError when the bounded queue is full or the
        circuit breaker is shedding load (OPEN: everything; HALF_OPEN:
        beyond the probe budget; DEGRADED: beyond half the queue bound) and
        ServerClosedError when the server is not accepting work."""
        with self._cond:
            if name not in self._queues:
                raise MXNetError(f"unknown endpoint {name!r}; registered: "
                                 f"{sorted(self._queues)}")
            q = self._queues[name]
        if not self._breaker.allow():
            q.endpoint.stats.bump("rejected")
            raise ServerOverloadError(
                f"circuit {self._breaker.state()}: shedding load until the "
                "device recovers; retry with backoff")
        req = self._make_request(q.endpoint, inputs, deadline_ms)
        with self._cond:
            if self._state != _RUNNING:
                raise ServerClosedError(f"server is {self._state}")
            # graceful degradation: while DEGRADED admit only up to half the
            # queue bound, so a struggling device sees less queued latency
            if self._breaker.state() == DEGRADED and \
                    q.pending_rows + req.rows > q.max_queue_rows // 2:
                q.endpoint.stats.bump("rejected")
                raise ServerOverloadError(
                    f"endpoint {name!r} degraded: admission tightened to "
                    f"{q.max_queue_rows // 2} rows; retry with backoff")
            if not q.offer(req):
                raise ServerOverloadError(
                    f"endpoint {name!r} queue full "
                    f"({q.pending_rows} rows >= {q.max_queue_rows}); retry with backoff")
            self._cond.notify()
        return req.future

    def predict(self, name: str, inputs, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None):
        """Blocking convenience wrapper over submit()."""
        return self.submit(name, inputs, deadline_ms).result(timeout=timeout)

    def _make_request(self, ep: ModelEndpoint, inputs,
                      deadline_ms: Optional[float]) -> Request:
        """Validate + host-normalize one request OUTSIDE the lock: every
        input becomes a contiguous numpy batch in the endpoint dtype."""
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)
        if len(inputs) != len(ep.input_shapes):
            raise MXNetError(f"endpoint {ep.name!r} takes "
                             f"{len(ep.input_shapes)} inputs, got {len(inputs)}")
        host = []
        rows = None
        squeeze = None
        for i, (x, shape, npdt) in enumerate(
                zip(inputs, ep.input_shapes, ep.np_dtypes)):
            a = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
            if a.shape == shape:
                a = a[None]
                sq = True
            elif a.shape[1:] == shape:
                sq = False
            else:
                raise MXNetError(
                    f"endpoint {ep.name!r} input {i}: expected per-example "
                    f"shape {shape} (optionally batched), got {a.shape}")
            if rows is None:
                rows, squeeze = a.shape[0], sq
            elif a.shape[0] != rows:
                raise MXNetError(f"endpoint {ep.name!r}: inputs disagree on "
                                 f"batch rows ({rows} vs {a.shape[0]})")
            if a.dtype != npdt:
                a = a.astype(npdt)
            host.append(onp.ascontiguousarray(a))
        if rows > ep.max_batch_size:
            raise MXNetError(
                f"request of {rows} rows exceeds endpoint {ep.name!r} "
                f"max_batch_size={ep.max_batch_size}; split the request")
        return Request(tuple(host), rows, squeeze, deadline_ms)

    # ------------------------------------------------------------------
    # worker loop
    # ------------------------------------------------------------------
    def _loop(self):
        while True:
            with self._cond:
                batch, q = self._wait_for_batch()
                if batch is None:
                    self._state = _STOPPED
                    return
            if batch:
                self._dispatch(q, batch)

    def _wait_for_batch(self):
        """Block (holding the lock) until some queue is ready, a drain can
        finish, or the server stops. Returns (requests, queue); requests may
        be [] when all ready work had expired, and (None, None) on exit."""
        while True:
            if self._state == _STOPPED:
                return None, None
            now = _now_us()
            flush = self._state == _DRAINING
            ready = [q for q in self._queues.values() if q.ready(now, flush)]
            if ready:
                # oldest head request first: closest to its latency budget
                q = min(ready, key=lambda q: q._pending[0].enqueue_us)
                return q.take_batch(now), q
            if flush:                      # draining and nothing pending
                return None, None
            wakeups = [t for q in self._queues.values()
                       for t in (q.next_wakeup_us(),) if t is not None]
            timeout = (max(min(wakeups) - now, 0) / 1e6) if wakeups else None
            self._cond.wait(timeout=timeout)

    def _dispatch(self, q: EndpointQueue, batch):
        from .. import telemetry
        ep = q.endpoint
        rows = sum(r.rows for r in batch)
        host_inputs = concat_inputs(batch, len(ep.input_shapes))
        from ..ops.registry import _profiler_running
        profiling = _profiler_running()
        t0 = _now_us()
        # retries must respect what clients asked for: never back off past
        # the earliest request deadline in the batch
        deadlines = [r.deadline_us for r in batch if r.deadline_us is not None]
        deadline_us = min(deadlines) if deadlines else None

        def run_step():
            _faults.check("serving_dispatch")
            if profiling:
                from .. import profiler
                return profiler._dispatch_profiled(
                    f"serving[{ep.name}]b{rows}",
                    lambda: ep.run_batch(host_inputs, rows), cat="serving")
            return ep.run_batch(host_inputs, rows)

        try:
            # adopt the oldest request's trace id for the whole batch step:
            # its end-to-end trace (submit -> batch -> device) is the one
            # closest to the latency budget, and the span records how many
            # requests/rows rode along
            with telemetry.span("serving.batch", trace_id=batch[0].trace_id,
                                endpoint=ep.name, rows=rows,
                                requests=len(batch)):
                with self._watchdog.watch(f"serving[{ep.name}]"):
                    outs, bucket = self._retry.run(
                        run_step, site="serving_dispatch",
                        deadline_us=deadline_us)
        except Exception as e:  # retries exhausted / fatal: fail the batch
            self._breaker.record_failure()
            for r in batch:
                fail(r.future, e)
            return
        self._breaker.record_success()
        step_us = _now_us() - t0
        ep.stats.record_step(step_us)
        off = 0
        done = _now_us()
        for r in batch:
            sliced = tuple(
                NDArray(o[off] if r.squeeze else o[off:off + r.rows], ctx=ep.ctx)
                for o in outs)
            resolve(r.future, sliced[0] if ep.num_outputs == 1 else sliced)
            ep.stats.record_latency(done - r.enqueue_us)
            ep.stats.bump("completed")
            if profiling:
                from .. import profiler
                profiler.record_duration(f"serving[{ep.name}].request",
                                         r.enqueue_us, done - r.enqueue_us,
                                         cat="serving")
            off += r.rows
