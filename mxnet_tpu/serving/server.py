"""InferenceServer: the request -> batch -> device -> response loop.

r6 rebuilt this from a one-endpoint-at-a-time, prepare-then-step loop into a
pipelined multi-tenant scheduler. Three coupled pieces:

**Router** (router.py): many ModelEndpoints (tenants) multiplex over the one
device-owning dispatch path. The next batch is picked earliest-deadline-first
across tenants, priced by each bucket's measured step-time EWMA, with
shortest-job-first among already-late tenants — a long batch cannot convoy
short requests — plus an anti-starvation escalation. Batches assemble at the
last moment (continuous batching): rows arriving during device step k join
the assembly for step k+1 instead of waiting out the in-flight step.

**Double-buffered host pipeline** (pipeline.py): a prep thread assembles and
``device_put``s batch k+1 into the next parity's input-buffer set while the
worker executes batch k, handing fully-built device buffers to the worker
under the shared condition — host time leaves the critical path (the
host/device overlap discipline of TensorFlow's dataflow executor). The
dispatch discipline stays single-owner: only the worker thread invokes
compiled executables; the prep thread touches JAX for host->device transfer
alone; client threads only validate, cast to host numpy, and enqueue.
``pipeline=False`` keeps the serial prepare-then-step path (same scheduler,
same executables — the bitwise reference for the pipelined path).

**Per-tenant shedding**: each tenant gets its own CircuitBreaker (unless the
server was built with an explicit shared ``breaker`` — the legacy
single-tenant contract), so one tenant's failures or stalls tighten *that
tenant's* admission (DEGRADED: half its queue bound; OPEN: shed all) while
the others keep serving. ``health()`` reports the worst circuit across
tenants plus per-tenant states.

Everything the serial server guaranteed still holds: bounded-queue
backpressure (ServerOverloadError at admission), per-request deadlines
enforced at assembly (expired work never occupies device rows), graceful
*bounded* drain (``stop(drain=True)`` flushes admitted work, abandons past
``drain_timeout_s`` — counted in ``mxtpu_drain_abandoned_total``), bitwise
per-request outputs (same executables, same padding), and per-batch
RetryPolicy + Watchdog + profiler integration on every device step.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as onp

from ..base import MXNetError
from .. import config as _config
from .. import telemetry as _telemetry
from ..telemetry import debug_server as _debug
from ..telemetry import flight as _flight
from ..telemetry.slo import MONITOR as _SLO
from ..ndarray.ndarray import NDArray
from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy
from ..resilience.watchdog import (CircuitBreaker, Watchdog, DEGRADED,
                                   HALF_OPEN, HEALTHY, OPEN)
from .batcher import EndpointQueue, Request, fail, resolve
from .endpoint import ModelEndpoint
from .errors import (HotSwapError, RequestTimeoutError, ServerClosedError,
                     ServerOverloadError)
from .pipeline import OverlapTracker, PreparedBatch, prepare_batch
from .router import Router, Tenant
from . import tailguard as _tailguard

__all__ = ["InferenceServer"]

_RUNNING, _DRAINING, _STOPPED = "running", "draining", "stopped"

#: returned by the wait loops to a worker/prep thread whose epoch was
#: superseded by a failover: exit silently, a replacement is already running
_SUPERSEDED = object()

#: how bad is a circuit state, for the worst-of health aggregation
_CIRCUIT_SEVERITY = {HEALTHY: 0, DEGRADED: 1, HALF_OPEN: 2, OPEN: 3}

_DRAIN_ABANDONED = _telemetry.counter(
    "mxtpu_drain_abandoned_total",
    "Requests abandoned because stop(drain=True) hit its timeout with the "
    "worker wedged: queued-never-batched ones failed with ServerClosedError, "
    "ones already inside a prepared/in-flight batch with "
    "RequestTimeoutError — never left to hang a waiting client.")

_FAILOVERS = _telemetry.counter(
    "mxtpu_serving_failovers_total",
    "Worker failovers performed, by reason: worker_dead (thread crashed) / "
    "worker_wedged (in-flight batch outlived the watchdog stall threshold) "
    "/ prep_dead (prep thread crashed).", labelnames=("reason",))
_FAILOVER_REQUEUED = _telemetry.counter(
    "mxtpu_serving_failover_requeued_total",
    "Requests returned to the front of their tenant queues by a failover "
    "(from prepared / in-flight batches of the dead worker); deadlines are "
    "re-checked at re-assembly.")


class _SwapRequest:
    """One routed hot-swap: host-staged weights + probe riding the worker's
    command path, applied between batches (the batch-boundary cutover)."""

    __slots__ = ("tenant", "host_params", "probe", "label", "future")

    def __init__(self, tenant, host_params, probe, label):
        self.tenant = tenant
        self.host_params = host_params
        self.probe = probe
        self.label = label
        self.future = Future()


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class InferenceServer:
    """Pipelined, multi-tenant dynamic-batching front-end over registered
    ModelEndpoints.

    Parameters
    ----------
    batch_timeout_ms : float
        Max time the oldest queued request waits before a partial batch is
        dispatched anyway (the latency half of the batching trade-off).
    max_queue : int
        Default admission-control bound, in rows, per endpoint (override
        per tenant at :meth:`register`). Submissions beyond it raise
        ServerOverloadError instead of growing the queue.
    retry_policy : resilience.RetryPolicy, optional
        Per-batch device-step retry (default: MXNET_RETRY_* config).
    breaker : resilience.CircuitBreaker, optional
        When given, ALL tenants share this breaker (the legacy single-tenant
        contract). When omitted, each tenant gets its own
        ``CircuitBreaker(scope="serving:<name>")`` — per-tenant shedding.
    watchdog_stall_s : float, optional
        Hang threshold for one device batch step (default
        MXNET_WATCHDOG_STALL_S). A stall degrades the stalled tenant's
        circuit breaker.
    drain_timeout_s : float, optional
        Bound on stop(drain=True) (default MXNET_SERVING_DRAIN_TIMEOUT_S).
    pipeline : bool
        True (default): double-buffered host pipeline — a prep thread
        overlaps batch k+1's concat/pad/device_put with device step k.
        False: serial prepare-then-step in the worker thread (bitwise
        reference path; same scheduler, same executables).
    pipeline_depth : int, optional
        Prepared batches allowed to wait for the worker. Depth d cycles
        d+1 staging/input parities, so the slot prep writes is never one a
        queued or in-flight batch still references; 1 (the default, via
        ``MXNET_SERVING_PIPELINE_DEPTH``) is classic double-buffering.
    """

    #: class default; instances resolve pipeline_depth/config in __init__
    _PIPELINE_DEPTH = 1

    def __init__(self, batch_timeout_ms: float = 2.0, max_queue: int = 256,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 watchdog_stall_s: Optional[float] = None,
                 drain_timeout_s: Optional[float] = None,
                 pipeline: bool = True,
                 pipeline_depth: Optional[int] = None):
        self._batch_timeout_us = int(batch_timeout_ms * 1000)
        self._max_queue_rows = int(max_queue)
        self._pipeline = bool(pipeline)
        depth = int(pipeline_depth if pipeline_depth is not None
                    else _config.get("MXNET_SERVING_PIPELINE_DEPTH"))
        if depth < 1:
            raise MXNetError(f"pipeline_depth must be >= 1, got {depth}")
        self._PIPELINE_DEPTH = depth
        self._router = Router(self._batch_timeout_us)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._state = _STOPPED
        self._thread: Optional[threading.Thread] = None       # worker
        self._prep_thread: Optional[threading.Thread] = None  # prep stage
        self._prep_done = True
        self._prepared: "list[PreparedBatch]" = []
        # failover bookkeeping: which thread generation is current (stale
        # workers exit when superseded), what each stage is holding right now
        # (so a failover can requeue it), and pending hot-swap commands
        self._epoch = 0
        self._inflight: Optional[PreparedBatch] = None
        self._preparing = None          # (tenant, [requests]) during prep
        self._swaps: "list[_SwapRequest]" = []
        self._stall_listeners: list = []
        self.failovers = 0
        self._overlap = OverlapTracker()
        self._retry = retry_policy if retry_policy is not None \
            else RetryPolicy.from_config()
        self._shared_breaker = breaker          # None => per-tenant breakers
        self._breaker = breaker if breaker is not None \
            else CircuitBreaker(scope="serving")
        self._watchdog = Watchdog(stall_s=watchdog_stall_s,
                                  on_stall=self._on_stall)
        self._drain_timeout_s = float(
            drain_timeout_s if drain_timeout_s is not None
            else _config.get("MXNET_SERVING_DRAIN_TIMEOUT_S"))
        self._generators: Dict[str, object] = {}   # name -> DecodeScheduler

    # ------------------------------------------------------------------
    # endpoint management
    # ------------------------------------------------------------------
    def register(self, endpoint: ModelEndpoint, warmup: bool = True,
                 max_queue: Optional[int] = None,
                 slo_ms: Optional[float] = None,
                 slo_target: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 tier: str = "gold") -> ModelEndpoint:
        """Attach an endpoint as a tenant; by default compiles every shape
        bucket now so no request ever pays first-compile latency (warmup also
        seeds the scheduler's per-bucket step-cost EWMA).

        ``max_queue`` overrides the server default queue bound (the tenant's
        row quota); ``slo_ms`` sets the tenant's scheduling SLO — requests
        without an explicit deadline are scheduled as if due ``slo_ms`` after
        submit, and it doubles as the tenant's latency *objective*: the SLO
        monitor tracks the fraction of requests finishing under it against
        ``slo_target`` (default MXNET_SLO_TARGET) with burn-rate alerting;
        ``breaker`` overrides the tenant's circuit breaker; ``tier`` is the
        tenant's brownout criticality ("gold" / "silver" / "bulk") — under
        sustained SLO burn the brownout ladder refuses bulk tenants first,
        then silver; gold is never refused (default gold, so existing
        registrations are untouchable by the ladder)."""
        if tier not in _tailguard.TIER_RANKS:
            raise MXNetError(
                f"unknown tenant tier {tier!r}; expected one of "
                f"{sorted(_tailguard.TIER_RANKS)}")
        with self._cond:
            if endpoint.name in self._router:
                raise MXNetError(f"endpoint {endpoint.name!r} already registered")
            q = EndpointQueue(
                endpoint,
                int(max_queue) if max_queue is not None
                else self._max_queue_rows,
                self._batch_timeout_us)
            if breaker is None:
                breaker = self._shared_breaker if self._shared_breaker \
                    is not None else CircuitBreaker(
                        scope=f"serving:{endpoint.name}")
            self._router.add(Tenant(
                endpoint.name, endpoint, q, breaker,
                slo_us=int(slo_ms * 1000) if slo_ms is not None else None,
                slo_target=slo_target, tier=tier))
        if slo_ms is not None:
            _SLO.register(endpoint.name, threshold_us=slo_ms * 1000.0,
                          target=slo_target, breaker=breaker)
        if warmup:
            endpoint.warmup()
        return endpoint

    def register_generator(self, engine, warmup: bool = True,
                           tenants: Optional[Dict[str, float]] = None,
                           default_slo_ms: Optional[float] = None):
        """Attach a generative :class:`~.generate.DecodeEndpoint` behind its
        own continuous-batching DecodeScheduler (the decode loop owns its
        device work — it does not ride the request-batching worker).

        ``tenants`` maps tenant name -> inter-token SLO in ms/token (a
        ``default`` tenant always exists). With ``warmup`` every prefill and
        decode bucket compiles now and the step-cost EWMAs are seeded, so no
        sequence pays first-compile latency. Starts with the server (or
        immediately if the server is running); returns the scheduler."""
        from .generate import DecodeScheduler
        with self._cond:
            if engine.name in self._generators:
                raise MXNetError(
                    f"generator {engine.name!r} already registered")
        sched = DecodeScheduler(engine, default_slo_ms=default_slo_ms)
        for tname, slo_ms in (tenants or {}).items():
            sched.add_tenant(tname, slo_ms)
        if warmup:
            engine.warmup()
        with self._cond:
            self._generators[engine.name] = sched
            running = self._state == _RUNNING
        if running:
            sched.start()
        return sched

    def generate(self, name: str, prompt,
                 max_new_tokens: Optional[int] = None,
                 tenant: str = "default", eos_id: Optional[int] = None,
                 on_token=None):
        """Stream tokens from a registered generator: returns the
        :class:`~.generate.TokenStream` for one queued sequence."""
        with self._cond:
            sched = self._generators.get(name)
        if sched is None:
            raise MXNetError(f"unknown generator {name!r}; registered: "
                             f"{sorted(self._generators)}")
        return sched.submit(prompt, max_new_tokens=max_new_tokens,
                            tenant=tenant, eos_id=eos_id, on_token=on_token)

    def endpoints(self):
        with self._cond:
            return self._router.names()

    def breaker_for(self, name: str) -> CircuitBreaker:
        """The named tenant's circuit breaker (per-tenant shedding state)."""
        with self._cond:
            if name not in self._router:
                raise MXNetError(f"unknown endpoint {name!r}; registered: "
                                 f"{self._router.names()}")
            return self._router.get(name).breaker

    # ------------------------------------------------------------------
    # zero-downtime weight hot-swap (routed through the worker)
    # ------------------------------------------------------------------
    def hot_swap(self, name: str, source, timeout: Optional[float] = None
                 ) -> dict:
        """Swap the named endpoint's weights to ``source`` (a checkpoint
        directory or state tree) WITHOUT dropping a request.

        The heavy host work happens here on the caller's thread: the
        checkpoint is checksum-verified, shape-checked against the serving
        model, and staged into fresh device buffers (the in-flight batch
        keeps reading the old ones). The validation probe + cutover then
        ride the worker's command path and run *between* batches: every
        batch executes against either the complete old weights or the
        complete new ones, never a mixture, and the queue keeps flowing —
        the swap costs one probe step, not a drain.

        Validation failure (probe outputs differ from the ones recorded
        with the checkpoint, or are non-finite) rolls back: the old weights
        keep serving and HotSwapError is raised here. A corrupt checkpoint
        is refused before anything is staged. Blocks for the swap outcome
        (bounded by ``timeout`` seconds; None = wait)."""
        with self._cond:
            if name not in self._router:
                raise MXNetError(f"unknown endpoint {name!r}; registered: "
                                 f"{self._router.names()}")
            tenant = self._router.get(name)
        # verify + shape-check + stage on the caller's thread (host work
        # plus device_put — never a compiled executable)
        host_params, probe, label = tenant.endpoint.load_swap_source(source)
        req = _SwapRequest(tenant, host_params, probe, label)
        with self._cond:
            if self._state != _RUNNING:
                raise ServerClosedError(
                    f"server is {self._state}; hot_swap needs a running "
                    "worker (use endpoint.hot_swap() on a stopped one)")
            self._swaps.append(req)
            self._cond.notify_all()
        return req.future.result(timeout=timeout)

    def _apply_swap(self, req: _SwapRequest):
        """Worker-thread half of a routed hot-swap (between batches)."""
        ep = req.tenant.endpoint
        try:
            staged = ep.stage_weights(req.host_params)
            report = ep.validate_and_commit(staged, req.probe)
            report["source"] = req.label
            _telemetry.event("hot_swap", endpoint=ep.name, ok=True,
                             source=str(req.label),
                             weights_epoch=ep.weights_epoch)
            resolve(req.future, report)
        except Exception as e:
            exc = e if isinstance(e, HotSwapError) else HotSwapError(
                f"hot swap of {ep.name!r} failed validation: {e}")
            _telemetry.event("hot_swap", endpoint=ep.name, ok=False,
                             source=str(req.label), error=str(e)[:200])
            fail(req.future, exc)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "InferenceServer":
        with self._cond:
            if self._state != _STOPPED:
                raise MXNetError(f"server is {self._state}")
            for t in (self._thread, self._prep_thread):
                if t is not None and t.is_alive():
                    raise MXNetError(
                        "a previous worker is still wedged in a device call "
                        "(abandoned drain); this server cannot be restarted")
            self._state = _RUNNING
            self._prepared.clear()
            self._spawn_threads()
            gens = list(self._generators.values())
        for g in gens:
            g.start()
        _debug.attach(self)     # /healthz + /statusz see every live server
        return self

    def _spawn_threads(self):  # mxlint: disable=CONC200
        """Start a fresh worker (+prep) generation (caller holds the lock):
        used by start() and by failover(), which bumps the epoch first so
        any surviving stale thread exits at its next loop turn."""
        epoch = self._epoch
        self._prep_done = not self._pipeline
        self._inflight = None
        self._preparing = None
        self._thread = threading.Thread(
            target=self._loop_exec if self._pipeline
            else self._loop_serial, args=(epoch,),
            name=f"mxtpu-serving-worker-e{epoch}", daemon=True)
        if self._pipeline:
            self._prep_thread = threading.Thread(
                target=self._loop_prep, args=(epoch,),
                name=f"mxtpu-serving-prep-e{epoch}", daemon=True)
            self._prep_thread.start()
        else:
            self._prep_thread = None
        self._thread.start()

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop serving. ``drain=True`` (default) processes every admitted
        request before returning, but never waits longer than ``timeout``
        seconds (default ``drain_timeout_s``): past it the remaining requests
        are abandoned and counted in ``mxtpu_drain_abandoned_total`` —
        queued-never-batched ones fail with ServerClosedError, requests
        already inside a prepared or in-flight batch with
        RequestTimeoutError (their latency budget died with the wedged
        worker) — so neither a wedged endpoint queue nor a hung device call
        can hang shutdown or leave a client waiting forever. ``drain=False``
        fails everything immediately."""
        timeout = self._drain_timeout_s if timeout is None else float(timeout)
        with self._cond:
            gens = list(self._generators.values())
        for g in gens:        # decode loops drain independently of the
            g.stop(drain=drain, timeout=timeout)   # request-batching worker
        with self._cond:
            if self._state == _STOPPED and self._thread is None and \
                    self._prep_thread is None:
                return
            # snapshot the thread handles under the lock: a concurrent stop()
            # (or a start() after abandon) must never see half-cleared
            # handles, so all joining below works on the locals
            worker, prep = self._thread, self._prep_thread
            if drain:
                self._state = _DRAINING
            else:
                self._state = _STOPPED
                exc = ServerClosedError("server stopped without drain")
                self._router.fail_all(exc)
                self._fail_prepared(exc)
                self._fail_swaps(ServerClosedError(
                    "server stopped without drain"))
            self._cond.notify_all()
        deadline = time.monotonic() + timeout
        if drain and (prep is not None or worker is not None):
            # the span is the goodput ledger's drain bucket: wall time spent
            # flushing admitted work during scale-down / shutdown
            with _telemetry.span("serving.drain",
                                 timeout_s=round(timeout, 3)):
                for t in (prep, worker):
                    if t is not None:
                        t.join(max(deadline - time.monotonic(), 0.0))
        else:
            for t in (prep, worker):
                if t is not None:
                    t.join(max(deadline - time.monotonic(), 0.0))
        if any(t is not None and t.is_alive() for t in (prep, worker)):
            # drain wedged (hung device step / endpoint queue): abandon.
            # The daemon threads may eventually finish their in-flight call;
            # they will find the state _STOPPED and exit, and resolve() on
            # already-failed futures is a no-op.
            with self._cond:
                self._state = _STOPPED
                abandoned = self._router.fail_all(ServerClosedError(
                    f"drain abandoned after {timeout:.1f}s (worker wedged)"))
                timed_out = RequestTimeoutError(
                    f"request abandoned inside a batch after the drain "
                    f"timeout ({timeout:.1f}s) with the worker wedged")
                abandoned += self._fail_prepared(timed_out)
                abandoned += self._fail_in_stage(timed_out)
                self._fail_swaps(ServerClosedError(
                    "drain abandoned (worker wedged)"))
                self._cond.notify_all()
            if abandoned:
                _DRAIN_ABANDONED.inc(abandoned)
            for t in (prep, worker):
                if t is not None:
                    t.join(1.0)
            if any(t is not None and t.is_alive() for t in (prep, worker)):
                # keep the handles: start() must refuse to run a second
                # worker beside a wedged one
                self._watchdog.stop()
                return
        with self._cond:
            if self._thread is worker:
                self._thread = None
            if self._prep_thread is prep:
                self._prep_thread = None
        self._watchdog.stop()

    @property
    def state(self) -> str:
        return self._state

    def health(self) -> dict:
        """Operator health snapshot: server lifecycle state, the worst
        circuit-breaker state across tenants (plus each tenant's own state
        and recent transitions), per-endpoint queue depth, and watchdog
        stall count."""
        with self._cond:
            state = self._state
            tenants = self._router.tenants()
        breakers = [self._breaker]
        endpoints = {}
        for t in tenants:
            if all(t.breaker is not b for b in breakers):
                breakers.append(t.breaker)
            endpoints[t.name] = {
                "pending_requests": len(t.queue),
                "pending_rows": t.queue.pending_rows,
                "circuit": t.breaker.state(),
                "slo_ms": t.slo_us / 1000.0 if t.slo_us else None,
                "slo_target": t.slo_target,
                "weights_epoch": t.endpoint.weights_epoch,
                # predicted-vs-measured step pricing, live: measured EWMA,
                # cost-model prior and blend progress per bucket
                "step_cost": t.endpoint.step_cost.snapshot_detail(),
            }
        worst = max((b.state() for b in breakers),
                    key=lambda s: _CIRCUIT_SEVERITY[s])
        with self._cond:
            generators = {n: g.snapshot()
                          for n, g in self._generators.items()}
        return {"state": state,
                "circuit": worst,
                "breaker": self._breaker.snapshot(),
                "tenants": {t.name: t.breaker.snapshot() for t in tenants},
                "endpoints": endpoints,
                "generators": generators,
                "prep_overlap_ratio": self._overlap.ratio(),
                "watchdog_stalls": self._watchdog.stalls,
                "worker_epoch": self._epoch,
                "failovers": self.failovers}

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False

    # ------------------------------------------------------------------
    # client surface
    # ------------------------------------------------------------------
    def submit(self, name: str, inputs, deadline_ms: Optional[float] = None,
               deadline=None) -> Future:
        """Enqueue a request; returns a Future resolving to the endpoint's
        output (an NDArray, or a tuple for multi-output models). A single
        example (no batch axis) resolves without a batch axis; a batch of n
        rows resolves to n-row outputs.

        ``deadline`` is a propagated :class:`~.tailguard.Deadline` (minted
        once at ingress); when set it overrides ``deadline_ms`` — the
        request carries the SAME end-to-end budget through the queue instead
        of re-deriving a fresh one here, and an already-spent budget raises
        DeadlineExceeded before admission.

        Raises ServerOverloadError when the tenant's bounded queue is full,
        its circuit breaker is shedding load (OPEN: everything; HALF_OPEN:
        beyond the probe budget; DEGRADED: beyond half the queue bound), or
        the brownout ladder is refusing this tenant's tier, and
        ServerClosedError when the server is not accepting work."""
        if deadline is not None:
            deadline.check("ingress")
        with self._cond:
            if name not in self._router:
                raise MXNetError(f"unknown endpoint {name!r}; registered: "
                                 f"{self._router.names()}")
            tenant = self._router.get(name)
        q = tenant.queue
        if _tailguard.BROWNOUT.shed_tier(tenant.tier):
            q.endpoint.stats.bump("rejected")
            q.endpoint.stats.record_shed("brownout")
            raise ServerOverloadError(
                f"endpoint {name!r} (tier {tenant.tier!r}) shed by brownout "
                f"level {_tailguard.BROWNOUT.level}: the fleet is burning "
                "its SLO budget; retry with backoff")
        if not tenant.breaker.allow():
            q.endpoint.stats.bump("rejected")
            q.endpoint.stats.record_shed(f"circuit_{tenant.breaker.state()}")
            raise ServerOverloadError(
                f"endpoint {name!r} circuit {tenant.breaker.state()}: "
                "shedding load until the device recovers; retry with backoff")
        req = self._make_request(q.endpoint, inputs, deadline_ms, deadline)
        with self._cond:
            if self._state != _RUNNING:
                raise ServerClosedError(f"server is {self._state}")
            # graceful degradation: while DEGRADED admit only up to half the
            # tenant's queue bound, so a struggling device sees less queued
            # latency — per-tenant: other tenants keep their full bound
            if tenant.breaker.state() == DEGRADED and \
                    q.pending_rows + req.rows > q.max_queue_rows // 2:
                q.endpoint.stats.bump("rejected")
                q.endpoint.stats.record_shed("degraded")
                raise ServerOverloadError(
                    f"endpoint {name!r} degraded: admission tightened to "
                    f"{q.max_queue_rows // 2} rows; retry with backoff")
            if not q.offer(req):
                q.endpoint.stats.record_shed("queue_full")
                raise ServerOverloadError(
                    f"endpoint {name!r} queue full "
                    f"({q.pending_rows} rows >= {q.max_queue_rows}); retry with backoff")
            self._cond.notify_all()
        return req.future

    def predict(self, name: str, inputs, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None):
        """Blocking convenience wrapper over submit()."""
        return self.submit(name, inputs, deadline_ms).result(timeout=timeout)

    def _make_request(self, ep: ModelEndpoint, inputs,
                      deadline_ms: Optional[float],
                      deadline=None) -> Request:
        """Validate + host-normalize one request OUTSIDE the lock: every
        input becomes a contiguous numpy batch in the endpoint dtype."""
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)
        if len(inputs) != len(ep.input_shapes):
            raise MXNetError(f"endpoint {ep.name!r} takes "
                             f"{len(ep.input_shapes)} inputs, got {len(inputs)}")
        host = []
        rows = None
        squeeze = None
        for i, (x, shape, npdt) in enumerate(
                zip(inputs, ep.input_shapes, ep.np_dtypes)):
            a = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
            if a.shape == shape:
                a = a[None]
                sq = True
            elif a.shape[1:] == shape:
                sq = False
            else:
                raise MXNetError(
                    f"endpoint {ep.name!r} input {i}: expected per-example "
                    f"shape {shape} (optionally batched), got {a.shape}")
            if rows is None:
                rows, squeeze = a.shape[0], sq
            elif a.shape[0] != rows:
                raise MXNetError(f"endpoint {ep.name!r}: inputs disagree on "
                                 f"batch rows ({rows} vs {a.shape[0]})")
            if a.dtype != npdt:
                a = a.astype(npdt)
            host.append(onp.ascontiguousarray(a))
        if rows > ep.max_batch_size:
            raise MXNetError(
                f"request of {rows} rows exceeds endpoint {ep.name!r} "
                f"max_batch_size={ep.max_batch_size}; split the request")
        return Request(tuple(host), rows, squeeze, deadline_ms,
                       deadline=deadline)

    # ------------------------------------------------------------------
    # shared scheduling helpers (caller holds the condition lock)
    # ------------------------------------------------------------------
    def _next_assembly(self, epoch: int, take_swaps: bool = False):  # mxlint: disable=CONC200
        """Block (holding the lock) until the Router yields a tenant whose
        batch should assemble now, a drain can finish, or the server stops.
        Returns (tenant, requests); requests may be [] when all ready work
        had expired, None on exit (stopped, or drain complete), and
        _SUPERSEDED when a failover replaced this thread's generation.
        ``take_swaps`` (the serial worker, which is its own dispatcher)
        additionally returns pending _SwapRequests — ahead of batch
        assembly, so a swap lands at the next batch boundary."""
        while True:
            if self._state == _STOPPED:
                return None
            if self._epoch != epoch:
                return _SUPERSEDED
            if take_swaps and self._swaps:
                return self._swaps.pop(0)
            now = _now_us()
            flush = self._state == _DRAINING
            if len(self._prepared) >= self._PIPELINE_DEPTH:
                # handoff slot occupied: nothing to do until the worker pops
                # it (notify_all) — do NOT wake on batch deadlines, assembly
                # cannot proceed anyway (bounded wait in case the worker
                # dies mid-batch; stop() notifies too)
                self._cond.wait(timeout=0.25)
                continue
            tenant = self._router.select(now, flush)
            if tenant is not None:
                return tenant, tenant.queue.take_batch(now)
            if flush:
                # slot free + nothing ready under flush => queues are empty
                return None
            wakeup = self._router.next_wakeup_us()
            timeout = (max(wakeup - now, 0) / 1e6) if wakeup is not None \
                else None
            self._cond.wait(timeout=timeout)

    def _fail_prepared(self, exc: Exception) -> int:  # mxlint: disable=CONC200
        """Fail every prepared-but-unexecuted batch (caller holds the lock);
        returns the number of requests failed."""
        n = 0
        while self._prepared:
            pb = self._prepared.pop(0)
            for r in pb.requests:
                pb.tenant.endpoint.stats.bump("cancelled")
                fail(r.future, exc)
                n += 1
        return n

    def _fail_in_stage(self, exc: Exception) -> int:  # mxlint: disable=CONC200
        """Fail the requests held by the in-flight device step and the prep
        stage (caller holds the lock). The wedged daemon thread may
        eventually finish and try to resolve them; resolve() on a settled
        future is a no-op, the client already got this error."""
        n = 0
        for holder in (self._inflight, self._preparing):
            if holder is None:
                continue
            tenant, requests = (holder.tenant, holder.requests) \
                if isinstance(holder, PreparedBatch) else holder
            for r in requests:
                tenant.endpoint.stats.bump("cancelled")
                fail(r.future, exc)
                n += 1
        self._inflight = None
        self._preparing = None
        return n

    def _fail_swaps(self, exc: Exception):  # mxlint: disable=CONC200
        """Fail pending hot-swap commands (caller holds the lock)."""
        while self._swaps:
            fail(self._swaps.pop(0).future, exc)

    def _on_stall(self, name: str, dt: float):
        """Watchdog hook: a stalled device step degrades the *stalled
        tenant's* circuit (falling back to the server breaker when the watch
        name is not a tenant's), then notifies registered stall listeners
        (the PoolSupervisor confirms the wedge and fails the worker over)."""
        ep_name = name.partition("[")[2].rstrip("]")
        tenant = self._router.find(ep_name)
        br = tenant.breaker if tenant is not None else self._breaker
        br.force_degraded(f"stall {name} {dt:.1f}s")
        for cb in list(self._stall_listeners):
            try:
                cb(name, dt)
            except Exception:
                pass            # a broken listener must not kill the monitor

    def add_stall_listener(self, cb):
        """Subscribe to watchdog stall events: ``cb(watch_name, elapsed_s)``
        runs on the watchdog monitor thread and must not block."""
        self._stall_listeners.append(cb)

    def remove_stall_listener(self, cb):
        try:
            self._stall_listeners.remove(cb)
        except ValueError:
            pass

    # ------------------------------------------------------------------
    # worker failover (driven by the PoolSupervisor)
    # ------------------------------------------------------------------
    def failover(self, reason: str, tenant_name: Optional[str] = None
                 ) -> Optional[dict]:
        """Replace a dead or wedged worker generation without restarting the
        server: requeue every batch the old generation held (prepared
        batches, the prep stage's in-hand assembly, and the in-flight device
        batch) at the FRONT of their tenant queues — original order and
        deadlines preserved, so expired requests still fail with
        RequestTimeoutError at re-assembly instead of silently re-running —
        trip only the affected tenant's circuit breaker, bump the thread
        epoch (a surviving zombie exits at its next loop turn; its late
        future resolutions are no-ops), and start fresh worker/prep threads.

        Returns a report dict, or None when the server was not running (a
        racing stop() wins). Other tenants' queues, breakers and SLOs are
        untouched — one tenant's wedged batch is that tenant's problem."""
        with self._cond:
            if self._state != _RUNNING:
                return None
            self._epoch += 1
            requeued = 0
            # newest-assembled first, so the oldest work ends up at the head
            for pb in reversed(self._prepared):
                pb.tenant.queue.requeue_front(pb.requests)
                requeued += len(pb.requests)
            self._prepared.clear()
            if self._preparing is not None:
                tenant, batch = self._preparing
                tenant.queue.requeue_front(batch)
                requeued += len(batch)
                self._preparing = None
            inflight = self._inflight
            if inflight is not None:
                inflight.tenant.queue.requeue_front(inflight.requests)
                requeued += len(inflight.requests)
                if tenant_name is None:
                    tenant_name = inflight.tenant.name
                self._inflight = None
            affected = self._router.find(tenant_name) \
                if tenant_name is not None else None
            if affected is not None:
                affected.breaker.record_failure()
            self.failovers += 1
            epoch = self._epoch
            self._spawn_threads()
            self._cond.notify_all()
        _FAILOVERS.labels(reason).inc()
        if requeued:
            _FAILOVER_REQUEUED.inc(requeued)
        report = {"reason": reason, "epoch": epoch, "requeued": requeued,
                  "tenant": tenant_name}
        _flight.trigger("failover", **report)
        return report

    # ------------------------------------------------------------------
    # serial worker (pipeline=False): assemble -> prepare -> execute inline
    # ------------------------------------------------------------------
    def _loop_serial(self, epoch: int):
        while True:
            with self._cond:
                item = self._next_assembly(epoch, take_swaps=True)
                if item is _SUPERSEDED:
                    return                 # a failover replaced this worker
                if item is None:
                    self._state = _STOPPED
                    self._fail_swaps(ServerClosedError("server stopped"))
                    self._cond.notify_all()
                    return
            if isinstance(item, _SwapRequest):
                self._apply_swap(item)     # batch boundary by construction
                continue
            tenant, batch = item
            if not batch:
                continue
            with self._cond:
                self._preparing = (tenant, batch)
            # no finally: if a thread-killing BaseException escapes
            # _prepare, the _preparing record survives for failover to
            # requeue; ordinary prep failures return None (futures failed)
            pb = self._prepare(tenant, batch, 0)
            with self._cond:
                if self._preparing is not None and \
                        self._preparing[1] is batch:
                    self._preparing = None
            if pb is not None:
                self._execute(pb)

    # ------------------------------------------------------------------
    # pipelined prep stage: assemble + device_put batch k+1 during step k
    # ------------------------------------------------------------------
    def _loop_prep(self, epoch: int):
        parity = 0
        while True:
            with self._cond:
                item = self._next_assembly(epoch)
                if item is _SUPERSEDED:
                    return                 # a failover replaced this stage
                if item is None:
                    self._prep_done = True
                    self._cond.notify_all()
                    return
            tenant, batch = item
            if not batch:
                continue
            with self._cond:
                self._preparing = (tenant, batch)
            # no finally: see _loop_serial — a killed prep thread leaves the
            # _preparing record for failover to requeue
            pb = self._prepare(tenant, batch, parity)
            with self._cond:
                if self._preparing is not None and \
                        self._preparing[1] is batch:
                    self._preparing = None
            if pb is None:
                continue                  # prep failed; futures already failed
            # cycle over depth+1 parities: with d batches queued ahead plus
            # one in flight, the slot being rewritten is always retired
            parity = (parity + 1) % (self._PIPELINE_DEPTH + 1)
            with self._cond:
                if self._epoch != epoch:
                    # superseded mid-prepare: hand the rows back to their
                    # queue — the replacement generation re-assembles them
                    tenant.queue.requeue_front(pb.requests)
                    self._cond.notify_all()
                    return
                if self._state == _STOPPED:
                    exc = ServerClosedError("server stopped")
                    for r in pb.requests:
                        tenant.endpoint.stats.bump("cancelled")
                        fail(r.future, exc)
                    continue
                self._prepared.append(pb)
                self._cond.notify_all()

    def _prepare(self, tenant: Tenant, batch, parity: int
                 ) -> Optional[PreparedBatch]:
        """Run the host prep for one assembled batch (lock NOT held); on
        failure fail the batch's futures against the tenant's breaker."""
        try:
            return prepare_batch(tenant, batch, parity, self._overlap,
                                 self._retry)
        except Exception as e:
            tenant.breaker.record_failure()
            for r in batch:
                fail(r.future, e)
            return None

    # ------------------------------------------------------------------
    # pipelined worker: execute prepared batches (the only executable caller)
    # ------------------------------------------------------------------
    def _loop_exec(self, epoch: int):
        while True:
            with self._cond:
                item = self._next_prepared(epoch)
                if item is _SUPERSEDED:
                    return                 # a failover replaced this worker
                if item is None:
                    self._state = _STOPPED
                    self._fail_swaps(ServerClosedError("server stopped"))
                    self._cond.notify_all()
                    return
            if isinstance(item, _SwapRequest):
                self._apply_swap(item)     # between batches: the boundary
                continue
            self._execute(item)

    def _next_prepared(self, epoch: int):  # mxlint: disable=CONC200
        """Block (holding the lock) for the next prepared batch or hot-swap
        command (commands first: they cut over at the batch boundary);
        None on stop or a fully-flushed drain, _SUPERSEDED on failover."""
        while True:
            if self._state == _STOPPED:
                return None
            if self._epoch != epoch:
                return _SUPERSEDED
            if self._swaps:
                return self._swaps.pop(0)
            if self._prepared:
                pb = self._prepared.pop(0)
                self._cond.notify_all()    # the handoff slot is free again
                return pb
            if self._state == _DRAINING and self._prep_done:
                return None
            self._cond.wait()

    # ------------------------------------------------------------------
    # device dispatch (worker thread only)
    # ------------------------------------------------------------------
    def _execute(self, pb: PreparedBatch):
        from .. import telemetry
        ep = pb.tenant.endpoint
        from ..ops.registry import _profiler_running
        profiling = _profiler_running()
        t0 = _now_us()

        def run_step():
            _faults.check("serving_dispatch")
            step = lambda: ep.execute(pb.inputs, pb.bucket, pb.rows,
                                      padded_host=pb.padded_host)
            if profiling:
                from .. import profiler
                return profiler._dispatch_profiled(
                    f"serving[{ep.name}]b{pb.rows}", step, cat="serving")
            return step()

        with self._cond:
            self._inflight = pb
        # `killed` guards the in-flight record: a thread-killing
        # BaseException (worker_kill drill, interpreter death) must leave it
        # set so failover can requeue the orphaned batch; every caught path
        # clears it below
        killed = True
        self._overlap.step_begin()
        try:
            # adopt the oldest request's trace id for the whole batch step:
            # its end-to-end trace (submit -> batch -> device) is the one
            # closest to the latency budget, and the span records how many
            # requests/rows rode along
            with telemetry.span("serving.batch",
                                trace_id=pb.requests[0].trace_id,
                                endpoint=ep.name, rows=pb.rows,
                                requests=len(pb.requests)):
                with self._watchdog.watch(f"serving[{ep.name}]"):
                    # retries must respect what clients asked for: never back
                    # off past the earliest request deadline in the batch
                    outs = self._retry.run(run_step, site="serving_dispatch",
                                           deadline_us=pb.deadline_us,
                                           budget_tier="execute")
            killed = False
        except Exception as e:  # retries exhausted / fatal: fail the batch
            killed = False
            pb.tenant.breaker.record_failure()
            failed_at = _now_us()
            for r in pb.requests:
                fail(r.future, e)
                _flight.record_request(r.trace_id, ep.name,
                                       failed_at - r.enqueue_us,
                                       rows=r.rows, ok=False,
                                       error=type(e).__name__)
                _SLO.record(ep.name, failed_at - r.enqueue_us, ok=False)
            return
        finally:
            self._overlap.step_end()
            if not killed:
                with self._cond:
                    # guarded: after a failover this slot belongs to the
                    # replacement worker's batch, not to this zombie
                    if self._inflight is pb:
                        self._inflight = None
        pb.tenant.breaker.record_success()
        # one executed batch = one unit of real work funding the execute
        # tier's retry budget
        _tailguard.retry_deposit("execute")
        ep.stats.record_step(_now_us() - t0)
        off = 0
        done = _now_us()
        for r in pb.requests:
            sliced = tuple(
                NDArray(o[off] if r.squeeze else o[off:off + r.rows], ctx=ep.ctx)
                for o in outs)
            resolve(r.future, sliced[0] if ep.num_outputs == 1 else sliced)
            ep.stats.record_latency(done - r.enqueue_us)
            ep.stats.bump("completed")
            _flight.record_request(r.trace_id, ep.name, done - r.enqueue_us,
                                   rows=r.rows)
            _SLO.record(ep.name, done - r.enqueue_us)
            if profiling:
                from .. import profiler
                profiler.record_duration(f"serving[{ep.name}].request",
                                         r.enqueue_us, done - r.enqueue_us,
                                         cat="serving")
            off += r.rows
