"""PoolSupervisor: declare a dead/wedged serving worker and fail it over.

The InferenceServer's recovery layers handle failures that *return*: a
raising device step retries, then fails its batch and feeds the tenant's
circuit breaker. Two failure shapes escape all of that:

  - the worker (or prep) **thread dies** — an uncatchable error tears it
    down mid-batch; the server object looks healthy but nothing dispatches
    ever again, queues grow until every client times out;
  - the worker **wedges** — a device call hangs forever; the Watchdog flags
    the stall and degrades the tenant's breaker, but the batch's requests
    and every queued request behind them are stuck regardless.

The supervisor is the recovery layer for both. It watches the server's
worker/prep threads — liveness by polling ``Thread.is_alive`` every
``MXNET_SUPERVISOR_POLL_S``, wedges via the server's existing Watchdog
(stall events subscribed through ``add_stall_listener``, then confirmed
against the still-in-flight batch so a slow-but-finishing step is never
killed) — and on either verdict drives ``InferenceServer.failover()``:

  - every batch the dead generation held (prepared, mid-prep, in-flight)
    is requeued at the FRONT of its tenant queue with original order and
    deadlines — expired requests fail with RequestTimeoutError at
    re-assembly, live ones simply run on the replacement worker;
  - only the affected tenant's circuit breaker is tripped — the other
    tenants' admission, SLOs and stats never notice;
  - a fresh worker/prep generation starts immediately (the thread epoch
    fences out zombies), counted in ``mxtpu_serving_failovers_total``.

Deterministic drill: the ``worker_kill`` fault kind raises a
BaseException-derived error that sails past retry and batch-failure
handling and kills the thread itself — exactly the failure this module
exists for::

    with PoolSupervisor(server):
        with faults.inject("worker_kill", site="serving_dispatch", times=1):
            ...                      # supervisor restarts the worker
"""
from __future__ import annotations

import threading
from typing import Optional

from ..base import MXNetError
from .. import config as _config
from .. import telemetry as _telemetry
from .server import InferenceServer, _RUNNING

__all__ = ["PoolSupervisor"]


class PoolSupervisor:
    """Liveness/wedge monitor + failover driver for one InferenceServer.

    Parameters
    ----------
    server : InferenceServer
        The server whose worker/prep threads are supervised.
    poll_s : float, optional
        Liveness poll interval (default ``MXNET_SUPERVISOR_POLL_S``).
    """

    def __init__(self, server: InferenceServer, poll_s: Optional[float] = None):
        self._server = server
        self.poll_s = float(poll_s if poll_s is not None
                            else _config.get("MXNET_SUPERVISOR_POLL_S"))
        if self.poll_s <= 0:
            raise MXNetError("poll_s must be > 0")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._stalled = None        # in-flight batch flagged by the watchdog
        self.reports: list = []     # failover report dicts, newest last

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "PoolSupervisor":
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._server.add_stall_listener(self._on_stall)
            self._thread = threading.Thread(
                target=self._run, name="mxtpu-pool-supervisor", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        with self._lock:
            self._stop.set()
            t, self._thread = self._thread, None
        self._server.remove_stall_listener(self._on_stall)
        if t is not None:
            t.join(timeout=self.poll_s * 4 + 1.0)

    def __enter__(self) -> "PoolSupervisor":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------------
    # evidence intake
    # ------------------------------------------------------------------
    def _on_stall(self, name: str, dt: float):
        """Watchdog listener (monitor thread; must not block): remember
        which in-flight batch stalled — the poll loop confirms it is STILL
        in flight before declaring the worker wedged, so a step that merely
        ran long but finished is never failed over."""
        srv = self._server
        ep_name = name.partition("[")[2].rstrip("]")
        with srv._cond:
            pb = srv._inflight
        if pb is not None and pb.tenant.name == ep_name:
            with self._lock:
                self._stalled = pb

    # ------------------------------------------------------------------
    # the verdict loop
    # ------------------------------------------------------------------
    def _run(self):
        while not self._stop.wait(self.poll_s):
            try:
                self._check()
            except Exception:
                pass        # supervision must outlive any single bad poll

    def _check(self):
        srv = self._server
        with srv._cond:
            if srv._state != _RUNNING:
                with self._lock:
                    self._stalled = None
                return
            worker, prep = srv._thread, srv._prep_thread
            inflight, preparing = srv._inflight, srv._preparing
            pipeline = srv._pipeline
        if worker is None:
            return
        with self._lock:
            stalled = self._stalled
        report = None
        if not worker.is_alive():
            name = inflight.tenant.name if inflight is not None else \
                (preparing[0].name if preparing is not None else None)
            report = srv.failover("worker_dead", tenant_name=name)
        elif pipeline and prep is not None and not prep.is_alive():
            name = preparing[0].name if preparing is not None else None
            report = srv.failover("prep_dead", tenant_name=name)
        elif stalled is not None:
            if stalled is inflight:
                report = srv.failover("worker_wedged",
                                      tenant_name=stalled.tenant.name)
            else:
                with self._lock:    # the stalled step finished after all
                    if self._stalled is stalled:
                        self._stalled = None
        if report is not None:
            with self._lock:
                self._stalled = None
                self.reports.append(report)
            _telemetry.event("supervisor_failover", **report)

    @property
    def failovers(self) -> int:
        with self._lock:
            return len(self.reports)
