"""Serving-layer error taxonomy.

Admission control needs errors a client can branch on: overload is retryable
with backoff, a missed deadline is not (the work was dropped on purpose), and
a closed server means the process is going away. All derive from MXNetError
so existing blanket handlers keep working.

Deadline taxonomy: every "the latency budget ran out" failure — queue expiry,
a backoff that cannot fit, a decode token past the budget — derives from
:class:`DeadlineExceeded`, so one ``except DeadlineExceeded`` catches the
whole family while ``RequestTimeoutError`` keeps its historical meaning
(expired while queued). Clients can therefore distinguish "deadline elapsed"
(not worth retrying: the budget is gone) from "server closed" (retryable on
another replica/host).
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["ServingError", "ServerOverloadError", "DeadlineExceeded",
           "RequestTimeoutError", "ServerClosedError", "HotSwapError",
           "KVPoolExhausted"]


class ServingError(MXNetError):
    """Base class for serving-layer failures."""


class ServerOverloadError(ServingError):
    """The bounded request queue is full; the request was rejected at
    admission (never enqueued). Retryable: back off and resubmit."""


class DeadlineExceeded(ServingError):
    """The request's end-to-end deadline budget ran out at some tier —
    ingress, queue, batch assembly, a retry backoff that could not fit, or
    decode mid-generation. NOT retryable: the client's budget is spent;
    retrying cannot make the answer arrive in time."""


class RequestTimeoutError(DeadlineExceeded):
    """The request's deadline expired while it waited in the queue; it was
    dropped before reaching the device (no compute was wasted on it)."""


class ServerClosedError(ServingError):
    """The server is stopped or draining and no longer admits new work."""


class HotSwapError(ServingError):
    """A weight hot-swap was refused (corrupt/mismatched checkpoint) or its
    probe validation failed. The endpoint rolled back and keeps serving the
    previous weights — the swap never became client-visible."""


class KVPoolExhausted(ServingError):
    """The paged KV cache has no free pages for a new sequence's reservation.
    Retryable by waiting: running sequences release pages as they finish, so
    the decode scheduler keeps the sequence queued instead of failing it.
    The message carries the ``RESOURCE_EXHAUSTED`` marker a real device OOM
    carries, so message-based retry classifiers agree."""
