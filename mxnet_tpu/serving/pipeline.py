"""Double-buffered host pipeline: batch k+1 prep overlaps device step k.

The serial dispatch loop pays ``concat + pad + device_put`` on the critical
path of every batch: the device idles while the host prepares, and the host
idles while the device executes. This module is the overlap half of the
rebuilt dispatch path (the host/device overlap discipline of TensorFlow's
dataflow executor, PAPERS.md): a dedicated *prep stage* assembles the next
batch — writing request rows straight into preallocated per-(bucket, parity)
staging buffers (``MXNET_SERVING_ZEROCOPY``; concat+pad is the fallback) and
``device_put``-ing into the input-buffer set for the next *parity* — while
the worker thread executes the current one. Host time disappears from the
critical path once steady state is reached.

Depth: ``MXNET_SERVING_PIPELINE_DEPTH`` (or ``InferenceServer(pipeline_depth=)``)
lets prep run d batches ahead; parities cycle over d+1 slots so the slot
being written is never one an in-flight or queued batch still references.

Parity (the double buffer): prepared batches alternate between two
input-buffer sets (parity 0 / parity 1, tracked per endpoint). Because the
handoff queue holds at most one prepared batch while one executes, the set
being written by prep is never the set the in-flight executable is reading —
the same two-slot discipline a hardware DMA double buffer uses. On
donation-capable backends the executable consumes (donates) its input set,
so each parity slot's memory is recycled by XLA rather than re-allocated.

:class:`OverlapTracker` measures the win honestly: it integrates device-busy
time and charges each prep window only the portion that truly overlapped a
device step. The exported gauge ``mxtpu_serving_prep_overlap_ratio`` is
cumulative overlapped-prep / total-prep (1.0 = all host prep hidden).

Single-dispatcher discipline: the prep stage touches JAX only for host→device
transfer (``device_put``); compiled executables are invoked by the worker
thread alone. Handoff happens under the server's shared condition as a
fully-built :class:`PreparedBatch` — the worker never blocks on host work.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Tuple

from .. import config as _config
from .. import telemetry as _telemetry
from ..resilience import faults as _faults
from . import bucketing
from .batcher import Request, concat_inputs
from .stats import set_prep_overlap_ratio

__all__ = ["PreparedBatch", "OverlapTracker", "prepare_batch"]


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class PreparedBatch:
    """One fully-prepared dispatch unit: device input buffers plus the
    requests whose rows they carry. Built by the prep stage, executed by the
    worker. ``padded_host`` is retained so a retry after a failed step can
    rebuild consumed (donated) device buffers without re-assembly."""

    __slots__ = ("tenant", "requests", "rows", "bucket", "inputs",
                 "padded_host", "parity", "deadline_us", "prep_us")

    def __init__(self, tenant, requests: Sequence[Request], rows: int,
                 bucket: int, inputs: Tuple, padded_host: Tuple,
                 parity: int, deadline_us: Optional[int], prep_us: float):
        self.tenant = tenant
        self.requests = list(requests)
        self.rows = rows
        self.bucket = bucket
        self.inputs = inputs
        self.padded_host = padded_host
        self.parity = parity
        self.deadline_us = deadline_us
        self.prep_us = prep_us


class OverlapTracker:
    """Cumulative prep/step overlap accounting.

    The worker brackets every device step with ``step_begin()``/
    ``step_end()``; the prep stage reports each prep window via
    ``prep_window(t0, t1)``. Overlap is computed exactly as the device-busy
    time elapsed between the two endpoints of the prep window (an integral
    over the busy indicator, not a sample), so a prep that straddles a step
    boundary is credited only for the covered part.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._busy_accum_us = 0.0       # total device-busy time ever
        self._busy_since: Optional[int] = None
        self.prep_us = 0.0
        self.overlap_us = 0.0
        self.steps = 0

    def _busy_at(self, t_us: int) -> float:  # mxlint: disable=CONC200
        # caller holds self._lock
        busy = self._busy_accum_us
        if self._busy_since is not None and t_us > self._busy_since:
            busy += t_us - self._busy_since
        return busy

    def step_begin(self):
        with self._lock:
            self._busy_since = _now_us()

    def step_end(self):
        with self._lock:
            if self._busy_since is not None:
                self._busy_accum_us += _now_us() - self._busy_since
                self._busy_since = None
            self.steps += 1

    def prep_window(self, t0_us: int, t1_us: int) -> float:
        """Record one prep window; returns the overlapped microseconds."""
        with self._lock:
            overlap = max(0.0, self._busy_at(t1_us) - self._busy_at(t0_us))
            self.prep_us += max(0, t1_us - t0_us)
            self.overlap_us += overlap
            ratio = (self.overlap_us / self.prep_us) if self.prep_us else 0.0
        set_prep_overlap_ratio(ratio)
        return overlap

    def ratio(self) -> float:
        with self._lock:
            return (self.overlap_us / self.prep_us) if self.prep_us else 0.0


def prepare_batch(tenant, requests: List[Request], parity: int,
                  tracker: OverlapTracker, retry) -> PreparedBatch:
    """The host half of one dispatch: concat request rows, pad to the shape
    bucket, transfer into the ``parity`` input-buffer set. Runs on the prep
    thread (pipelined) or inline on the worker (serial mode); either way the
    server lock is NOT held. Raises on unrecoverable prep failure — the
    caller fails the batch's futures and records the tenant breaker."""
    ep = tenant.endpoint
    rows = sum(r.rows for r in requests)
    deadlines = [r.deadline_us for r in requests if r.deadline_us is not None]
    deadline_us = min(deadlines) if deadlines else None

    def run_prep():
        _faults.check("serving_prep")
        if _config.get("MXNET_SERVING_ZEROCOPY"):
            # zero-copy assembly: write each request's rows straight into
            # the endpoint's per-(bucket, parity) staging buffers. Already
            # bucket-sized, so the pad step inside prepare() is a no-op
            # view — the only copy left on the ingest path is the
            # device_put itself. The parity discipline that protects the
            # device buffer sets protects the staging set equally: a slot
            # is rewritten only after its batch fully retires.
            bucket = bucketing.bucket_for(rows, ep.buckets)
            bufs = ep.staging_buffers(bucket, parity)
            off = 0
            for r in requests:
                for i in range(len(bufs)):
                    bufs[i][off:off + r.rows] = r.inputs[i]
                off += r.rows
            for b in bufs:
                b[rows:bucket] = 0       # stale tail rows would leak into
            host_inputs = bufs           # the padded region
        else:
            host_inputs = concat_inputs(requests, len(ep.input_shapes))
        return ep.prepare(host_inputs, rows, parity=parity)

    t0 = _now_us()
    # adopt the oldest request's trace id: the prep span joins the same
    # end-to-end trace the batch/device_step spans continue on the worker
    with _telemetry.span("serving.prep", trace_id=requests[0].trace_id,
                         endpoint=ep.name, rows=rows, parity=parity):
        inputs, bucket, padded_host = retry.run(
            run_prep, site="serving_prep", deadline_us=deadline_us)
    t1 = _now_us()
    tracker.prep_window(t0, t1)
    ep.stats.record_prep(t1 - t0)
    return PreparedBatch(tenant, requests, rows, bucket, inputs, padded_host,
                         parity, deadline_us, t1 - t0)
