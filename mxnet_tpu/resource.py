"""Resource manager (parity: include/mxnet/resource.h:43-241 ResourceRequest/
ResourceManager over src/resource.cc).

TPU-native mapping — most reference resources are subsumed:
  - kTempSpace (scratch workspace): XLA allocates fused-kernel scratch
    itself; ``Resource.get_space`` hands back a host numpy scratch buffer
    for host-side ops (the only place user code still needs one).
  - kRandom / kParallelRandom (per-device RNG streams): the threefry key
    chain in ``mxnet_tpu.random`` — ``Resource.get_random`` returns a fresh
    split key, the per-op stream discipline of the reference's
    ResourceRequest{kRandom}.
  - kCuDNNDropoutDesc: N/A (dropout is a jitted mask draw).
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["ResourceRequest", "Resource", "request"]


class ResourceRequest:
    """Request tags (resource.h:43-51)."""
    kRandom = "random"
    kTempSpace = "temp_space"
    kParallelRandom = "parallel_random"
    kCuDNNDropoutDesc = "cudnn_dropout_desc"

    def __init__(self, type_=kTempSpace):
        self.type = type_


class Resource:
    """A granted resource handle (resource.h Resource)."""

    def __init__(self, req: ResourceRequest):
        self.req = req

    def get_random(self):
        """Fresh PRNG key from the global threefry chain (kRandom)."""
        from . import random as _random
        return _random.take_key()

    def get_space(self, shape, dtype="float32"):
        """Host scratch buffer (kTempSpace). Device scratch is XLA's job —
        this exists for host-side ops (decode staging, custom op buffers)."""
        import numpy as onp
        return onp.empty(shape, dtype)

    def get_parallel_random(self, n):
        """n independent keys (kParallelRandom): one split, n streams."""
        import jax
        return jax.random.split(self.get_random(), n)


def request(req: ResourceRequest) -> Resource:
    """ResourceManager::Request analog."""
    if not isinstance(req, ResourceRequest):
        req = ResourceRequest(req)
    if req.type == ResourceRequest.kCuDNNDropoutDesc:
        raise MXNetError("cudnn_dropout_desc has no TPU analog "
                         "(dropout draws jitted masks)")
    return Resource(req)
