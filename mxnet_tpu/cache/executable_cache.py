"""Persistent executable cache: compiled XLA programs, content-addressed.

The compile ledger (PR 10) already fingerprints every lowered program —
a sha256 of canonicalized StableHLO that is stable across processes and
machines. This module turns that fingerprint into a *cache key*: compiled
executables are serialized via ``jax.experimental.serialize_executable``
and stored under ``MXNET_EXEC_CACHE_DIR`` so the next process that lowers
the same program deserializes it instead of paying XLA again. Integration
happens once, inside ``compile_ledger.lower_and_compile()`` — every AOT
compile site (serving buckets, decode prefill/step pairs, the train-step
autoformat path, the opt-in eager ledger) hits the cache transparently.

Correctness before speed:

  * the key covers everything that could make a cached executable wrong on
    this process: the StableHLO fingerprint, backend platform + device kind
    + device count, the donation layout of the lowering, the caller's
    trigger key (endpoint/bucket/mesh/dtype), and the jax / jaxlib /
    backend runtime versions. Any mismatch is simply a different key — a
    miss, never a wrong load;
  * entries are two files, payload (``ent-<key>.bin``) and manifest
    (``ent-<key>.json``), each written tmp + fsync + rename so a reader
    only ever sees a complete entry; concurrent writers race benignly
    (last atomic rename wins, both wrote identical bytes);
  * the manifest carries the payload's sha256; :func:`load` verifies it
    before unpickling, so a truncated or bit-flipped payload is detected,
    warned about, deleted, and answered with a miss — the caller falls
    back to a live compile. **Nothing in this module raises on the serving
    path**: every failure mode degrades to "compile it yourself";
  * the store is LRU byte-bounded (``MXNET_EXEC_CACHE_MAX_BYTES``):
    payload mtimes are the recency order, touched on every hit, and
    :func:`store` evicts oldest-first until the directory fits.

The ``exec_cache`` fault site lets chaos drills poison an entry on disk
(kind ``cache_poison``): the injected fault is *consumed* here and turned
into real on-disk corruption, so the genuine digest-verify path — not a
shortcut — proves the fallback.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..telemetry.metrics import REGISTRY

__all__ = ["enabled", "cache_dir", "max_bytes", "build_key", "key_digest",
           "load", "store", "stats", "entries", "clear", "reset_stats"]

log = logging.getLogger("mxnet_tpu.cache")

_HITS = REGISTRY.counter(
    "mxtpu_exec_cache_hits_total",
    "Executable-cache hits: compiles answered by deserializing a stored "
    "executable instead of running XLA.")
_MISSES = REGISTRY.counter(
    "mxtpu_exec_cache_misses_total",
    "Executable-cache misses, by reason: absent (never stored) / corrupt "
    "(payload digest mismatch — entry deleted) / key_mismatch (manifest "
    "disagrees with the requested key) / error (load machinery failed).",
    labelnames=("reason",))
_EVICTIONS = REGISTRY.counter(
    "mxtpu_exec_cache_evictions_total",
    "Entries evicted to keep the store under MXNET_EXEC_CACHE_MAX_BYTES "
    "(least-recently-used payload mtime first).")
_BYTES = REGISTRY.gauge(
    "mxtpu_exec_cache_bytes",
    "Total payload bytes currently in the on-disk executable cache "
    "(refreshed on every store/evict/load of this process).")
_DESER_S = REGISTRY.counter(
    "mxtpu_exec_cache_deserialize_seconds_total",
    "Wall seconds spent deserializing cached executables — the price of a "
    "hit (compare mxtpu_compile_wall_seconds_total, the price of a miss).")

_LOCK = threading.Lock()
# process-local stats for /compilez and tests (mirror of the counters)
_STATS = {"hits": 0, "misses": 0, "evictions": 0, "stores": 0,
          "deserialize_s": 0.0}


def _cfg(name, default):
    try:
        from .. import config
        return config.get(name, default)
    except Exception as e:      # fail-open: a broken config never blocks serving
        log.debug("config read %s failed: %s", name, e)
        return default


def cache_dir() -> str:
    """The store directory ('' = cache disabled), read live."""
    return str(_cfg("MXNET_EXEC_CACHE_DIR", "") or "")


def max_bytes() -> int:
    """LRU byte budget (0 = unbounded)."""
    try:
        return int(_cfg("MXNET_EXEC_CACHE_MAX_BYTES", 1 << 30))
    except (TypeError, ValueError):
        return 1 << 30


def enabled() -> bool:
    return bool(cache_dir())


# ---------------------------------------------------------------------------
# key construction
# ---------------------------------------------------------------------------

def _runtime_versions() -> Dict[str, str]:
    out: Dict[str, str] = {}
    try:
        import jax
        out["jax"] = str(getattr(jax, "__version__", "?"))
        import jaxlib
        out["jaxlib"] = str(getattr(jaxlib, "__version__", "?"))
    except Exception as e:      # unknown version still forms a valid key
        log.debug("runtime version probe failed: %s", e)
        out.setdefault("jax", "?")
    return out


def _device_identity() -> Dict[str, Any]:
    """Backend platform, device kind and count — a payload serialized for
    one topology must never load on another."""
    out: Dict[str, Any] = {}
    try:
        import jax
        devs = jax.devices()
        out["platform"] = str(devs[0].platform) if devs else "?"
        out["device_kind"] = str(devs[0].device_kind) if devs else "?"
        out["device_count"] = len(devs)
        try:
            out["platform_version"] = str(
                jax.extend.backend.get_backend().platform_version)
        except Exception as e:  # optional key refinement, not load-bearing
            log.debug("platform_version probe failed: %s", e)
    except Exception as e:      # no backend yet: '?' keys still partition safely
        log.debug("device identity probe failed: %s", e)
        out["platform"] = "?"
    return out


def build_key(fingerprint: str, lowered=None,
              extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Assemble the full cache key for one lowered program.

    ``fingerprint`` is the canonicalized-StableHLO sha256 (the content
    address), ``lowered`` contributes the donation layout, ``extra`` is the
    compile site's trigger key (endpoint/bucket/mesh/dtype) — anything the
    fingerprint might not capture about how the executable will be driven.
    """
    key: Dict[str, Any] = {"fingerprint": str(fingerprint)}
    key.update(_device_identity())
    key["versions"] = _runtime_versions()
    if lowered is not None:
        try:
            key["donate_argnums"] = sorted(
                int(i) for i in getattr(lowered, "donate_argnums", ()) or ())
        except Exception as e:  # unknown layout -> conservative empty slot
            log.debug("donation layout probe failed: %s", e)
            key["donate_argnums"] = []
    if extra:
        key["extra"] = {str(k): str(v) for k, v in sorted(extra.items())}
    return key


def key_digest(key: Dict[str, Any]) -> str:
    """sha256 over the canonical JSON of the key — the entry's file name."""
    canon = json.dumps(key, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()


def _paths(d: str, digest: str) -> Tuple[str, str]:
    return (os.path.join(d, f"ent-{digest}.bin"),
            os.path.join(d, f"ent-{digest}.json"))


# ---------------------------------------------------------------------------
# store / load
# ---------------------------------------------------------------------------

def _atomic_write(path: str, data: bytes):
    """tmp + fsync + rename in the destination directory: a reader sees the
    old entry, no entry, or the complete new one — never a torn write."""
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(prefix=".tmp-", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _total_bytes(d: str) -> int:
    total = 0
    try:
        for n in os.listdir(d):
            if n.startswith("ent-") and n.endswith(".bin"):
                try:
                    total += os.stat(os.path.join(d, n)).st_size
                except OSError:
                    pass
    except OSError:
        pass
    return total


def _drop_entry(d: str, digest: str):
    for p in _paths(d, digest):
        try:
            os.unlink(p)
        except OSError:
            pass


def _evict(d: str, budget: int) -> int:
    """Delete least-recently-used entries until the store fits ``budget``
    payload bytes; returns how many entries went."""
    if budget <= 0:
        return 0
    ents: List[Tuple[float, int, str]] = []   # (mtime, size, digest)
    try:
        for n in os.listdir(d):
            if not (n.startswith("ent-") and n.endswith(".bin")):
                continue
            try:
                st = os.stat(os.path.join(d, n))
            except OSError:
                continue
            ents.append((st.st_mtime, st.st_size, n[4:-4]))
    except OSError:
        return 0
    total = sum(sz for _, sz, _ in ents)
    if total <= budget:
        return 0
    evicted = 0
    for _, sz, digest in sorted(ents):
        if total <= budget:
            break
        _drop_entry(d, digest)
        total -= sz
        evicted += 1
    if evicted:
        _EVICTIONS.inc(evicted)
        with _LOCK:
            _STATS["evictions"] += evicted
    return evicted


def store(key: Dict[str, Any], compiled) -> bool:
    """Serialize ``compiled`` under ``key``. Best-effort: returns False (and
    stays silent beyond a debug log) on any failure — a full disk must not
    fail the compile that just succeeded."""
    d = cache_dir()
    if not d:
        return False
    try:
        from jax.experimental import serialize_executable as _jse
        payload, in_tree, out_tree = _jse.serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree),
                            protocol=pickle.HIGHEST_PROTOCOL)
        digest = key_digest(key)
        os.makedirs(d, exist_ok=True)
        bin_path, man_path = _paths(d, digest)
        _atomic_write(bin_path, blob)
        manifest = {"key": key, "payload_sha256":
                    hashlib.sha256(blob).hexdigest(),
                    "payload_bytes": len(blob), "created": time.time()}
        _atomic_write(man_path, (json.dumps(manifest, sort_keys=True)
                                 + "\n").encode("utf-8"))
        _evict(d, max_bytes())
        _BYTES.set(_total_bytes(d))
        with _LOCK:
            _STATS["stores"] += 1
        return True
    except Exception as e:
        log.debug("executable cache store failed: %s", e)
        return False


def _miss(reason: str) -> None:
    _MISSES.labels(reason).inc()
    with _LOCK:
        _STATS["misses"] += 1
    return None


def load(key: Dict[str, Any]):
    """Deserialize the executable stored under ``key``, or None (a miss).

    Verifies the manifest digest against the payload bytes before
    unpickling; corrupt or mismatched entries are deleted and answered
    with a miss plus a warning — the caller recompiles, clients never see
    an error. Never raises.
    """
    d = cache_dir()
    if not d:
        return None
    digest = key_digest(key)
    bin_path, man_path = _paths(d, digest)
    _consume_poison_fault(bin_path)
    try:
        try:
            with open(man_path, "rb") as f:
                manifest = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError):
            return _miss("absent")
        if manifest.get("key") != key:
            # a digest collision or a hand-edited manifest: refuse it
            return _miss("key_mismatch")
        try:
            with open(bin_path, "rb") as f:
                blob = f.read()
        except OSError:
            return _miss("absent")
        if hashlib.sha256(blob).hexdigest() != manifest.get("payload_sha256"):
            log.warning("executable cache entry %s corrupt (payload digest "
                        "mismatch); deleting and recompiling", digest[:12])
            _drop_entry(d, digest)
            _BYTES.set(_total_bytes(d))
            return _miss("corrupt")
        from jax.experimental import serialize_executable as _jse
        t0 = time.perf_counter()
        payload, in_tree, out_tree = pickle.loads(blob)
        compiled = _jse.deserialize_and_load(payload, in_tree, out_tree)
        dt = time.perf_counter() - t0
        _DESER_S.inc(dt)
        try:
            os.utime(bin_path)          # LRU touch
        except OSError:
            pass
        _HITS.inc()
        _BYTES.set(_total_bytes(d))
        with _LOCK:
            _STATS["hits"] += 1
            _STATS["deserialize_s"] += dt
        return compiled
    except Exception as e:
        # an undeserializable (stale-format, cross-runtime) payload is a
        # miss, not an error surface: drop it so the recompile re-stores
        log.warning("executable cache load of %s failed (%s); recompiling",
                    digest[:12], e)
        _drop_entry(d, digest)
        return _miss("error")


def _consume_poison_fault(bin_path: str):
    """Fault hook: a ``cache_poison`` injection at the ``exec_cache`` site
    is consumed here and converted into real on-disk corruption (payload
    truncated to half), so the genuine sha256-verify fallback path — not a
    simulated one — is what the chaos drill exercises."""
    try:
        from ..resilience import faults as _faults
    except Exception as e:      # no resilience layer -> no faults to consume
        log.debug("faults import failed: %s", e)
        return
    try:
        _faults.check("exec_cache")
    except Exception as e:
        if getattr(e, "kind", None) != "cache_poison":
            raise
        try:
            size = os.path.getsize(bin_path)
            with open(bin_path, "r+b") as f:
                f.truncate(max(size // 2, 1))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# introspection
# ---------------------------------------------------------------------------

def entries() -> List[Dict[str, Any]]:
    """Manifests of every entry currently in the store (oldest first)."""
    d = cache_dir()
    out: List[Dict[str, Any]] = []
    if not d or not os.path.isdir(d):
        return out
    for n in sorted(os.listdir(d)):
        if not (n.startswith("ent-") and n.endswith(".json")):
            continue
        try:
            with open(os.path.join(d, n), "rb") as f:
                man = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError):
            continue
        man["digest"] = n[4:-5]
        try:
            man["mtime"] = os.stat(
                os.path.join(d, f"ent-{man['digest']}.bin")).st_mtime
        except OSError:
            man["mtime"] = 0.0
        out.append(man)
    out.sort(key=lambda m: m["mtime"])
    return out


def stats() -> Dict[str, Any]:
    """Process-local cache activity plus the store's current size."""
    with _LOCK:
        snap = dict(_STATS)
    d = cache_dir()
    snap["enabled"] = bool(d)
    snap["dir"] = d
    snap["bytes"] = _total_bytes(d) if d else 0
    snap["deserialize_s"] = round(snap["deserialize_s"], 6)
    total = snap["hits"] + snap["misses"]
    snap["hit_rate"] = round(snap["hits"] / total, 4) if total else None
    return snap


def clear():
    """Delete every entry in the store (tests / operator reset)."""
    d = cache_dir()
    if not d or not os.path.isdir(d):
        return
    for n in os.listdir(d):
        if n.startswith("ent-") and (n.endswith(".bin")
                                     or n.endswith(".json")):
            try:
                os.unlink(os.path.join(d, n))
            except OSError:
                pass
    _BYTES.set(0)


def reset_stats():
    """Zero the process-local stat mirror (tests)."""
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0.0 if k == "deserialize_s" else 0
