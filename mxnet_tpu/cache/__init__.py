"""mxnet_tpu.cache — persistent, content-addressed compiled-artifact caches.

The first (and defining) member is :mod:`executable_cache`: serialized XLA
executables keyed by (StableHLO fingerprint, device topology, runtime
versions), stored on disk so a restarted or scaled-out replica starts
compile-free. See ROADMAP item 2 and the "Elastic fleet runbook" in
RESILIENCE.md.
"""
from __future__ import annotations

from . import executable_cache

__all__ = ["executable_cache"]
