"""mx.error (parity: python/mxnet/error.py): typed MXNetError subclasses with
a registration decorator mapping error-type prefixes in messages to classes."""
from __future__ import annotations

from .base import MXNetError

__all__ = ["MXNetError", "register", "InternalError"]

_ERROR_TYPES = {}


def register_error(func_name=None, cls=None):
    """Register an error class keyed by its name (base.py:92). Usable as a
    bare decorator or with an explicit name."""
    if callable(func_name):
        cls, func_name = func_name, None

    def deco(c):
        _ERROR_TYPES[func_name or c.__name__] = c
        return c
    return deco(cls) if cls is not None else deco


register = register_error


@register_error
class InternalError(MXNetError):
    """Internal invariant violation (error.py:31)."""

    def __init__(self, msg):
        if "InternalError:" not in msg:
            msg = f"InternalError: {msg}"
        super().__init__(msg)


def get_error_class(name):
    return _ERROR_TYPES.get(name, MXNetError)
