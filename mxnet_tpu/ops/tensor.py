"""Shape-manipulation, indexing, ordering and linalg operators.

Parity surface: src/operator/tensor/ (matrix_op.cc reshape/transpose/slice family,
indexing_op.cc take/gather_nd/scatter_nd/one_hot, ordering_op.cc topk/sort/argsort,
init_op.cc, dot-inl.h, la_op.cc) — all lowered to single XLA HLO ops on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------
@register("reshape", jit=True)
def reshape(x, *, shape, reverse=False):
    """Reshape with the reference's special codes 0 (copy dim), -1 (infer),
    -2 (copy rest), -3 (merge two), -4 (split) — matrix_op.cc Reshape."""
    shape = tuple(shape)
    if not any(s in (0, -2, -3, -4) for s in shape):
        return jnp.reshape(x, shape)
    src = list(x.shape)
    out = []
    i = 0  # index into src
    j = 0
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = shape[j + 1], shape[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(s); i += 1
        j += 1
    return jnp.reshape(x, tuple(out))


@register("transpose", jit=True)
def transpose(x, *, axes=None):
    return jnp.transpose(x, axes)


@register("swapaxes", jit=True)
def swapaxes(x, *, dim1=0, dim2=1):
    return jnp.swapaxes(x, dim1, dim2)


@register("flatten", jit=True)
def flatten(x):
    """Collapse all but the first axis (matrix_op.cc Flatten)."""
    return jnp.reshape(x, (x.shape[0], -1))


@register("expand_dims", jit=True)
def expand_dims(x, *, axis):
    return jnp.expand_dims(x, axis)


@register("squeeze", jit=True)
def squeeze(x, *, axis=None):
    return jnp.squeeze(x, axis=axis)


@register("broadcast_to", jit=True)
def broadcast_to(x, *, shape):
    shape = tuple(d if s == 0 else s for s, d in zip(shape, x.shape)) \
        if len(shape) == x.ndim else tuple(shape)
    return jnp.broadcast_to(x, shape)


@register("broadcast_axis", jit=True)
def broadcast_axis(x, *, axis, size):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    shape = list(x.shape)
    for a, s in zip(axes, sizes):
        shape[a] = s
    return jnp.broadcast_to(x, tuple(shape))


@register("concat", jit=True)
def concat(*arrays, dim=1):
    return jnp.concatenate(arrays, axis=dim)


@register("stack", jit=True)
def stack(*arrays, axis=0):
    return jnp.stack(arrays, axis=axis)


@register("split", jit=True)
def split(x, *, num_outputs, axis=1, squeeze_axis=False):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register("split_v2", jit=True)
def split_v2(x, *, indices_or_sections, axis=0, squeeze_axis=False):
    if isinstance(indices_or_sections, (list, tuple)):
        parts = jnp.split(x, list(indices_or_sections), axis=axis)
    else:
        parts = jnp.split(x, indices_or_sections, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("slice", jit=True)
def slice_op(x, *, begin, end, step=None):
    idx = []
    step = step or (None,) * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(slice(b, e, s))
    return x[tuple(idx)]


@register("slice_axis", jit=True)
def slice_axis(x, *, axis, begin, end):
    if end is None or end == 0 and begin > 0:
        end = x.shape[axis]
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like", jit=True)
def slice_like(x, shape_like, *, axes=None):
    axes = range(x.ndim) if not axes else axes
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return x[tuple(idx)]


@register("_getitem")
def _getitem(x, *, key):
    return x[key]


@register("reverse", jit=True)
def reverse(x, *, axis):
    return jnp.flip(x, axis=axis)


@register("tile", jit=True)
def tile(x, *, reps):
    return jnp.tile(x, reps)


@register("repeat", jit=True)
def repeat(x, *, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register("pad", jit=True)
def pad(x, *, mode="constant", pad_width=None, constant_value=0.0):
    """Pad (src/operator/pad.cc): pad_width is the flat 2*ndim tuple as in the
    reference; mode constant/edge/reflect."""
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(x.ndim)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pw, mode=jmode, constant_values=constant_value)
    return jnp.pad(x, pw, mode=jmode)


@register("depth_to_space", jit=True)
def depth_to_space(x, *, block_size):
    n, c, h, w = x.shape
    b = block_size
    y = x.reshape(n, b, b, c // (b * b), h, w)
    y = y.transpose(0, 3, 4, 1, 5, 2)
    return y.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth", jit=True)
def space_to_depth(x, *, block_size):
    n, c, h, w = x.shape
    b = block_size
    y = x.reshape(n, c, h // b, b, w // b, b)
    y = y.transpose(0, 3, 5, 1, 2, 4)
    return y.reshape(n, c * b * b, h // b, w // b)


@register("diag", jit=True)
def diag(x, *, k=0):
    return jnp.diag(x, k=k) if x.ndim <= 2 else jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)


@register("shape_array", differentiable=False, jit=True)
def shape_array(x):
    return jnp.asarray(x.shape, dtype=jnp.int64 if False else jnp.int32)


@register("size_array", differentiable=False, jit=True)
def size_array(x):
    import numpy as onp
    return jnp.asarray([int(onp.prod(x.shape))], dtype=jnp.int32)


@register("where", jit=True)
def where(cond, a, b):
    return jnp.where(cond.astype(bool) if cond.dtype != jnp.bool_ else cond, a, b)


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------
@register("take", jit=True)
def take(x, indices, *, axis=0, mode="clip"):
    """Gather along axis (indexing_op.cc Take); modes clip/wrap like the reference."""
    idx = indices.astype(jnp.int32)
    return jnp.take(x, idx, axis=axis, mode=mode)


@register("batch_take", jit=True)
def batch_take(x, indices):
    idx = indices.astype(jnp.int32)
    return jnp.take_along_axis(x, idx[:, None], axis=1)[:, 0]


@register("pick", jit=True)
def pick(x, indices, *, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.expand_dims(indices.astype(jnp.int32), axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return out if keepdims else jnp.squeeze(out, axis=axis)


@register("gather_nd", jit=True)
def gather_nd(x, indices):
    """gather_nd (indexing_op.cc): indices shape (M, ...) indexes first M dims."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return x[tuple(idx[i] for i in range(m))]


@register("scatter_nd", jit=True)
def scatter_nd(data, indices, *, shape):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register("_scatter_set_nd")
def _scatter_set_nd(lhs, data, indices, *, shape=None):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return lhs.at[tuple(idx[i] for i in range(m))].set(data)


@register("index_add", jit=True)
def index_add(lhs, data, indices):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return lhs.at[tuple(idx[i] for i in range(m))].add(data)


@register("index_copy", jit=True)
def index_copy(old, idx, new):
    return old.at[idx.astype(jnp.int32)].set(new)


@register("one_hot", differentiable=False, jit=True)
def one_hot(indices, *, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..base import DTypes
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=DTypes.jnp(dtype))
    return oh * (on_value - off_value) + off_value


@register("boolean_mask_dense")
def boolean_mask_dense(data, mask, *, axis=0):
    """Dense analog of boolean_mask (contrib): zero out unmasked rows. The
    shape-dynamic true boolean_mask lives in the numpy frontend (host fallback)."""
    m = mask.astype(data.dtype)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    return data * m.reshape(shape)


@register("sequence_mask", jit=True)
def sequence_mask(data, sequence_length=None, *, use_sequence_length=False, value=0.0,
                  axis=0):
    """SequenceMask (src/operator/sequence_mask.cc): data is (seq, batch, ...) when
    axis=0 or (batch, seq, ...) when axis=1."""
    if not use_sequence_length or sequence_length is None:
        return data
    seq_axis, batch_axis = (0, 1) if axis == 0 else (1, 0)
    seq_len = data.shape[seq_axis]
    pos = jnp.arange(seq_len)
    shape = [1] * data.ndim
    shape[seq_axis] = seq_len
    pos = pos.reshape(shape)
    sl_shape = [1] * data.ndim
    sl_shape[batch_axis] = data.shape[batch_axis]
    sl = sequence_length.astype(jnp.int32).reshape(sl_shape)
    return jnp.where(pos < sl, data, jnp.asarray(value, data.dtype))


@register("sequence_last", jit=True)
def sequence_last(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    seq_axis = axis
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, data.shape[seq_axis] - 1, axis=seq_axis)
    idx = (sequence_length.astype(jnp.int32) - 1)
    dmoved = jnp.moveaxis(data, seq_axis, 0)  # (seq, batch, ...)
    return jnp.take_along_axis(
        dmoved, idx.reshape((1, -1) + (1,) * (dmoved.ndim - 2)), axis=0)[0]


@register("sequence_reverse", jit=True)
def sequence_reverse(data, sequence_length=None, *, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    d = jnp.moveaxis(data, axis, 0)
    seq_len = d.shape[0]
    pos = jnp.arange(seq_len)[:, None]
    sl = sequence_length.astype(jnp.int32)[None, :]
    rev_idx = jnp.where(pos < sl, sl - 1 - pos, pos)
    out = jnp.take_along_axis(d, rev_idx.reshape(rev_idx.shape + (1,) * (d.ndim - 2)),
                              axis=0)
    return jnp.moveaxis(out, 0, axis)


# ---------------------------------------------------------------------------
# ordering (reference: ordering_op.cc via CUB; here XLA sort)
# ---------------------------------------------------------------------------
@register("sort", jit=True)
def sort(x, *, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort", differentiable=False, jit=True)
def argsort(x, *, axis=-1, is_ascend=True, dtype="float32"):
    from ..base import DTypes
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(DTypes.jnp(dtype))


@register("topk", differentiable=False, jit=True)
def topk(x, *, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    from ..base import DTypes
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = lax.top_k(-xm if is_ascend else xm, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(DTypes.jnp(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idx
    if ret_typ == "both":
        return vals, idx
    if ret_typ == "mask":
        mask = jnp.zeros(xm.shape, x.dtype)
        mask = mask.at[..., :].set(0)
        oh = jax.nn.one_hot(jnp.moveaxis(idx, axis, -1).astype(jnp.int32),
                            xm.shape[-1], dtype=x.dtype).sum(-2)
        return jnp.moveaxis(oh, -1, axis)
    raise ValueError(ret_typ)


@register("unique", differentiable=False)
def unique(x):
    return jnp.unique(x, size=x.size, fill_value=x.reshape(-1)[-1])


# ---------------------------------------------------------------------------
# init / ranges
# ---------------------------------------------------------------------------
@register("arange_like", differentiable=False, jit=True)
def arange_like(x, *, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = int(jnp.size(x)) if not hasattr(x, "shape") else int(
            jnp.prod(jnp.asarray(x.shape)))
        import numpy as onp
        n = int(onp.prod(x.shape))
        out = start + step * jnp.arange(n, dtype=x.dtype)
        return out.reshape(x.shape)
    n = x.shape[axis]
    return start + step * jnp.arange(n, dtype=x.dtype)


# ---------------------------------------------------------------------------
# linalg (reference: tensor/dot-inl.h, la_op.cc via LAPACK → XLA linalg)
# ---------------------------------------------------------------------------
@register("einsum", jit=True)
def einsum(*operands, subscripts):
    """einsum (numpy/np_einsum_op.cc): contraction by equation; lowers to XLA
    dot_general chains so multi-operand contractions ride the MXU."""
    return jnp.einsum(subscripts, *operands)


@register("dot", jit=True)
def dot(a, b, *, transpose_a=False, transpose_b=False):
    """dot (tensor/dot-inl.h): 2-D matmul contract last/first axes; MXU-native."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot", jit=True)
def batch_dot(a, b, *, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register("matmul", jit=True)
def matmul(a, b):
    return jnp.matmul(a, b)


@register("khatri_rao", jit=True)
def khatri_rao(*arrays):
    out = arrays[0]
    for a in arrays[1:]:
        out = (out[:, None, :] * a[None, :, :]).reshape(-1, out.shape[-1])
    return out


@register("linalg_gemm2", jit=True)
def linalg_gemm2(a, b, *, transpose_a=False, transpose_b=False, alpha=1.0):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register("linalg_gemm", jit=True)
def linalg_gemm(a, b, c, *, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b) + beta * c


@register("linalg_potrf", jit=True)
def linalg_potrf(a):
    return jnp.linalg.cholesky(a)


@register("linalg_trsm", jit=True)
def linalg_trsm(a, b, *, transpose=False, rightside=False, lower=True, alpha=1.0):
    import jax.scipy.linalg as jsl
    if rightside:
        # solve X A = alpha B  =>  A^T X^T = alpha B^T
        xt = jsl.solve_triangular(jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * b, -1, -2),
                                  lower=not lower if transpose else not lower,
                                  trans=0)
        return jnp.swapaxes(xt, -1, -2)
    return jsl.solve_triangular(a, alpha * b, lower=lower, trans=1 if transpose else 0)


@register("linalg_trmm", jit=True)
def linalg_trmm(a, b, *, transpose=False, rightside=False, lower=True, alpha=1.0):
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    return alpha * (jnp.matmul(b, tri) if rightside else jnp.matmul(tri, b))


@register("linalg_syrk", jit=True)
def linalg_syrk(a, *, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register("linalg_sumlogdiag", jit=True)
def linalg_sumlogdiag(a):
    return jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_extractdiag", jit=True)
def linalg_extractdiag(a, *, offset=0):
    return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)


@register("linalg_makediag", jit=True)
def linalg_makediag(a, *, offset=0):
    return jax.vmap(jnp.diag, in_axes=0)(a.reshape(-1, a.shape[-1])).reshape(
        a.shape[:-1] + (a.shape[-1] + abs(offset),) * 2) if a.ndim > 1 else jnp.diag(a, k=offset)


@register("linalg_svd", jit=True)
def linalg_svd(a):
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    return u, s, vt


@register("linalg_inverse", jit=True)
def linalg_inverse(a):
    return jnp.linalg.inv(a)


@register("linalg_det", jit=True)
def linalg_det(a):
    return jnp.linalg.det(a)


@register("linalg_slogdet", jit=True)
def linalg_slogdet(a):
    sign, logdet = jnp.linalg.slogdet(a)
    return sign, logdet


@register("linalg_potri", jit=True)
def linalg_potri(a):
    """Inverse of the SPD matrix whose Cholesky factor is ``a`` (la_op.cc
    potri): (a a^T)^-1 via two triangular solves — one MXU-friendly
    batched trsm pair instead of an explicit inverse."""
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    linv = jax.scipy.linalg.solve_triangular(a, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("linalg_syevd", jit=True)
def linalg_syevd(a):
    """Symmetric eigendecomposition (la_op.cc syevd): returns (U, L) with
    rows of U the eigenvectors (reference layout: a = U^T diag(L) U)."""
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w


@register("linalg_gelqf", jit=True)
def linalg_gelqf(a):
    """LQ factorization of a full-rank wide matrix (la_op.cc gelqf):
    a = L Q with Q orthonormal rows — the QR of a^T transposed."""
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("linalg_extracttrian", jit=True)
def linalg_extracttrian(a, *, offset=0, lower=True):
    """Pack the triangular part of each matrix into a vector (la_op.cc
    ExtractTrian): row-major walk over the kept triangle."""
    n = a.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower else \
        jnp.triu_indices(n, k=offset)
    return a[..., rows, cols]


@register("linalg_maketrian")
def linalg_maketrian(a, *, offset=0, lower=True):
    """Unpack a vector into a triangular matrix (la_op.cc MakeTrian),
    inverse of linalg_extracttrian for the same offset/lower."""
    m = a.shape[-1]
    # m packs the triangle of side (n - |offset|): T(s) = s(s+1)/2 = m
    s = int(round((-1 + (1 + 8 * m) ** 0.5) / 2))
    n = s + abs(offset)
    rows, cols = jnp.tril_indices(n, k=offset) if lower else \
        jnp.triu_indices(n, k=offset)
    out = jnp.zeros(a.shape[:-1] + (n, n), dtype=a.dtype)
    return out.at[..., rows, cols].set(a)


# ---------------------------------------------------------------------------
# misc tensor ops (matrix_op.cc / histogram.cc / ravel.cc / im2col.h)
# ---------------------------------------------------------------------------
@register("histogram", differentiable=False)
def histogram(data, bins=None, *, bin_cnt=None, range=None):
    """Histogram counts (histogram.cc): either explicit bin edges or
    (bin_cnt, range). Counts are integer like the reference's int64 output
    (int32 here — the widest integer with jax x64 disabled)."""
    if bins is not None:
        counts, edges = jnp.histogram(data, bins=bins)
    else:
        counts, edges = jnp.histogram(data, bins=int(bin_cnt), range=range)
    return counts.astype(jnp.int32), edges


@register("broadcast_like")
def broadcast_like(lhs, rhs, *, lhs_axes=None, rhs_axes=None):
    """Broadcast lhs to rhs's shape (matrix_op.cc BroadcastLike); with axes
    given, only those axes take rhs's extent."""
    if lhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    target = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        target[la % lhs.ndim] = rhs.shape[ra % rhs.ndim]
    return jnp.broadcast_to(lhs, tuple(target))


@register("reshape_like")
def reshape_like(lhs, rhs, *, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape lhs to rhs's shape over the selected axis windows
    (matrix_op.cc ReshapeLike)."""
    if lhs_begin is None and rhs_begin is None:
        return jnp.reshape(lhs, rhs.shape)
    lb = 0 if lhs_begin is None else lhs_begin % (lhs.ndim + 1)
    le = lhs.ndim if lhs_end is None else lhs_end % (lhs.ndim + 1)
    rb = 0 if rhs_begin is None else rhs_begin % (rhs.ndim + 1)
    re_ = rhs.ndim if rhs_end is None else rhs_end % (rhs.ndim + 1)
    target = lhs.shape[:lb] + rhs.shape[rb:re_] + lhs.shape[le:]
    return jnp.reshape(lhs, target)


@register("ravel_multi_index", differentiable=False)
def ravel_multi_index(data, *, shape):
    """(ndim, N) coordinates -> flat indices (ravel.cc)."""
    coords = [data[i].astype(jnp.int32) for i in range(len(shape))]
    strides = []
    acc = 1
    for s in reversed(shape):
        strides.append(acc)
        acc *= s
    strides = list(reversed(strides))
    flat = sum(c * st for c, st in zip(coords, strides))
    return flat.astype(data.dtype)


@register("unravel_index", differentiable=False)
def unravel_index(data, *, shape):
    """Flat indices -> (ndim, N) coordinates (ravel.cc UnravelIndex)."""
    coords = jnp.unravel_index(data.astype(jnp.int32), shape)
    return jnp.stack([c.astype(data.dtype) for c in coords], axis=0)


def _slice_tuple(shape, begin, end, step=None):
    step = step if step else (None,) * len(begin)
    idx = []
    for i, (b, e) in enumerate(zip(begin, end)):
        s = step[i] if i < len(step) else None
        idx.append(slice(b, e, s))
    return tuple(idx)


@register("slice_assign")
def slice_assign(lhs, rhs, *, begin, end, step=None):
    """Write rhs into lhs[begin:end:step] (matrix_op.cc _slice_assign) —
    functional: returns the updated array (XLA scatter)."""
    return lhs.at[_slice_tuple(lhs.shape, begin, end, step)].set(rhs)


@register("slice_assign_scalar")
def slice_assign_scalar(lhs, *, scalar, begin, end, step=None):
    return lhs.at[_slice_tuple(lhs.shape, begin, end, step)].set(scalar)


@register("im2col")
def im2col(data, *, kernel, stride=None, dilate=None, pad=None):
    """Sliding-window patch extraction (im2col.h): NCHW input ->
    (N, C*prod(kernel), L) patch matrix. One XLA patch-gather, the matmul
    side of convolution-as-GEMM."""
    kh, kw = kernel
    sh, sw = stride or (1, 1)
    dh, dw = dilate or (1, 1)
    ph, pw = pad or (0, 0)
    patches = lax.conv_general_dilated_patches(
        data, filter_shape=(kh, kw), window_strides=(sh, sw),
        padding=((ph, ph), (pw, pw)), rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n = data.shape[0]
    return patches.reshape(n, patches.shape[1], -1)


@register("col2im")
def col2im(data, *, output_size, kernel, stride=None, dilate=None, pad=None):
    """Scatter-accumulate patches back to an image (im2col.h col2im): the
    exact adjoint of im2col, taken as its XLA-transposed VJP."""
    oh, ow = output_size
    c = data.shape[1] // (kernel[0] * kernel[1])
    ref = jnp.zeros((data.shape[0], c, oh, ow), data.dtype)
    _, vjp = jax.vjp(
        lambda x: im2col(x, kernel=kernel, stride=stride, dilate=dilate,
                         pad=pad), ref)
    return vjp(data)[0]


@register("BlockGrad")
def BlockGrad(x):
    """Identity forward, zero gradient (tensor/elemwise_unary_op_basic.cc
    BlockGrad / stop_gradient)."""
    return lax.stop_gradient(x)


@register("take_along_axis")
def take_along_axis(x, indices, *, axis=0):
    """np.take_along_axis as a registered op so both frontends (and the
    symbolic tracer) can batched-gather — e.g. the BERT masked-position
    gather (arr[b, idx[b, p], ...])."""
    return jnp.take_along_axis(x, indices.astype(jnp.int32), axis=axis)
